"""Stall detector — the practical liveness sanitizer.

Parity with reference ``srcs/go/utils/stalldetector.go:9-46``: wrap any
blocking operation; a watchdog thread prints ``"<op> stalled for <t>"``
every ``period`` seconds until the operation finishes, then ``recovered``.
Enabled by ``KF_CONFIG_ENABLE_STALL_DETECTION``.
"""

from __future__ import annotations

import contextlib
import threading
import time

from kungfu_tpu.utils.envs import ENABLE_STALL_DETECTION, parse_bool_env
from kungfu_tpu.utils.log import get_logger

_log = get_logger("stall")
DEFAULT_PERIOD_S = 3.0


@contextlib.contextmanager
def stall_detector(name: str, period: float = DEFAULT_PERIOD_S, force: bool = False):
    if not (force or parse_bool_env(ENABLE_STALL_DETECTION)):
        yield
        return
    done = threading.Event()
    t0 = time.time()
    stalled = [False]

    def watch():
        while not done.wait(period):
            stalled[0] = True
            _log.warning("%s stalled for %.1fs", name, time.time() - t0)

    th = threading.Thread(target=watch, daemon=True)
    th.start()
    try:
        yield
    finally:
        done.set()
        th.join(timeout=1)
        if stalled[0]:
            _log.warning("%s recovered after %.1fs", name, time.time() - t0)
