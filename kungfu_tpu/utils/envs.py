"""The worker bootstrap env-var contract.

Parity with reference ``srcs/go/kungfu/env/envs.go:4-18`` and
``kungfu/config/config.go``: the launcher communicates everything a worker
needs through ``KF_*`` environment variables; unset envs fall back to
single-process mode (reference ``env/config.go:24-80``).

Bootstrap envs (written by the runner, read once at init):

==========================  ====================================================
``KF_SELF_SPEC``            this worker's ``host:port``
``KF_INIT_PEERS``           comma-separated worker list
``KF_INIT_RUNNERS``         comma-separated runner list
``KF_PARENT_ID``            runner that spawned us (``host:port``)
``KF_INIT_CLUSTER_VERSION`` integer mesh-epoch at spawn time
``KF_ALLREDUCE_STRATEGY``   host-engine strategy name (see plan.strategy)
``KF_DEVICE_STRATEGY``      device allreduce schedule (ops.schedules)
``KF_CONFIG_SERVER``        URL of the elastic config server
``KF_JOB_START_TIMESTAMP``  unix seconds the job started (event timeline)
``KF_PROC_START_TIMESTAMP`` unix seconds this process started
``KF_NUM_DEVICES``          virtual device count for CPU-backend clusters
``KF_COORDINATOR``          jax.distributed coordinator address
``KF_NUM_PROCESSES``        jax.distributed process count
``KF_PROCESS_ID``           jax.distributed process index
``KF_WORLD_PEERS``          full provisioned worker-slot list (max world).
                            When set, the jax.distributed world is booted
                            ONCE over ALL slots and elastic resize re-carves
                            the device mesh over the *active* subset — no
                            world re-init, surviving workers keep training
                            (reference live-resize semantics,
                            ``peer/peer.go:236-276``)
==========================  ====================================================

Tuning envs (read anywhere, any time):

=================================  ============================================
``KF_CONFIG_ENABLE_MONITORING``    "true"/"false"
``KF_CONFIG_MONITORING_PERIOD``    seconds, default 1
``KF_CONFIG_ENABLE_STALL_DETECTION`` "true"/"false"
``KF_CONFIG_LOG_LEVEL``            DEBUG/INFO/WARN/ERROR
``KF_CONFIG_STRATEGY_HASH_METHOD`` chunk→strategy hash: "simple"|"name"
``KF_CONFIG_WAIT_RUNNER_TIMEOUT``  s to wait for a runner before a resize
                                   notification is dropped, default 10
``KF_CONFIG_CHUNK_SIZE``           engine chunk bytes; default 1 MiB,
                                   or 256 KiB when all peers share one
                                   host (measured, engine.py).  Must be
                                   identical cluster-wide (set at the
                                   launcher; it propagates to workers)
``KF_CONFIG_ENGINE_THREADS``       native executor threads, default
                                   min(8, cores)
``KF_CONFIG_ENGINE_TIMEOUT``       per-collective timeout s, default 60
``KF_CONFIG_PEER_DEADLINE``        per-peer send/recv deadline s for one
                                   engine collective primitive; on
                                   exhaustion a typed PeerFailureError
                                   (suspect rank attached) replaces the
                                   hang/raw error — the entry point of
                                   shrink-to-survivors recovery.  Default
                                   = the engine timeout (comm/engine.py)
``KF_CONFIG_ENABLE_TRACE``         truthy: log scope entry depth +
                                   duration (utils/trace.py) AND record
                                   flight-recorder timeline events
                                   (monitor/timeline.py)
``KF_CONFIG_TRACE_DUMP``           timeline JSONL dump target: a
                                   directory (one trace-*.jsonl per
                                   process) or an exact *.jsonl path;
                                   written on Peer.close/exit and merged
                                   by scripts/kftrace
                                   (monitor/timeline.py)
``KF_CONFIG_TIMELINE_CAP``         flight-recorder ring capacity in
                                   events, default 65536; evictions are
                                   counted in kf_timeline_dropped_total
                                   (monitor/timeline.py)
``KF_CONFIG_ENABLE_CLUSTER_MONITOR`` truthy: each worker pushes live
                                   snapshots (step, counters, latency
                                   deltas, recent collective spans) to
                                   the cluster aggregator co-hosted with
                                   the config server; view with
                                   ``kftop`` (monitor/aggregator.py)
``KF_CONFIG_MONITOR_PUSH_PERIOD``  snapshot push interval seconds,
                                   default 1 (monitor/aggregator.py)
``KF_CONFIG_MONITOR_STALE_AFTER``  seconds without a snapshot before the
                                   aggregator flags a rank *stale*;
                                   default 3x the push period — well
                                   inside the failure detector's 10 s
                                   down verdict (monitor/aggregator.py)
``KF_CONFIG_P2P_RESPONDERS``       p2p blob responder pool size override;
                                   default scales with peer count via
                                   host_pool_size (store/p2p.py)
``KF_CONFIG_OVERLAP_DEPTH``        bound on in-flight async collective
                                   handles per engine (the kf-overlap
                                   window), default 2; issuing past it
                                   blocks until one completes.  Local
                                   backpressure only — tags and issue
                                   order are unchanged, so peers may
                                   legally run different depths
                                   (comm/engine.py; learnable via
                                   policy.bandit.OverlapDepthBandit)
``KF_CONFIG_HOST_POOL_MAX``        cap on the load-scaled host-plane
                                   responder/sender pools, default 16
                                   (wins over per-pool floors); current
                                   sizes exported as the
                                   kf_host_pool_size{pool=...} gauge
                                   (comm/host.py)
``KF_CONFIG_USE_AFFINITY``         truthy: partition host cores between
                                   colocated workers (utils/affinity.py)
``KF_CONFIG_WATCH_GRACE``          runner natural-end grace window s,
                                   default 10 (runner/watch.py)
``KF_XRAY_WINDOW_STEPS``           steps in the online kf-xray
                                   attribution window the aggregator
                                   serves under /cluster -> xray,
                                   default 32 (monitor/xray.py)
``KF_XRAY_PEAK_FLOPS``             per-chip peak FLOP/s pinned for the
                                   kf_mfu gauge, overriding TPU
                                   device-kind detection; unset on CPU
                                   meshes = no MFU, model-FLOPs rate
                                   only (ops/costmodel.py)
``KF_PP_STAGES``                   pipeline stages (the cross-DCN pp
                                   axis degree), default 1;
                                   ParallelPlan.from_env reads it so
                                   entrypoints stop hand-wiring the
                                   axis combination (parallel/train.py)
``KF_PP_MICROBATCHES``             pipeline microbatches per step, 0 =
                                   the stage count (the minimum that
                                   fills the pipe); parallel/train.py
``KF_PP_SCHEDULE``                 pipeline microbatch schedule: 1f1b
                                   (default) | interleaved |
                                   sequential (the naive baseline the
                                   bench gate measures against);
                                   parallel/train.py -> parallel/pp.py
=================================  ============================================

Transport / native-runtime envs:

=============================  ================================================
``KF_TPU_HOST_TRANSPORT``      host channel backend: "auto"|"native"|"python"
                               (comm/host.py)
``KF_TPU_USE_UNIXSOCK``        "0" disables the colocated-peer unix sockets;
                               default on (comm/host.py)
``KF_SOCK_DIR``                unix sockfile directory override; default
                               /tmp/kf-tpu-<uid> (comm/host.py AND
                               native/transport.cpp — keep in lockstep)
``KF_TPU_NO_NATIVE``           "1" skips the native .so entirely (numpy +
                               python-transport fallbacks, native/__init__.py)
``KF_NATIVE_ENGINE``           "0"/"false"/"no" disables the fully-native
                               collective executor; default on (comm/engine.py)
``KF_NATIVE_MARCH``            build the native .so with -march=<value>
                               (homogeneous clusters only; native/__init__.py)
``KF_NATIVE_SANITIZE``         "tsan"|"asan": load the sanitizer-instrumented
                               native build variant (libkfnative-<v>.so) for
                               race/memory debugging (native/__init__.py)
``KF_MONITOR_ADDR``            failure-detector endpoint workers report to
                               (monitor/signals.py; set by the runner)
=============================  ================================================

Multislice (TPU pod) envs — the ``MEGASCALE_*`` names are the TPU
runtime's contract, read by :mod:`kungfu_tpu.platforms.tpu_pod` and the
slice topology layer (:mod:`kungfu_tpu.elastic.slices`):

=================================  ============================================
``MEGASCALE_COORDINATOR_ADDRESS``  multislice DCN coordinator (slice 0 host 0)
``MEGASCALE_SLICE_ID``             this host's slice index; in the CPU-mesh
                                   emulation contract the launcher sets it
                                   per worker (= worker rank // ranks/slice)
``MEGASCALE_NUM_SLICES``           total slice count; >1 switches the peer to
                                   the hierarchical ICI-within / DCN-across
                                   communicator and slice-granular elasticity
``KF_SLICE_RANKS``                 worker ranks per slice, pinned by the
                                   launcher (``kfrun -num-slices``); without
                                   it the topology derives ranks/slice from
                                   the bootstrap worker count
=================================  ============================================

Serving envs (the kf-serve inference plane, :mod:`kungfu_tpu.serve`;
see docs/serving.md):

=============================  ================================================
``KF_SERVE_QUEUE_DEPTH``       router admission bound: accepted-but-unfinished
                               requests past it are rejected with the typed
                               ``ServeOverloadError`` instead of queueing
                               unboundedly; default 64 (serve/router.py)
``KF_SERVE_PAGE_TOKENS``       tokens per KV-cache page, default 16
                               (serve/kvcache.py)
``KF_SERVE_KV_PAGES``          KV-cache pool capacity in pages, default 512;
                               the per-rank footprint is the
                               ``kf_kv_cache_bytes`` gauge (serve/kvcache.py)
``KF_SERVE_MAX_BATCH``         decode batch width (continuous-batching slots)
                               per engine, default 8; the policy layer's
                               BatchWidthController moves the *admitted* width
                               under this cap (serve/engine.py)
``KF_SERVE_MAX_TOKENS``        per-request new-token cap, default 256
                               (serve/engine.py)
``KF_SERVE_COMMIT_EVERY``      decode positions between progress commits to
                               the router (the replay boundary after a worker
                               death), default 8 (serve/router.py)
``KF_SERVE_REQUEST_DEADLINE``  router per-request progress deadline seconds
                               (no progress/completion within it = a strike
                               against the worker; strikes escalate to the
                               dead-worker ladder), default 60
                               (serve/router.py)
``KF_SERVE_SLO_TTFT_MS``       time-to-first-token SLO target ms, default 500
                               (serve/slo.py)
``KF_SERVE_SLO_E2E_MS``        end-to-end request SLO target ms, default 5000
                               (serve/slo.py)
=============================  ================================================

Persistence envs (the durable state plane,
:mod:`kungfu_tpu.elastic.persist`; see docs/persistence.md):

=============================  ================================================
``KF_PERSIST_DIR``             manifest root for durable checkpoints; unset =
                               the persist plane is off (``kfrun
                               -persist-dir`` / ``-restore-from`` set it)
``KF_PERSIST_PERIOD``          seconds between issued persists, default 30.0;
                               0 = persist at every commit (demos/tests)
``KF_PERSIST_ASYNC_DEPTH``     max in-flight async persist handles before
                               issue blocks on the oldest, default 2
``KF_PERSIST_KEEP``            keep-last-k complete manifests retained by
                               rank-0 GC (min 1), default 3
``KF_PERSIST_RESTORE``         truthy = restore-armed start: the worker
                               agrees on and restores the newest complete
                               manifest before training (set by ``kfrun
                               -restore-from``)
=============================  ================================================

Sentinel envs (the kf-sentinel judging plane,
:mod:`kungfu_tpu.monitor.sentinel`; see docs/sentinel.md — the sentinel
and kfhist read these tokens from ``os.environ`` directly via mirror
constants, like timeline.py's CAP_ENV, so the stubbed kfhist/CI context
never imports this jax-adjacent module; :func:`sentinel_knobs` below
pins the defaults both sides must agree on):

=============================  ================================================
``KF_SENTINEL_DIR``            durable metrics-history root; unset = the
                               whole sentinel plane is off and aggregator
                               behavior is byte-identical (``kfrun
                               -sentinel`` sets it)
``KF_SENTINEL_KEEP_BYTES``     per-stream history ring byte budget,
                               default 8 MiB; oldest sealed segments are
                               GC'd past it (monitor/history.py)
``KF_SENTINEL_PERIOD``         seconds between sentinel samples, default
                               1.0; <= 0 samples on every aggregator
                               ingest (tests)
``KF_SENTINEL_WINDOW``         changepoint window in samples, default 8
                               (monitor/detect.py)
``KF_SENTINEL_THRESHOLD``      median-shift score (MAD multiples) before
                               a series alerts, default 4.0
``KF_SENTINEL_MFU_FLOOR``      MFU watermark: alert when the cluster MFU
                               mean sinks below it; default 0 = off
``KF_SENTINEL_STEP_CEILING_S`` step-time watermark seconds; default 0 =
                               off
``KF_SENTINEL_WARMUP_STEPS``   steps considered warmup, default 32; XLA
                               recompiles AFTER it raise the
                               recompile-steady alert
``KF_SENTINEL_INCIDENT_WINDOW`` history records embedded in an incident
                               flight record, default 64
``KF_SENTINEL_SLO_SHORT``      SLO burn-rate short window in samples,
                               default 6 (serve/slo.py SLORules)
``KF_SENTINEL_SLO_LONG``       SLO burn-rate long window in samples,
                               default 24 (serve/slo.py SLORules)
=============================  ================================================

Pulse envs (kf-pulse gradient-signal monitoring,
:mod:`kungfu_tpu.monitor.pulse`; see docs/pulse.md — the pulse module
reads these via mirror constants, same stdlib-only doctrine as the
sentinel; :func:`pulse_knobs` pins the shared defaults):

=============================  ================================================
``KF_PULSE_EVERY``             sample the gradient-noise-scale /
                               variance pair every N training steps,
                               default 10; <= 0 disables the pulse
                               plane (``PulseMonitor.from_env`` returns
                               None and the step is byte-identical)
``KF_PULSE_EMA``               EMA weight for smoothing the per-sample
                               GNS/variance estimates, default 0.2
=============================  ================================================

Fault-injection envs (the chaos layer, :mod:`kungfu_tpu.chaos`; see
docs/fault_tolerance.md for the full matrix):

=============================  ================================================
``KF_CHAOS_SPEC``              deterministic fault clauses
                               (``die``/``die_slice``/``reset``/``delay``/
                               ``drop_fanout``/``drop_request``/
                               ``config_down``; grammar in chaos/spec.py).
                               Unset = every injection hook is a zero-cost
                               no-op and behavior is byte-identical to an
                               injection-free build
``KF_CHAOS_SEED``              integer seed for the only randomized
                               perturbation (delay jitter), default 0
=============================  ================================================

Protocol-verifier envs (the kf-verify static SPMD checker,
:mod:`kungfu_tpu.analysis.protoverify`; see docs/lint.md):

=============================  ================================================
``KF_VERIFY_MAX_RANKS``        largest world size the geometry sweep
                               enumerates ParallelPlans for, default 16
``KF_VERIFY_GEOMETRY_CAP``     hard cap on geometries simulated per family
                               (0 = unlimited), default 0
``KF_VERIFY_TIMEOUT_S``        wall-clock budget for the whole geometry
                               sweep in seconds, default 60.0; on expiry
                               the sweep reports how many geometries it
                               covered instead of silently truncating
=============================  ================================================

Kernel / model / data selection envs:

=============================  ================================================
``KF_JAX_PLATFORM``            jax platform for workers ("cpu"|"tpu"|...);
                               runner sets "cpu" for local clusters (peer.py)
``KF_DATA_DIR``                dataset cache root, default ~/.cache/kungfu_tpu
                               (datasets/cache.py)
``KF_TPU_CKPT_BACKEND``        checkpoint backend: "auto"|"orbax"|"npz"
                               (checkpoint.py)
``KF_TPU_ATTN``                attention impl: "auto"|"flash"|"plain"
                               (models/transformer.py)
``KF_TPU_LM_HEAD``             lm-head impl: "auto"|"fused"|"plain"
                               (models/transformer.py)
``KF_TPU_XENT``                cross-entropy impl: "auto"|"fused"|"plain"|
                               "xla" (ops/pallas/xent.py)
``KF_TPU_BN_COMPUTE``          "f32" restores legacy f32 batch-norm compute
                               (models/nn.py)
``KF_PALLAS_BWD``              "pallas" forces the pallas backward kernels
                               even under interpret mode (ops/pallas)
``KF_PALLAS_COLLECTIVES``      ring-collective impl: "auto"|"pallas"|"lax"
                               (ops/pallas/collectives.py; launch-set,
                               read at import)
``KF_XENT_FWD_MIN_ELEMENTS``   min logits elements before the fused xent
                               forward engages (ops/pallas/xent.py)
``KF_XENT_XLA_BUDGET_MB``      logits-bytes budget under which plain XLA
                               xent is preferred (ops/pallas/xent.py)
=============================  ================================================

Not an env var (registered so the ``KF_*`` contract scan covers C++):
``KF_SIMD_CLONES`` is a compile-time macro in native/reduce.cpp selecting
per-ISA function cloning.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Optional

from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.plan.peer import PeerID, parse_peer_id
from kungfu_tpu.plan.peerlist import PeerList
from kungfu_tpu.plan.strategy import Strategy, parse_strategy

#: launch-set knob objects (import-time env reads with an explicit
#: ``reload()`` — the recompile-hazard hoist pattern of ops/pallas):
#: every instance registers here so tooling that mutates the
#: environment (tests above all) can re-read ALL of them without
#: enumerating modules by hand
LAUNCH_KNOBS: list = []


def register_launch_knobs(knobs):
    """Track a reload()-able launch-knob object; returns it."""
    LAUNCH_KNOBS.append(knobs)
    return knobs


class LaunchKnobs:
    """Base for a set of launch-set env knobs: subclasses implement
    ``_read(self)`` — read ``os.environ``, validate loudly (ValueError
    on a typo beats silently mis-routing), assign attributes.  The env
    is read at CONSTRUCTION (import time) and on explicit
    :meth:`reload`, never at trace time — the recompile-hazard hoist —
    and every instance auto-registers for :func:`reload_launch_knobs`
    so tooling that mutates the environment can re-read all knobs
    without enumerating modules."""

    def __init__(self):
        self._read()
        register_launch_knobs(self)

    def reload(self):
        """Re-read the current environment; returns self."""
        self._read()
        return self

    def _read(self) -> None:
        raise NotImplementedError


def reload_launch_knobs() -> None:
    """Re-read every registered launch-set knob from the current
    environment (test teardowns; config tools)."""
    for k in LAUNCH_KNOBS:
        k.reload()


# bootstrap envs
SELF_SPEC = "KF_SELF_SPEC"
INIT_PEERS = "KF_INIT_PEERS"
INIT_RUNNERS = "KF_INIT_RUNNERS"
PARENT_ID = "KF_PARENT_ID"
INIT_CLUSTER_VERSION = "KF_INIT_CLUSTER_VERSION"
ALLREDUCE_STRATEGY = "KF_ALLREDUCE_STRATEGY"
DEVICE_STRATEGY = "KF_DEVICE_STRATEGY"
CONFIG_SERVER = "KF_CONFIG_SERVER"
JOB_START_TIMESTAMP = "KF_JOB_START_TIMESTAMP"
PROC_START_TIMESTAMP = "KF_PROC_START_TIMESTAMP"
NUM_DEVICES = "KF_NUM_DEVICES"
COORDINATOR = "KF_COORDINATOR"
NUM_PROCESSES = "KF_NUM_PROCESSES"
PROCESS_ID = "KF_PROCESS_ID"
WORLD_PEERS = "KF_WORLD_PEERS"

# tuning envs
ENABLE_MONITORING = "KF_CONFIG_ENABLE_MONITORING"
MONITORING_PERIOD = "KF_CONFIG_MONITORING_PERIOD"
ENABLE_STALL_DETECTION = "KF_CONFIG_ENABLE_STALL_DETECTION"
LOG_LEVEL = "KF_CONFIG_LOG_LEVEL"
STRATEGY_HASH_METHOD = "KF_CONFIG_STRATEGY_HASH_METHOD"
WAIT_RUNNER_TIMEOUT = "KF_CONFIG_WAIT_RUNNER_TIMEOUT"
CHUNK_SIZE = "KF_CONFIG_CHUNK_SIZE"
ENGINE_THREADS = "KF_CONFIG_ENGINE_THREADS"
ENGINE_TIMEOUT = "KF_CONFIG_ENGINE_TIMEOUT"
PEER_DEADLINE = "KF_CONFIG_PEER_DEADLINE"
HOST_POOL_MAX = "KF_CONFIG_HOST_POOL_MAX"
P2P_RESPONDERS = "KF_CONFIG_P2P_RESPONDERS"
OVERLAP_DEPTH = "KF_CONFIG_OVERLAP_DEPTH"

# observability envs (read by kungfu_tpu/monitor/timeline.py, which
# defines mirror constants next to its reader code; registered here so
# the env-contract scan anchors them like every other KF_* knob)
TRACE_DUMP = "KF_CONFIG_TRACE_DUMP"
TIMELINE_CAP = "KF_CONFIG_TIMELINE_CAP"

# live cluster-monitor envs (monitor/aggregator.py: per-rank snapshot
# pushes to the aggregator co-hosted with the config server)
ENABLE_CLUSTER_MONITOR = "KF_CONFIG_ENABLE_CLUSTER_MONITOR"
MONITOR_PUSH_PERIOD = "KF_CONFIG_MONITOR_PUSH_PERIOD"
MONITOR_STALE_AFTER = "KF_CONFIG_MONITOR_STALE_AFTER"

# kf-xray envs (monitor/xray.py + ops/costmodel.py define mirror
# constants next to their readers, like timeline.py's CAP_ENV; the
# env-contract scan anchors the tokens here)
XRAY_WINDOW_STEPS = "KF_XRAY_WINDOW_STEPS"
XRAY_PEAK_FLOPS = "KF_XRAY_PEAK_FLOPS"

# pipeline-parallel envs (kf-pipeline: read by ParallelPlan.from_env in
# parallel/train.py, consumed by parallel/pp.py)
PP_STAGES = "KF_PP_STAGES"
PP_MICROBATCHES = "KF_PP_MICROBATCHES"
PP_SCHEDULE = "KF_PP_SCHEDULE"

# multislice envs.  The MEGASCALE_* names are the TPU runtime's own
# contract (libtpu/GKE publish them on every pod host; the emulation
# contract sets them per worker process — platforms/tpu_pod.py);
# KF_SLICE_RANKS is this framework's addition: the launcher pins the
# ranks-per-slice so elastic membership changes cannot break the
# bootstrap-derived slice mapping.  Registered here so the env-contract
# scan anchors them instead of module-local constants drifting.
MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"
MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
SLICE_RANKS = "KF_SLICE_RANKS"

# serving envs (read by kungfu_tpu/serve via these constants; registered
# here so the env-contract scan anchors the kf-serve knobs to the same
# registry as every other KF_* token)
SERVE_QUEUE_DEPTH = "KF_SERVE_QUEUE_DEPTH"
SERVE_PAGE_TOKENS = "KF_SERVE_PAGE_TOKENS"
SERVE_KV_PAGES = "KF_SERVE_KV_PAGES"
SERVE_MAX_BATCH = "KF_SERVE_MAX_BATCH"
SERVE_MAX_TOKENS = "KF_SERVE_MAX_TOKENS"
SERVE_COMMIT_EVERY = "KF_SERVE_COMMIT_EVERY"
SERVE_REQUEST_DEADLINE = "KF_SERVE_REQUEST_DEADLINE"
SERVE_SLO_TTFT_MS = "KF_SERVE_SLO_TTFT_MS"
SERVE_SLO_E2E_MS = "KF_SERVE_SLO_E2E_MS"

# persistence envs (read by kungfu_tpu/elastic/persist.py via
# persist_knobs() at plane construction and by the runner's supervisor
# path; registered here so the env-contract scan anchors the kf-persist
# knobs to the same registry as every other KF_* token)
PERSIST_DIR = "KF_PERSIST_DIR"
PERSIST_PERIOD = "KF_PERSIST_PERIOD"
PERSIST_ASYNC_DEPTH = "KF_PERSIST_ASYNC_DEPTH"
PERSIST_KEEP = "KF_PERSIST_KEEP"
PERSIST_RESTORE = "KF_PERSIST_RESTORE"

# kf-sentinel envs (monitor/sentinel.py + monitor/history.py define
# mirror constants next to their readers and parse os.environ directly —
# the stubbed kfhist/kftop context cannot import this module; registered
# here so the env-contract scan anchors the tokens, and sentinel_knobs()
# below pins the defaults both sides must agree on)
SENTINEL_DIR = "KF_SENTINEL_DIR"
SENTINEL_KEEP_BYTES = "KF_SENTINEL_KEEP_BYTES"
SENTINEL_PERIOD = "KF_SENTINEL_PERIOD"
SENTINEL_WINDOW = "KF_SENTINEL_WINDOW"
SENTINEL_THRESHOLD = "KF_SENTINEL_THRESHOLD"
SENTINEL_MFU_FLOOR = "KF_SENTINEL_MFU_FLOOR"
SENTINEL_STEP_CEILING_S = "KF_SENTINEL_STEP_CEILING_S"
SENTINEL_WARMUP_STEPS = "KF_SENTINEL_WARMUP_STEPS"
SENTINEL_INCIDENT_WINDOW = "KF_SENTINEL_INCIDENT_WINDOW"
SENTINEL_SLO_SHORT = "KF_SENTINEL_SLO_SHORT"
SENTINEL_SLO_LONG = "KF_SENTINEL_SLO_LONG"

# kf-pulse envs (monitor/pulse.py defines mirror constants next to its
# reader, same doctrine as the sentinel tokens above; pulse_knobs()
# below pins the defaults both sides must agree on)
PULSE_EVERY = "KF_PULSE_EVERY"
PULSE_EMA = "KF_PULSE_EMA"

# fault-injection envs (read by kungfu_tpu/chaos/inject.py at controller
# creation; registered here so the env-contract scan anchors them to the
# same registry as every other KF_* knob)
CHAOS_SPEC = "KF_CHAOS_SPEC"
CHAOS_SEED = "KF_CHAOS_SEED"

# protocol-verifier envs (read by kungfu_tpu/analysis/protoverify.py via
# os.environ directly — the analysis package is stdlib-only and must not
# import this jax-adjacent module; registered here so the env-contract
# scan anchors the kf-verify knobs to the same registry, and
# verify_knobs() below pins the defaults both sides must agree on)
VERIFY_MAX_RANKS = "KF_VERIFY_MAX_RANKS"
VERIFY_GEOMETRY_CAP = "KF_VERIFY_GEOMETRY_CAP"
VERIFY_TIMEOUT_S = "KF_VERIFY_TIMEOUT_S"

ALL_BOOTSTRAP_ENVS = [
    SELF_SPEC, INIT_PEERS, INIT_RUNNERS, PARENT_ID, INIT_CLUSTER_VERSION,
    ALLREDUCE_STRATEGY, CONFIG_SERVER, JOB_START_TIMESTAMP,
    PROC_START_TIMESTAMP, NUM_DEVICES, COORDINATOR, NUM_PROCESSES, PROCESS_ID,
    WORLD_PEERS,
]


def parse_bool_env(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def parse_int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def parse_float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def verify_knobs() -> dict:
    """The kf-verify geometry-sweep knobs, parsed with their defaults.

    protoverify._knobs() reads the same tokens from ``os.environ``
    directly (it cannot import this module); tests pin that both sides
    use these exact defaults so the documented contract cannot drift.
    """
    return {
        "max_ranks": parse_int_env(VERIFY_MAX_RANKS, 16),
        "geometry_cap": parse_int_env(VERIFY_GEOMETRY_CAP, 0),
        "timeout_s": parse_float_env(VERIFY_TIMEOUT_S, 60.0),
    }


def persist_knobs() -> dict:
    """The kf-persist plane knobs, parsed with their defaults
    (elastic/persist.py constructs a :class:`~kungfu_tpu.elastic.
    persist.PersistPlane` from these; kfrun's ``-persist-dir`` /
    ``-restore-from`` flags export the dir + restore arm)."""
    return {
        "dir": os.environ.get(PERSIST_DIR, ""),
        "period_s": parse_float_env(PERSIST_PERIOD, 30.0),
        "depth": parse_int_env(PERSIST_ASYNC_DEPTH, 2),
        "keep": parse_int_env(PERSIST_KEEP, 3),
        "restore": parse_bool_env(PERSIST_RESTORE, False),
    }


def sentinel_knobs() -> dict:
    """The kf-sentinel plane knobs, parsed with their defaults.

    monitor/sentinel.py reads the same tokens from ``os.environ``
    directly (the stubbed kfhist context cannot import this module);
    tests pin that both sides use these exact defaults so the
    documented contract cannot drift.
    """
    return {
        "dir": os.environ.get(SENTINEL_DIR, ""),
        "keep_bytes": parse_int_env(SENTINEL_KEEP_BYTES, 8 << 20),
        "period_s": parse_float_env(SENTINEL_PERIOD, 1.0),
        "window": parse_int_env(SENTINEL_WINDOW, 8),
        "threshold": parse_float_env(SENTINEL_THRESHOLD, 4.0),
        "mfu_floor": parse_float_env(SENTINEL_MFU_FLOOR, 0.0),
        "step_ceiling_s": parse_float_env(SENTINEL_STEP_CEILING_S, 0.0),
        "warmup_steps": parse_int_env(SENTINEL_WARMUP_STEPS, 32),
        "incident_window": parse_int_env(SENTINEL_INCIDENT_WINDOW, 64),
        "slo_short": parse_int_env(SENTINEL_SLO_SHORT, 6),
        "slo_long": parse_int_env(SENTINEL_SLO_LONG, 24),
    }


def pulse_knobs() -> dict:
    """The kf-pulse plane knobs, parsed with their defaults.

    monitor/pulse.py reads the same tokens from ``os.environ`` directly
    (mirror constants, same doctrine as :func:`sentinel_knobs`); tests
    pin that both sides use these exact defaults so the documented
    contract cannot drift.
    """
    return {
        "every": parse_int_env(PULSE_EVERY, 10),
        "ema": parse_float_env(PULSE_EMA, 0.2),
    }


@dataclass
class Config:
    """Parsed bootstrap configuration for one worker process."""

    self_id: PeerID
    cluster: Cluster
    parent: Optional[PeerID] = None
    strategy: Strategy = Strategy.AUTO
    #: initial device-plane allreduce schedule ("" = psum default)
    device_strategy: str = ""
    init_version: int = 0
    config_server: str = ""
    single_process: bool = False
    coordinator: str = ""
    num_processes: int = 1
    process_id: int = 0
    #: full provisioned worker-slot list; None = fixed world (world == the
    #: initial worker list, resize beyond it needs relaunched processes)
    world_peers: Optional[PeerList] = None
    job_start: float = field(default_factory=time.time)
    proc_start: float = field(default_factory=time.time)

    @property
    def detached(self) -> bool:
        """True when self is not a member of the current worker list."""
        return self.cluster.workers.rank(self.self_id) is None

    @property
    def rank(self) -> int:
        r = self.cluster.workers.rank(self.self_id)
        if r is None:
            raise RuntimeError(
                f"peer {self.self_id} is not in the worker list {self.cluster.workers}"
            )
        return r

    @property
    def size(self) -> int:
        return self.cluster.size()


def parse_config_from_env(env=None) -> Config:
    """Parse the bootstrap contract; fall back to single-process mode when
    ``KF_SELF_SPEC`` is unset (reference ``env/config.go:24-80``)."""
    env = env if env is not None else os.environ
    self_spec = env.get(SELF_SPEC)
    if not self_spec:
        c = Cluster.single_process()
        return Config(self_id=c.workers[0], cluster=c, single_process=True,
                      device_strategy=env.get(DEVICE_STRATEGY, ""))
    self_id = parse_peer_id(self_spec)
    workers = PeerList.parse(env.get(INIT_PEERS, self_spec))
    runners_spec = env.get(INIT_RUNNERS, "")
    if runners_spec:
        runners = PeerList.parse(runners_spec)
    else:
        # no runner daemon (mp-spawn / test mode): synthesize one per host
        from kungfu_tpu.plan.hostspec import DEFAULT_RUNNER_PORT

        runners = PeerList(tuple(PeerID(h, DEFAULT_RUNNER_PORT) for h in workers.hosts()))
    cluster = Cluster(runners, workers)
    cluster.validate()
    parent = parse_peer_id(env[PARENT_ID]) if env.get(PARENT_ID) else None
    world_spec = env.get(WORLD_PEERS, "")
    world = PeerList.parse(world_spec) if world_spec else None
    if world is not None and world.rank(self_id) is None:
        raise ValueError(f"{WORLD_PEERS} set but {self_id} is not a slot in {world}")
    # with a provisioned world, the jax process identity is the WORLD slot
    # index (stable across resizes), not the elastic worker rank
    num_processes = int(env.get(NUM_PROCESSES, str(len(world)) if world else "1"))
    process_id = int(env.get(PROCESS_ID, str(world.rank(self_id)) if world else "0"))
    return Config(
        self_id=self_id,
        cluster=cluster,
        parent=parent,
        strategy=parse_strategy(env.get(ALLREDUCE_STRATEGY, "AUTO")),
        device_strategy=env.get(DEVICE_STRATEGY, ""),
        init_version=int(env.get(INIT_CLUSTER_VERSION, "0")),
        config_server=env.get(CONFIG_SERVER, ""),
        coordinator=env.get(COORDINATOR, ""),
        num_processes=num_processes,
        process_id=process_id,
        world_peers=world,
        job_start=float(env.get(JOB_START_TIMESTAMP, time.time())),
        proc_start=float(env.get(PROC_START_TIMESTAMP, time.time())),
    )


def single_machine_env(rank: int, size: int, host: str = "127.0.0.1") -> dict:
    """Env dict for mp-spawned single-machine workers
    (reference ``env/config.go:59`` SingleMachineEnv)."""
    from kungfu_tpu.plan.hostspec import DEFAULT_PORT_RANGE

    lo, _ = DEFAULT_PORT_RANGE
    peers = ",".join(f"{host}:{lo + i}" for i in range(size))
    return {
        SELF_SPEC: f"{host}:{lo + rank}",
        INIT_PEERS: peers,
        INIT_RUNNERS: f"{host}:38080",
        INIT_CLUSTER_VERSION: "0",
    }
