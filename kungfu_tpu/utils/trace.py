"""Scoped tracing / profiling.

Parity with the reference's optional stdtracer (``TRACE_SCOPE``,
``include/kungfu/utils/trace.hpp:1-17``, enabled by
``KUNGFU_ENABLE_TRACE``) plus the TPU-native upgrade: scopes can also
drive :mod:`jax.profiler` so a traced region produces an XPlane/
TensorBoard trace of the actual device timeline.

* ``trace_scope(name)`` — context manager / decorator.  When
  ``KF_CONFIG_ENABLE_TRACE`` is truthy, logs entry depth + duration and
  accumulates per-name (count, total) stats; near-zero cost when off.
* ``trace_report()`` — aggregated table of all scopes seen.
* ``device_trace(logdir)`` — jax.profiler capture of the wrapped region
  (the stdtracer analog for the compiled side: XLA owns the device
  schedule, so device-side "tracing" is the profiler, not prints).

The runner stamps ``KF_JOB_START_TIMESTAMP`` / ``KF_PROC_START_TIMESTAMP``
(``runner/job.py``), and ``kungfu_tpu.utils.log.log_event`` anchors event
lines on them — together these reproduce the reference's event-timeline
logging (``_utils.py:44-51``).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
from typing import Dict, Optional, Tuple

from kungfu_tpu.utils.log import get_logger

_log = get_logger("trace")

ENABLE_TRACE = "KF_CONFIG_ENABLE_TRACE"

_local = threading.local()
_stats_lock = threading.Lock()
_stats: Dict[str, Tuple[int, float]] = {}
#: per-name duration histograms (monitor.registry.Histogram, imported
#: lazily — utils must stay importable without the monitor package)
_hists: Dict[str, object] = {}
_Histogram = None


def trace_enabled() -> bool:
    return os.environ.get(ENABLE_TRACE, "").lower() in ("1", "true", "yes")


def _hist_cls():
    global _Histogram
    if _Histogram is None:
        from kungfu_tpu.monitor.registry import Histogram

        _Histogram = Histogram
    return _Histogram


def _record(name: str, dt: float) -> None:
    # resolve the histogram class BEFORE taking the lock: the first call
    # imports the monitor package, and running the import machinery under
    # _stats_lock could deadlock against a module whose import-time code
    # records a scope (import lock vs stats lock, opposite orders)
    cls = _hist_cls()
    with _stats_lock:
        n, total = _stats.get(name, (0, 0.0))
        _stats[name] = (n + 1, total + dt)
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = cls()
    # observe outside _stats_lock: the histogram has its own lock and
    # nesting the two would put an avoidable edge in the lock graph
    h.observe(dt)


def record_duration(name: str, dt: float) -> None:
    """Public aggregation hook: feed one scope duration into the trace
    stats AND its latency histogram — ``timeline.span`` regions report
    here so ``trace_report`` covers them like any ``trace_scope``."""
    _record(name, dt)


@contextlib.contextmanager
def trace_scope(name: str, force: bool = False):
    """Time a region; nested scopes are indented by depth in the log."""
    if not (force or trace_enabled()):
        yield
        return
    depth = getattr(_local, "depth", 0)
    _local.depth = depth + 1
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _local.depth = depth
        _record(name, dt)
        _log.info("%s%s took %.3fms", "  " * depth, name, dt * 1e3)


def traced(fn=None, *, name: Optional[str] = None):
    """Decorator form of :func:`trace_scope`."""
    if fn is None:
        return functools.partial(traced, name=name)

    scope = name or fn.__qualname__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with trace_scope(scope):
            return fn(*args, **kwargs)

    return wrapper


def trace_report() -> Dict[str, Dict[str, float]]:
    """Aggregated scope stats: ``{name: {count, total_s, mean_ms,
    min_ms, max_ms, p50_ms, p95_ms}}``.  The original three keys keep
    their exact semantics; the tail keys come from the fixed-bucket
    histogram (``monitor.registry.Histogram``) — a mean alone hides
    exactly the straggler tails this subsystem exists to expose."""
    with _stats_lock:
        snap = dict(_stats)
        hists = dict(_hists)
    out: Dict[str, Dict[str, float]] = {}
    for name, (n, total) in snap.items():
        row = {
            "count": n,
            "total_s": total,
            "mean_ms": (total / n * 1e3) if n else 0.0,
        }
        h = hists.get(name)
        if h is not None and h.count:
            s = h.summary()
            row["min_ms"] = s["min"] * 1e3
            row["max_ms"] = s["max"] * 1e3
            row["p50_ms"] = s["p50"] * 1e3
            row["p95_ms"] = s["p95"] * 1e3
        out[name] = row
    return out


def reset_trace_stats() -> None:
    with _stats_lock:
        _stats.clear()
        _hists.clear()


@contextlib.contextmanager
def device_trace(logdir: str, force: bool = False):
    """Capture a jax.profiler trace (XPlane, viewable in TensorBoard /
    xprof) of the wrapped region.  No-op unless tracing is enabled."""
    if not (force or trace_enabled()):
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        _log.info("device trace written to %s", logdir)
