"""Scoped tracing / profiling.

Parity with the reference's optional stdtracer (``TRACE_SCOPE``,
``include/kungfu/utils/trace.hpp:1-17``, enabled by
``KUNGFU_ENABLE_TRACE``) plus the TPU-native upgrade: scopes can also
drive :mod:`jax.profiler` so a traced region produces an XPlane/
TensorBoard trace of the actual device timeline.

* ``trace_scope(name)`` — context manager / decorator.  When
  ``KF_CONFIG_ENABLE_TRACE`` is truthy, logs entry depth + duration and
  accumulates per-name (count, total) stats; near-zero cost when off.
* ``trace_report()`` — aggregated table of all scopes seen.
* ``device_trace(logdir)`` — jax.profiler capture of the wrapped region
  (the stdtracer analog for the compiled side: XLA owns the device
  schedule, so device-side "tracing" is the profiler, not prints).

The runner stamps ``KF_JOB_START_TIMESTAMP`` / ``KF_PROC_START_TIMESTAMP``
(``runner/job.py``), and ``kungfu_tpu.utils.log.log_event`` anchors event
lines on them — together these reproduce the reference's event-timeline
logging (``_utils.py:44-51``).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time
from typing import Dict, Optional, Tuple

from kungfu_tpu.utils.log import get_logger

_log = get_logger("trace")

ENABLE_TRACE = "KF_CONFIG_ENABLE_TRACE"

_local = threading.local()
_stats_lock = threading.Lock()
_stats: Dict[str, Tuple[int, float]] = {}


def trace_enabled() -> bool:
    return os.environ.get(ENABLE_TRACE, "").lower() in ("1", "true", "yes")


def _record(name: str, dt: float) -> None:
    with _stats_lock:
        n, total = _stats.get(name, (0, 0.0))
        _stats[name] = (n + 1, total + dt)


@contextlib.contextmanager
def trace_scope(name: str, force: bool = False):
    """Time a region; nested scopes are indented by depth in the log."""
    if not (force or trace_enabled()):
        yield
        return
    depth = getattr(_local, "depth", 0)
    _local.depth = depth + 1
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _local.depth = depth
        _record(name, dt)
        _log.info("%s%s took %.3fms", "  " * depth, name, dt * 1e3)


def traced(fn=None, *, name: Optional[str] = None):
    """Decorator form of :func:`trace_scope`."""
    if fn is None:
        return functools.partial(traced, name=name)

    scope = name or fn.__qualname__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with trace_scope(scope):
            return fn(*args, **kwargs)

    return wrapper


def trace_report() -> Dict[str, Dict[str, float]]:
    """Aggregated scope stats: ``{name: {count, total_s, mean_ms}}``."""
    with _stats_lock:
        snap = dict(_stats)
    return {
        name: {
            "count": n,
            "total_s": total,
            "mean_ms": (total / n * 1e3) if n else 0.0,
        }
        for name, (n, total) in snap.items()
    }


def reset_trace_stats() -> None:
    with _stats_lock:
        _stats.clear()


@contextlib.contextmanager
def device_trace(logdir: str, force: bool = False):
    """Capture a jax.profiler trace (XPlane, viewable in TensorBoard /
    xprof) of the wrapped region.  No-op unless tracing is enabled."""
    if not (force or trace_enabled()):
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        _log.info("device trace written to %s", logdir)
