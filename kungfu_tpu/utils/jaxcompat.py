"""Version shims for the jax API surface this tree targets.

The codebase is written against the modern jax surface (``jax.shard_map``
with ``check_vma``, ``jax.typeof`` with ``.vma``, ``jax.lax.pcast``),
but deployment environments pin older releases (0.4.x) where the same
functionality lives under ``jax.experimental.shard_map`` with
``check_rep`` and values carry no varying-manual-axes type at all.
Every module imports the symbols from here so the skew is absorbed in
one place; when the minimum jax is raised this file shrinks to
re-exports.
"""

from __future__ import annotations

import jax

try:  # modern surface (jax >= 0.6 exports it at top level)
    from jax import shard_map as _shard_map

    _MODERN_SHARD_MAP = True
except ImportError:  # 0.4.x: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _MODERN_SHARD_MAP = False


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """``jax.shard_map`` with the ``check_vma`` kwarg accepted on every
    jax version (mapped to 0.4.x's ``check_rep``, which gates the same
    replication/varying analysis under its old name).

    On 0.4.x the check defaults OFF: this tree satisfies the modern
    checker via ``vma=`` declarations on pallas ``out_shape``s, but
    0.4.x's ``check_rep`` has no replication rule for ``pallas_call``
    at all and rejects any kernel-bearing body outright.  Modern jax
    keeps its default (fully checked)."""
    if check_vma is not None:
        kw["check_vma" if _MODERN_SHARD_MAP else "check_rep"] = check_vma
    elif not _MODERN_SHARD_MAP:
        kw["check_rep"] = False
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)


def typeof(x):
    """``jax.typeof`` where it exists; the abstract value otherwise.
    0.4.x avals carry no ``vma`` attribute — callers read it with
    ``getattr(..., "vma", ())`` so the absence means "varies over
    nothing", which is exactly 0.4.x semantics (no vma typing)."""
    t = getattr(jax, "typeof", None)
    if t is not None:
        return t(x)
    return jax.core.get_aval(x)


def tpu_compiler_params(**kw):
    """``pallas.tpu.CompilerParams`` under its current name (0.4.x calls
    the same dataclass ``TPUCompilerParams``)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def axis_size(axis_name):
    """``jax.lax.axis_size`` where it exists; on 0.4.x the classic
    ``psum(1, axis)`` idiom, which jax folds to a concrete int for
    non-tracer operands (so ``range(axis_size(ax))`` stays legal)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast_varying(x, axes):
    """``lax.pcast(x, axes, to="varying")`` on jax versions with vma
    typing; identity on 0.4.x, where no value carries a varying type and
    the cast has nothing to record."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None or not axes:
        return x
    return pcast(x, tuple(axes), to="varying")


#: one-shot guard for install_compile_metrics (a second install would
#: double-count every compile)
_COMPILE_METRICS_INSTALLED = False

#: the jax monitoring event that marks one XLA backend compile — the
#: recompile signal kf-sentinel's recompile-steady watermark judges
_BACKEND_COMPILE_EVENT = "backend_compile_duration"


def install_compile_metrics() -> bool:
    """Mirror XLA compiles into the unified registry:
    ``kf_jit_compiles_total`` (counter) and ``kf_jit_compile_seconds``
    (histogram) tick on every ``/jax/core/compile/
    backend_compile_duration`` monitoring event — so the cluster
    snapshots carry them, kftop can show them, and the sentinel's
    recompile-steady watermark can alert on compiles after warmup
    (a steady-state recompile means a shape leak / cache bust).

    None-safe across jax versions: where ``jax.monitoring`` has no
    duration-listener hook this is a no-op returning ``False``.
    Idempotent — peers and tests may both call it."""
    global _COMPILE_METRICS_INSTALLED
    if _COMPILE_METRICS_INSTALLED:
        return True
    register = getattr(getattr(jax, "monitoring", None),
                       "register_event_duration_secs_listener", None)
    if register is None:
        return False
    from kungfu_tpu.monitor.registry import REGISTRY

    def _on_duration(name: str, duration: float, **_kw) -> None:
        if name.endswith(_BACKEND_COMPILE_EVENT):
            REGISTRY.counter("kf_jit_compiles_total").inc()
            REGISTRY.histogram("kf_jit_compile_seconds").observe(
                float(duration))

    register(_on_duration)
    _COMPILE_METRICS_INSTALLED = True
    return True


def set_cpu_device_count(n: int) -> None:
    """Force an ``n``-device virtual CPU platform across jax versions.

    Newer jax exposes ``jax_num_cpu_devices``; older versions only take
    the XLA flag.  Either way the setting must land BEFORE backend init
    (the first ``jax.devices()`` locks the platform in) — callers are
    the CPU-mesh benchmark/example harnesses, which run in fresh
    processes."""
    import os

    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except (AttributeError, KeyError):
        # this jax predates the option; the XLA flag is the only knob.
        # A RuntimeError (option exists but the backend is already
        # initialized) must propagate: the flag fallback would be a
        # silent no-op and the caller would run on 1 device.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={int(n)}")
