"""CPU affinity for local workers (reference NUMA placement analog).

The reference binds each local rank to a NUMA-partitioned CPU set via
``sched_setaffinity`` when ``KUNGFU_USE_AFFINITY`` is set
(``srcs/cpp/src/numa/affinity.cpp:26-40``, enabled in
``python/init.cpp:23-28``).  On a TPU host the same concern applies to
the host-side input pipeline and the collective engine's reducer
threads: N worker processes on one VM should not migrate across each
other's cores.  Enabled by ``KF_CONFIG_USE_AFFINITY``; the partition is
an even split of the currently-allowed CPUs by local rank (hwloc-style
topology discovery is unnecessary — cloud TPU VMs expose flat,
homogeneous vCPU sets).
"""

from __future__ import annotations

import os
from typing import List, Optional

from kungfu_tpu.utils.log import get_logger

_log = get_logger("affinity")

USE_AFFINITY = "KF_CONFIG_USE_AFFINITY"


def affinity_enabled() -> bool:
    return os.environ.get(USE_AFFINITY, "").lower() in ("1", "true", "yes")


def partition_cpus(cpus: List[int], local_rank: int, local_size: int) -> List[int]:
    """Even contiguous split of ``cpus`` (ranks with lower index get the
    remainder, matching the reference's per-rank partition)."""
    if local_size <= 0:
        raise ValueError("local_size must be positive")
    if not 0 <= local_rank < local_size:
        raise ValueError(f"local_rank {local_rank} not in [0, {local_size})")
    cpus = sorted(cpus)
    n = len(cpus)
    base, extra = divmod(n, local_size)
    start = local_rank * base + min(local_rank, extra)
    size = base + (1 if local_rank < extra else 0)
    return cpus[start : start + size]


def bind_local_rank(
    local_rank: int, local_size: int, pid: int = 0, force: bool = False
) -> Optional[List[int]]:
    """Pin ``pid`` (default: this process) to its local rank's CPU share.

    Returns the CPU list bound to, or None if disabled / unsupported /
    the share would be empty (never binds to an empty set — better
    unpinned than unschedulable)."""
    if not (force or affinity_enabled()):
        return None
    if not hasattr(os, "sched_getaffinity"):  # pragma: no cover - non-Linux
        _log.warning("affinity unsupported on this platform")
        return None
    allowed = sorted(os.sched_getaffinity(pid))
    share = partition_cpus(allowed, local_rank, local_size)
    if not share:
        _log.warning(
            "no CPUs for local rank %d/%d over %d allowed; leaving unpinned",
            local_rank, local_size, len(allowed),
        )
        return None
    os.sched_setaffinity(pid, share)
    _log.info("local rank %d/%d bound to CPUs %s", local_rank, local_size, share)
    return share
