"""Bounded, jittered retry/backoff vocabulary.

Every network retry loop in the tree must (a) bound its attempts — by a
deadline or an attempt count — and (b) back off between attempts with
jitter, so a whole cluster retrying the same dead endpoint does not
re-synchronize into a thundering herd (the ``retry-discipline`` kf-lint
rule enforces both; see :mod:`kungfu_tpu.analysis.retrydiscipline`).
These helpers are the blessed way to satisfy (b): a ``time.sleep`` whose
argument is computed — rather than a bare constant — is what the rule
looks for.

``backoff_delay`` implements capped exponential backoff with half-to-full
jitter (the delay for attempt ``k`` is uniform in
``[cap_k/2, cap_k)`` where ``cap_k = min(cap, base * 2**k)``): the mean
grows exponentially while two peers that failed at the same instant
still spread out.  ``jittered`` keeps a *fixed* mean period but
desynchronizes callers — for poll loops whose total duration is part of
a documented contract (e.g. the connect ladder's 500 x 200 ms window).
"""

from __future__ import annotations

import random
import time
from typing import Optional

#: exponent clamp: 2**16 * any sane base overflows every cap long before
#: this, but a caller looping hundreds of times must not overflow float
_MAX_EXP = 16


def backoff_delay(
    attempt: int,
    base: float = 0.2,
    cap: float = 2.0,
    rng: Optional[random.Random] = None,
) -> float:
    """Delay in seconds for 0-based ``attempt``: capped exponential with
    half-to-full jitter."""
    r = (rng or random).random()
    return min(cap, base * (2 ** min(max(attempt, 0), _MAX_EXP))) * (0.5 + 0.5 * r)


def sleep_backoff(
    attempt: int,
    base: float = 0.2,
    cap: float = 2.0,
    rng: Optional[random.Random] = None,
) -> float:
    """Sleep :func:`backoff_delay`; returns the slept delay."""
    d = backoff_delay(attempt, base, cap, rng)
    time.sleep(d)
    return d


def jittered(period: float, rng: Optional[random.Random] = None) -> float:
    """``period`` spread uniformly over ``[period/2, 3*period/2)`` — the
    mean is preserved (total-duration contracts hold) but concurrent
    retriers decorrelate."""
    r = (rng or random).random()
    return period * (0.5 + r)
