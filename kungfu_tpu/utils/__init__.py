from kungfu_tpu.utils.log import get_logger, log_event
from kungfu_tpu.utils.stall import stall_detector

__all__ = ["get_logger", "log_event", "stall_detector"]
