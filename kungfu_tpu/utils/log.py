"""Leveled logging + event timeline.

Parity with reference ``srcs/go/log/logger.go`` (level from env) and
``srcs/python/kungfu/python/_utils.py`` ``_log_event`` (wall time + seconds
since job/proc start, for measuring init/resync latency in elastic runs).
"""

from __future__ import annotations

import logging
import os
import sys
import time

_FMT = "[kf-tpu] %(asctime)s %(levelname).1s %(name)s: %(message)s"


def get_logger(name: str = "kungfu_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(_FMT, datefmt="%H:%M:%S"))
        logger.addHandler(h)
        level = os.environ.get("KF_CONFIG_LOG_LEVEL", "INFO").upper()
        logger.setLevel(getattr(logging, level, logging.INFO))
        logger.propagate = False
    return logger


def log_event(name: str) -> None:
    """Print an event with wall time and offsets from job/proc start."""
    now = time.time()
    job0 = float(os.environ.get("KF_JOB_START_TIMESTAMP", now))
    proc0 = float(os.environ.get("KF_PROC_START_TIMESTAMP", now))
    get_logger("event").info(
        "%s | wall=%.3f job+%.3fs proc+%.3fs", name, now, now - job0, now - proc0
    )
