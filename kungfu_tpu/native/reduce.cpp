// Native reduce kernels for the host-side collective engine.
//
// TPU-native equivalent of the reference's C++ reduction layer
// (srcs/go/kungfu/base/op.cpp std_transform_2 + f16.c AVX half kernels):
// the graph-collective engine's hot inner loop — accumulate a received
// chunk into the local buffer — runs here instead of numpy, with bf16
// added as a first-class dtype (it is the TPU wire format for gradients).
//
// SIMD comes from compiler auto-vectorization of the tight typed loops
// (-O3; portable codegen by default — see Makefile ARCHFLAGS for the
// -march=native opt-in); f16/bf16 widen to f32, reduce, and narrow back with
// round-to-nearest-even, matching XLA's conversion semantics.

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace {

enum Op : int32_t { OP_SUM = 0, OP_MIN = 1, OP_MAX = 2, OP_PROD = 3 };

enum Dtype : int32_t {
  DT_U8 = 0,
  DT_I8 = 1,
  DT_I16 = 2,
  DT_I32 = 3,
  DT_I64 = 4,
  DT_U16 = 5,
  DT_U32 = 6,
  DT_U64 = 7,
  DT_F16 = 8,
  DT_F32 = 9,
  DT_F64 = 10,
  DT_BF16 = 11,
};

template <typename T, typename F>
void apply(T* dst, const T* src, size_t n, F f) {
  for (size_t i = 0; i < n; ++i) dst[i] = f(dst[i], src[i]);
}

// min/max propagate NaN like np.minimum/np.maximum (a!=a is false for
// integral T, so the checks fold away there)
template <typename T>
inline T nan_min(T a, T b) {
  if (a != a) return a;
  if (b != b) return b;
  return b < a ? b : a;
}

template <typename T>
inline T nan_max(T a, T b) {
  if (a != a) return a;
  if (b != b) return b;
  return a < b ? b : a;
}

template <typename T>
int run_typed(T* dst, const T* src, size_t n, int32_t op) {
  switch (op) {
    case OP_SUM:
      apply(dst, src, n, [](T a, T b) { return static_cast<T>(a + b); });
      return 0;
    case OP_MIN:
      apply(dst, src, n, [](T a, T b) { return nan_min(a, b); });
      return 0;
    case OP_MAX:
      apply(dst, src, n, [](T a, T b) { return nan_max(a, b); });
      return 0;
    case OP_PROD:
      apply(dst, src, n, [](T a, T b) { return static_cast<T>(a * b); });
      return 0;
  }
  return -1;
}

// -- half / bfloat16 conversions -----------------------------------------
inline float f16_to_f32(uint16_t h) {
  uint32_t sign = static_cast<uint32_t>(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t man = h & 0x3FFu;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;
    } else {  // subnormal: normalize
      int shift = 0;
      while ((man & 0x400u) == 0) {
        man <<= 1;
        ++shift;
      }
      man &= 0x3FFu;
      bits = sign | ((127 - 15 - shift + 1) << 23) | (man << 13);
    }
  } else if (exp == 0x1F) {
    bits = sign | 0x7F800000u | (man << 13);
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

inline uint16_t f32_to_f16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t sign = (bits >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xFFu) - 127 + 15;
  uint32_t man = bits & 0x7FFFFFu;
  if (exp >= 0x1F) {  // overflow / inf / nan
    uint32_t m = ((bits >> 23) & 0xFFu) == 0xFFu && man ? 0x200u : 0u;
    return static_cast<uint16_t>(sign | 0x7C00u | m);
  }
  if (exp <= 0) {  // subnormal or zero
    if (exp < -10) return static_cast<uint16_t>(sign);
    man |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = man >> shift;
    uint32_t rem = man & ((1u << shift) - 1);
    uint32_t mid = 1u << (shift - 1);
    if (rem > mid || (rem == mid && (half & 1u))) ++half;
    return static_cast<uint16_t>(sign | half);
  }
  uint32_t half = static_cast<uint32_t>(exp) << 10 | (man >> 13);
  uint32_t rem = man & 0x1FFFu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1u))) ++half;
  return static_cast<uint16_t>(sign | half);
}

inline float bf16_to_f32(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  if ((bits & 0x7FFFFFFFu) > 0x7F800000u) {  // nan: keep quiet bit
    return static_cast<uint16_t>((bits >> 16) | 0x40u);
  }
  uint32_t lsb = (bits >> 16) & 1u;  // round to nearest even
  bits += 0x7FFFu + lsb;
  return static_cast<uint16_t>(bits >> 16);
}

// branchless bf16 round-to-nearest-even narrow: vectorizes (mask+blend)
// where the branchy f32_to_bf16 forces scalar code on the hot path
inline uint16_t f32_to_bf16_branchless(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  uint32_t lsb = (bits >> 16) & 1u;
  uint32_t rounded = (bits + 0x7FFFu + lsb) >> 16;
  uint32_t nan_out = (bits >> 16) | 0x40u;  // quiet the NaN
  bool is_nan = (bits & 0x7FFFFFFFu) > 0x7F800000u;
  return static_cast<uint16_t>(is_nan ? nan_out : rounded);
}

// op hoisted out of the loop so each case is a tight widen/op/narrow
// loop the vectorizer can handle
template <float (*ToF)(uint16_t), uint16_t (*FromF)(float), typename F>
void loop_16(uint16_t* dst, const uint16_t* src, size_t n, F f) {
  for (size_t i = 0; i < n; ++i) {
    dst[i] = FromF(f(ToF(dst[i]), ToF(src[i])));
  }
}

template <float (*ToF)(uint16_t), uint16_t (*FromF)(float)>
int run_16(uint16_t* dst, const uint16_t* src, size_t n, int32_t op) {
  switch (op) {
    case OP_SUM:
      loop_16<ToF, FromF>(dst, src, n, [](float a, float b) { return a + b; });
      return 0;
    case OP_MIN:
      loop_16<ToF, FromF>(dst, src, n, [](float a, float b) { return nan_min(a, b); });
      return 0;
    case OP_MAX:
      loop_16<ToF, FromF>(dst, src, n, [](float a, float b) { return nan_max(a, b); });
      return 0;
    case OP_PROD:
      loop_16<ToF, FromF>(dst, src, n, [](float a, float b) { return a * b; });
      return 0;
  }
  return -1;
}

// Runtime SIMD dispatch for the hot dtypes (the reference's explicit AVX
// f16 kernels, base/f16.c, done the portable way): target_clones emits
// SSE2/AVX2/AVX-512 variants of the whole inlined loop and the dynamic
// linker picks the widest one this CPU supports — no -march opt-in, no
// SIGILL risk on heterogeneous shared-filesystem fleets (the Makefile
// ARCHFLAGS concern).
// ... except under TSan/ASan: target_clones dispatches through IFUNC
// resolvers, which the dynamic linker runs during relocation — BEFORE
// the sanitizer runtime initializes — and that segfaults at startup.
// Sanitizer builds take the portable loop; they exist to find races,
// not to win benchmarks.
#if defined(__x86_64__) && defined(__GNUC__) && !defined(__clang__) && \
    !defined(__SANITIZE_THREAD__) && !defined(__SANITIZE_ADDRESS__)
#define KF_SIMD_CLONES \
  __attribute__((target_clones("default", "avx2", "avx512f")))
#else
#define KF_SIMD_CLONES
#endif

KF_SIMD_CLONES
int run_f32(float* dst, const float* src, size_t n, int32_t op) {
  return run_typed(dst, src, n, op);
}

KF_SIMD_CLONES
int run_f64(double* dst, const double* src, size_t n, int32_t op) {
  return run_typed(dst, src, n, op);
}

KF_SIMD_CLONES
int run_bf16(uint16_t* dst, const uint16_t* src, size_t n, int32_t op) {
  return run_16<bf16_to_f32, f32_to_bf16_branchless>(dst, src, n, op);
}

}  // namespace

extern "C" {

// dst <- dst OP src, elementwise over n elements (reference std_transform_2)
int kf_transform2(void* dst, const void* src, int64_t n, int32_t dtype,
                  int32_t op) {
  size_t m = static_cast<size_t>(n);
  switch (dtype) {
    case DT_U8: return run_typed(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(src), m, op);
    case DT_I8: return run_typed(static_cast<int8_t*>(dst), static_cast<const int8_t*>(src), m, op);
    case DT_I16: return run_typed(static_cast<int16_t*>(dst), static_cast<const int16_t*>(src), m, op);
    case DT_I32: return run_typed(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src), m, op);
    case DT_I64: return run_typed(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src), m, op);
    case DT_U16: return run_typed(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src), m, op);
    case DT_U32: return run_typed(static_cast<uint32_t*>(dst), static_cast<const uint32_t*>(src), m, op);
    case DT_U64: return run_typed(static_cast<uint64_t*>(dst), static_cast<const uint64_t*>(src), m, op);
    case DT_F32: return run_f32(static_cast<float*>(dst), static_cast<const float*>(src), m, op);
    case DT_F64: return run_f64(static_cast<double*>(dst), static_cast<const double*>(src), m, op);
    case DT_F16:
      return run_16<f16_to_f32, f32_to_f16>(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src), m, op);
    case DT_BF16:
      return run_bf16(
          static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src), m, op);
  }
  return -1;
}

int kf_version() { return 1; }

}  // extern "C"
