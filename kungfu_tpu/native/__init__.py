"""ctypes loader for the native host-runtime kernels.

The data-plane inner loop of the host collective engine — accumulate a
received chunk into the local buffer — runs in C++
(:file:`reduce.cpp`, the analog of reference ``base/op.cpp``
``std_transform_2`` + ``f16.c``), loaded here via ctypes (no pybind11 in
this environment).  The library is built lazily with ``make`` on first
use; when no toolchain or prebuilt ``.so`` is available every entry point
falls back to numpy, so the framework never hard-depends on the native
build (set ``KF_TPU_NO_NATIVE=1`` to force the fallback).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))


def _variant() -> str:
    """Sanitizer build variant from ``KF_NATIVE_SANITIZE`` ("" = plain).

    ``tsan``/``asan`` select the instrumented .so (separate output name
    + flag stamp, so variants never mix).  The sanitizer RUNTIME must be
    present at process start — run python under
    ``LD_PRELOAD=libtsan.so.0`` (resp. ``libasan.so``) or use the
    standalone ``kfstress-tsan`` binary; a bare dlopen of an
    instrumented .so into an uninstrumented python aborts."""
    v = os.environ.get("KF_NATIVE_SANITIZE", "").strip().lower()
    return v if v in ("tsan", "asan") else ""


def _lib_path() -> str:
    v = _variant()
    name = f"libkfnative-{v}.so" if v else "libkfnative.so"
    return os.path.join(_HERE, name)



_DTYPE_CODES = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.int16): 2,
    np.dtype(np.int32): 3,
    np.dtype(np.int64): 4,
    np.dtype(np.uint16): 5,
    np.dtype(np.uint32): 6,
    np.dtype(np.uint64): 7,
    np.dtype(np.float16): 8,
    np.dtype(np.float32): 9,
    np.dtype(np.float64): 10,
}
# ml_dtypes bfloat16 (the jax/TPU dtype) when available
try:  # pragma: no cover - environment dependent
    import ml_dtypes

    _DTYPE_CODES[np.dtype(ml_dtypes.bfloat16)] = 11
except ImportError:  # pragma: no cover
    pass

_OP_CODES = {"sum": 0, "min": 1, "max": 2, "prod": 3}

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    march = os.environ.get("KF_NATIVE_MARCH")
    make_args = ["make", "-C", _HERE, "-s"]
    if _variant():
        make_args.append(_variant())
    if march:
        make_args.append(f"ARCHFLAGS=-march={march}")
    # cross-process build lock: N local workers race on first use; losers
    # must wait for the winner's atomic rename, not observe a half-built .so
    lock_path = os.path.join(_HERE, ".build.lock")
    try:
        import fcntl

        with open(lock_path, "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                subprocess.run(
                    make_args, check=True, capture_output=True, timeout=120
                )
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)
        return os.path.exists(_lib_path())
    except (ImportError, OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first call; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:  # lock-free fast path: per-chunk callers
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("KF_TPU_NO_NATIVE") == "1":
            return None
        # make is dependency-aware, so always run it: a stale .so after a
        # reduce.cpp edit must be rebuilt, not silently loaded
        if not _build() and not os.path.exists(_lib_path()):
            return None
        try:
            lib = ctypes.CDLL(_lib_path())
        except OSError:
            return None
        lib.kf_transform2.restype = ctypes.c_int
        lib.kf_transform2.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


_NP_REDUCERS = {
    "sum": np.add,
    "min": np.minimum,
    "max": np.maximum,
    "prod": np.multiply,
}

#: public set of supported reduce-op names (consumed by the collective
#: engine; keep in sync with _OP_CODES / reduce.cpp)
REDUCE_OPS = frozenset(_NP_REDUCERS)


def transform2(dst: np.ndarray, src: np.ndarray, op: str) -> np.ndarray:
    """dst <- dst OP src in place (reference ``Transform2``,
    ``base/op.go:19-36``).  Arrays must be contiguous, same shape+dtype."""
    if dst.shape != src.shape or dst.dtype != src.dtype:
        raise ValueError(f"shape/dtype mismatch {dst.shape}/{dst.dtype} vs {src.shape}/{src.dtype}")
    lib = load()
    code = _DTYPE_CODES.get(dst.dtype)
    if (
        lib is not None
        and code is not None
        and dst.flags.c_contiguous
        and src.flags.c_contiguous
        and op in _OP_CODES
    ):
        rc = lib.kf_transform2(
            dst.ctypes.data, src.ctypes.data, dst.size, code, _OP_CODES[op]
        )
        if rc == 0:
            return dst
    _NP_REDUCERS[op](dst, src, out=dst)
    return dst


