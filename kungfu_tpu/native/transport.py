"""ctypes wrapper for the native C++ host transport (:file:`transport.cpp`).

Gives :mod:`kungfu_tpu.comm.host` a drop-in native backend for its message
channel: the accept loop, framed decode, rendezvous queues, token fencing,
and the pooled sender all run in C++ threads, with Python entering only
for control/p2p handler callbacks.  Falls back cleanly (``available()``
False) when the toolchain is absent.
"""

from __future__ import annotations

import ctypes
from typing import Callable, List, Optional

from kungfu_tpu import native as _native

# int cb(name, payload, len, src): return 0 if consumed, 1 to enqueue
MSG_CB = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_ubyte),
    ctypes.c_uint32,
    ctypes.c_char_p,
)

_proto_done = False


def _lib():
    global _proto_done
    lib = _native.load()
    if lib is None:
        return None
    if not hasattr(lib, "kf_host_create"):  # stale prebuilt .so without transport
        return None
    if not _proto_done:
        lib.kf_host_create.restype = ctypes.c_void_p
        lib.kf_host_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_int,
        ]
        lib.kf_host_close.argtypes = [ctypes.c_void_p]
        lib.kf_host_set_token.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.kf_host_token.restype = ctypes.c_uint32
        lib.kf_host_token.argtypes = [ctypes.c_void_p]
        lib.kf_host_send.restype = ctypes.c_int
        lib.kf_host_send.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
        ]
        lib.kf_host_recv.restype = ctypes.c_int
        lib.kf_host_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_double,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.kf_host_buf_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
        lib.kf_host_recv_into.restype = ctypes.c_int
        lib.kf_host_recv_into.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_double, ctypes.c_void_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.kf_host_recv_begin.restype = ctypes.c_void_p
        lib.kf_host_recv_begin.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_uint32, ctypes.POINTER(ctypes.c_int),
        ]
        lib.kf_host_recv_finish.restype = ctypes.c_int
        lib.kf_host_recv_finish.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_double, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.kf_host_recv_abort.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_void_p,
        ]
        lib.kf_host_ping.restype = ctypes.c_int
        lib.kf_host_ping.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double]
        lib.kf_host_reset_connections.argtypes = [ctypes.c_void_p]
        lib.kf_host_set_control_cb.argtypes = [ctypes.c_void_p, MSG_CB]
        lib.kf_host_set_p2p_cb.argtypes = [ctypes.c_void_p, MSG_CB]
        lib.kf_host_ingress_snapshot.restype = ctypes.c_int
        lib.kf_host_ingress_snapshot.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.kf_host_egress_snapshot.restype = ctypes.c_int
        lib.kf_host_egress_snapshot.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.kf_engine_all_reduce.restype = ctypes.c_int
        lib.kf_engine_all_reduce.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_uint64, ctypes.c_double, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double),
        ]
        _proto_done = True
    return lib


def available() -> bool:
    return _lib() is not None


class NativeTransport:
    """One C++ channel endpoint.  Raises OSError if the port can't bind."""

    def __init__(self, self_spec: str, port: int, bind_host: str = "",
                 token: int = 0, use_unix: bool = True):
        lib = _lib()
        if lib is None:
            raise RuntimeError("native transport unavailable")
        self._libref = lib  # keep alive through interpreter teardown
        if bind_host:
            # the C++ bind path takes a dotted-quad only (inet_pton);
            # resolve hostnames here, and fall back to the wildcard rather
            # than failing channel creation on an unresolvable name
            import socket as _socket

            try:
                bind_host = _socket.gethostbyname(bind_host)
            except OSError:
                bind_host = ""
        self._h = lib.kf_host_create(
            self_spec.encode(), (bind_host or "").encode(), port, token,
            1 if use_unix else 0,
        )
        if not self._h:
            raise OSError(f"cannot bind native channel on port {port}")
        # CFUNCTYPE objects must outlive the channel
        self._cbs: List[object] = []

    def close(self) -> None:
        if self._h:
            self._libref.kf_host_close(self._h)
            self._h = None

    def set_token(self, token: int) -> None:
        self._libref.kf_host_set_token(self._h, token)

    @property
    def token(self) -> int:
        return int(self._libref.kf_host_token(self._h))

    def send(self, peer_spec: str, name: str, payload, conn_type: int,
             retries: int) -> None:
        """``payload``: any contiguous buffer (bytes, numpy array,
        memoryview) — passed by POINTER to the C++ writev (which sends
        from the caller's memory synchronously), so a ~100 MiB gossip
        blob crosses Python→wire with zero copies."""
        if isinstance(payload, bytes):
            # bytes → borrowed char* (no copy); the object outlives the
            # synchronous call
            ptr = ctypes.cast(ctypes.c_char_p(payload), ctypes.c_void_p)
            nbytes = len(payload)
        else:
            mv = memoryview(payload)
            if not mv.contiguous:
                raise ValueError("send needs a contiguous buffer")
            import numpy as _np

            arr = _np.frombuffer(mv.cast("B"), _np.uint8)  # view, ro-safe
            ptr = ctypes.c_void_p(arr.ctypes.data)
            nbytes = arr.nbytes
        rc = self._libref.kf_host_send(
            self._h, peer_spec.encode(), name.encode(), ptr, nbytes,
            conn_type, retries,
        )
        if rc == -3:
            raise ValueError(
                f"payload of {nbytes} bytes exceeds the 3 GiB frame "
                "limit — split the blob (the engine chunks at 1 MiB; this "
                "can only come from an oversized p2p/control message)"
            )
        if rc != 0:
            raise ConnectionError(
                f"cannot reach {peer_spec} after {retries} retries")

    def recv(self, src_spec: str, name: str, conn_type: int,
             timeout: Optional[float]) -> bytes:
        out = ctypes.POINTER(ctypes.c_ubyte)()
        out_len = ctypes.c_uint32()
        rc = self._libref.kf_host_recv(
            self._h, src_spec.encode(), name.encode(), conn_type,
            -1.0 if timeout is None else float(timeout),
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if rc == 1:
            raise TimeoutError(
                f"recv {name!r} from {src_spec} timed out after {timeout}s")
        if rc != 0:
            raise ConnectionError("channel closed")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._libref.kf_host_buf_free(out)

    def recv_into(self, src_spec: str, name: str, conn_type: int,
                  timeout: Optional[float], buf) -> bool:
        """Receive directly into ``buf`` (a writable contiguous buffer,
        e.g. a numpy array) — the registered-buffer zero-copy path
        (reference RecvInto/WaitRecvBuf): the payload goes socket→buffer
        with no allocation, queue hop, or ctypes copy.  Returns False on
        size mismatch (payload stays queued; fall back to :meth:`recv`)."""
        mv = memoryview(buf)
        if mv.readonly or not mv.contiguous:
            raise ValueError("recv_into needs a writable contiguous buffer")
        cap = mv.nbytes
        got = ctypes.c_uint32()
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        rc = self._libref.kf_host_recv_into(
            self._h, src_spec.encode(), name.encode(), conn_type,
            -1.0 if timeout is None else float(timeout),
            addr, cap, ctypes.byref(got),
        )
        if rc == 0:
            return True
        if rc == -2:
            return False
        if rc == 1:
            raise TimeoutError(
                f"recv_into {name!r} from {src_spec} timed out after {timeout}s")
        raise ConnectionError("channel closed")

    def recv_begin(self, src_spec: str, name: str, conn_type: int, buf):
        """Register ``buf`` for a zero-copy receive BEFORE the request is
        dispatched (see kf_host_recv_begin).  Returns an opaque handle to
        pass to :meth:`recv_finish`/:meth:`recv_abort`, or None when
        nothing was registered (rc -2: a queued payload of another size —
        fall back to :meth:`recv`; rc 2: channel closed)."""
        mv = memoryview(buf)
        if mv.readonly or not mv.contiguous:
            raise ValueError("recv_begin needs a writable contiguous buffer")
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        rc = ctypes.c_int()
        h = self._libref.kf_host_recv_begin(
            self._h, src_spec.encode(), name.encode(), conn_type,
            addr, mv.nbytes, ctypes.byref(rc),
        )
        if h is None:
            if rc.value == 2:
                raise ConnectionError("channel closed")
            return None  # -2 size mismatch / -3 duplicate: caller recvs
        return h

    def recv_finish(self, src_spec: str, name: str, conn_type: int,
                    timeout: Optional[float], handle) -> bool:
        """Resolve a :meth:`recv_begin` registration; True = buffer
        filled, False = a queued payload of another size (fall back to
        :meth:`recv`).  Consumes the handle on every outcome."""
        got = ctypes.c_uint32()
        rc = self._libref.kf_host_recv_finish(
            self._h, src_spec.encode(), name.encode(), conn_type,
            -1.0 if timeout is None else float(timeout),
            handle, ctypes.byref(got),
        )
        if rc == 0:
            return True
        if rc == -2:
            return False
        if rc == 1:
            raise TimeoutError(
                f"recv_finish {name!r} from {src_spec} timed out after {timeout}s")
        raise ConnectionError("channel closed")

    def recv_abort(self, src_spec: str, name: str, conn_type: int, handle) -> None:
        self._libref.kf_host_recv_abort(
            self._h, src_spec.encode(), name.encode(), conn_type, handle)

    def ping(self, peer_spec: str, timeout: float) -> bool:
        return self._libref.kf_host_ping(self._h, peer_spec.encode(), timeout) == 0

    def reset_connections(self) -> None:
        self._libref.kf_host_reset_connections(self._h)

    def set_control_handler(self, fn: Callable[[str, bytes, str], bool]) -> None:
        """``fn(name, payload, src) -> consumed``; not-consumed falls
        through to the rendezvous queue."""
        self._set_cb(self._libref.kf_host_set_control_cb, fn)

    def set_p2p_handler(self, fn: Callable[[str, bytes, str], bool]) -> None:
        self._set_cb(self._libref.kf_host_set_p2p_cb, fn)

    def _set_cb(self, setter, fn) -> None:
        @MSG_CB
        def trampoline(name, payload, length, src):
            try:
                data = ctypes.string_at(payload, length) if length else b""
                return 0 if fn(name.decode(), data, src.decode()) else 1
            except Exception:  # noqa: BLE001 - never unwind into C++
                return 1

        self._cbs.append(trampoline)
        setter(self._h, trampoline)

    def engine_all_reduce(self, peers_csv: str, buf, elem_size: int,
                          dtype_code: int, op_code: int, graph_data,
                          pair_offsets, n_pairs: int, tag: str,
                          hash_mode: int, chunk_size: int, timeout: float,
                          max_threads: int, stats) -> int:
        """Fully-native chunked graph allreduce; ``buf`` (writable
        contiguous, e.g. numpy) is reduced in place.  ``graph_data`` /
        ``pair_offsets`` / ``stats`` are int32/int32/float64 numpy arrays.
        Returns the raw C return code (0 ok / 1 timeout / 2 closed ...)."""
        mv = memoryview(buf)
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf))
        return self._libref.kf_engine_all_reduce(
            self._h, peers_csv.encode(), addr, mv.nbytes, elem_size,
            dtype_code, op_code,
            graph_data.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pair_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_pairs, tag.encode(), hash_mode, chunk_size, timeout,
            max_threads,
            stats.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )

    def ingress_totals(self) -> dict:
        return self._counter_totals(self._libref.kf_host_ingress_snapshot)

    def egress_totals(self) -> dict:
        return self._counter_totals(self._libref.kf_host_egress_snapshot)

    def _counter_totals(self, snapshot_fn) -> dict:
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = snapshot_fn(self._h, buf, cap)
            if n >= 0:
                break
            cap = -n + 1
        out = {}
        for line in buf.value.decode().splitlines():
            src, _, num = line.rpartition(" ")
            if src:
                out[src] = int(num)
        return out

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
