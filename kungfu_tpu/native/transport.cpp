// Native host-side message transport — the C++ rchannel equivalent.
//
// Wire-compatible with kungfu_tpu/comm/host.py (little-endian framing:
//   magic u32 | token u32 | conn_type u8 | src_len u16 | src
//   | name_len u16 | name | payload_len u32 | payload
// ), so a native channel and a Python channel interoperate freely.
// This is the TPU build's analog of the reference's Go transport
// (srcs/go/rchannel/{connection,client,server,handler}): typed named
// messages over TCP, rendezvous-by-name receive queues keyed by the
// cluster-version token (fencing, connection.go:28-47,77-87), pooled
// per-peer sender connections (client/connection_pool.go), 500x200ms
// connect retries (config.go:16-18), and ping echo (handler/ping.go).
//
// Exposed as a flat C API consumed via ctypes (no pybind11 in this
// environment); see kungfu_tpu/native/transport.py.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include <cerrno>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// Timed condition waits pick their clock per build: libstdc++ lowers
// steady_clock waits to pthread_cond_clockwait, which GCC 10's TSan does
// NOT intercept — the hidden unlock/relock inside the wait corrupts
// TSan's lock-ownership model and floods the run with bogus "double
// lock" / missing happens-before reports.  Sanitizer builds therefore
// wait on the (intercepted) system clock; production keeps the
// jump-proof steady clock.
#if defined(__SANITIZE_THREAD__)
using wait_clock = std::chrono::system_clock;
#else
using wait_clock = std::chrono::steady_clock;
#endif

constexpr uint32_t kMagic = 0x4B465450;  // "KFTP"
constexpr int kConnPing = 1;
constexpr int kConnControl = 2;
constexpr int kConnCollective = 3;
constexpr int kConnPeerToPeer = 4;

// framing sanity limits: the wire is unauthenticated, so a u32 length
// from a stray/hostile connection must not drive a near-4 GiB allocation
// (std::bad_alloc in a stream thread would std::terminate the worker).
// 3 GiB admits any realistic single blob (a ~700M-param f32 model);
// SENDERS enforce the same bound loudly (error, not a silent remote
// connection drop), keeping the failure next to its cause.
constexpr uint32_t kMaxFrame = 3u << 30;  // shared with comm/host.py MAX_FRAME
constexpr uint16_t kMaxMetaLen = 4096;    // src / name fields

// callback: return 0 if consumed, nonzero to fall through to the queue
using msg_cb = int (*)(const char *name, const uint8_t *payload,
                       uint32_t len, const char *src);

struct Msg {
    uint32_t token = 0;
    uint8_t conn_type = 0;
    std::string src;
    std::string name;
    std::string payload;
};

bool read_exact(int fd, void *buf, size_t n) {
    auto *p = static_cast<char *>(buf);
    while (n > 0) {
        ssize_t r = ::read(fd, p, n);
        if (r <= 0) { return false; }
        p += r;
        n -= static_cast<size_t>(r);
    }
    return true;
}

bool write_all(int fd, const void *buf, size_t n) {
    const auto *p = static_cast<const char *>(buf);
    while (n > 0) {
        ssize_t r = ::write(fd, p, n);
        if (r <= 0) { return false; }
        p += r;
        n -= static_cast<size_t>(r);
    }
    return true;
}

void put_u16(std::string &out, uint16_t v) {
    char b[2] = {static_cast<char>(v & 0xff), static_cast<char>(v >> 8)};
    out.append(b, 2);
}

void put_u32(std::string &out, uint32_t v) {
    char b[4];
    for (int i = 0; i < 4; ++i) { b[i] = static_cast<char>((v >> (8 * i)) & 0xff); }
    out.append(b, 4);
}

uint16_t get_u16(const uint8_t *p) {
    return static_cast<uint16_t>(p[0] | (p[1] << 8));
}

uint32_t get_u32(const uint8_t *p) {
    return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
}

std::string encode_head(uint32_t token, uint8_t conn_type, const std::string &src,
                        const std::string &name, uint32_t payload_len) {
    std::string out;
    out.reserve(17 + src.size() + name.size());
    put_u32(out, kMagic);
    put_u32(out, token);
    out.push_back(static_cast<char>(conn_type));
    put_u16(out, static_cast<uint16_t>(src.size()));
    out.append(src);
    put_u16(out, static_cast<uint16_t>(name.size()));
    out.append(name);
    put_u32(out, payload_len);
    return out;
}

std::string encode_msg(uint32_t token, uint8_t conn_type, const std::string &src,
                       const std::string &name, const uint8_t *payload,
                       uint32_t payload_len) {
    std::string out = encode_head(token, conn_type, src, name, payload_len);
    if (payload_len > 0) { out.append(reinterpret_cast<const char *>(payload), payload_len); }
    return out;
}

// gather-write header + payload without staging them into one buffer (the
// payload copy dominated send cost for MB-scale gradient chunks)
bool writev_all(int fd, const void *head, size_t head_n, const void *payload,
                size_t payload_n) {
    struct iovec iov[2];
    iov[0].iov_base = const_cast<void *>(head);
    iov[0].iov_len = head_n;
    iov[1].iov_base = const_cast<void *>(payload);
    iov[1].iov_len = payload_n;
    int iovcnt = payload_n > 0 ? 2 : 1;
    struct iovec *cur = iov;
    while (iovcnt > 0) {
        ssize_t w = ::writev(fd, cur, iovcnt);
        if (w < 0) {
            if (errno == EINTR) { continue; }
            return false;
        }
        size_t n = static_cast<size_t>(w);
        while (iovcnt > 0 && n >= cur->iov_len) {
            n -= cur->iov_len;
            ++cur;
            --iovcnt;
        }
        if (iovcnt > 0 && n > 0) {
            cur->iov_base = static_cast<char *>(cur->iov_base) + n;
            cur->iov_len -= n;
        }
    }
    return true;
}

// header through payload_len; the payload itself is read separately so
// the stream loop can route it straight into a registered receive buffer
bool decode_head(int fd, Msg &m, uint32_t &payload_len) {
    uint8_t head[11];
    if (!read_exact(fd, head, sizeof(head))) { return false; }
    if (get_u32(head) != kMagic) { return false; }
    m.token = get_u32(head + 4);
    m.conn_type = head[8];
    uint16_t src_len = get_u16(head + 9);
    if (src_len > kMaxMetaLen) { return false; }
    m.src.resize(src_len);
    if (src_len && !read_exact(fd, &m.src[0], src_len)) { return false; }
    uint8_t nl[2];
    if (!read_exact(fd, nl, 2)) { return false; }
    uint16_t name_len = get_u16(nl);
    if (name_len > kMaxMetaLen) { return false; }
    m.name.resize(name_len);
    if (name_len && !read_exact(fd, &m.name[0], name_len)) { return false; }
    uint8_t pl[4];
    if (!read_exact(fd, pl, 4)) { return false; }
    payload_len = get_u32(pl);
    if (payload_len > kMaxFrame) { return false; }
    return true;
}

bool decode_msg(int fd, Msg &m) {
    uint32_t payload_len = 0;
    if (!decode_head(fd, m, payload_len)) { return false; }
    m.payload.resize(payload_len);
    if (payload_len && !read_exact(fd, &m.payload[0], payload_len)) { return false; }
    return true;
}

bool split_peer(const std::string &peer, std::string &host, uint16_t &port) {
    auto pos = peer.rfind(':');
    if (pos == std::string::npos) { return false; }
    host = peer.substr(0, pos);
    long p = ::strtol(peer.c_str() + pos + 1, nullptr, 10);
    if (p <= 0 || p > 65535) { return false; }
    port = static_cast<uint16_t>(p);
    return true;
}

// colocated peers talk over a unix domain socket (reference: sockfile
// /tmp/kungfu-run-<port>.sock, plan/addr.go:24; UseUnixSock=true const).
// Keyed by host AND port: loopback-alias multi-host simulations give the
// same port to one worker on every host, so port alone would alias peers.
// Sockfiles live in a per-uid mode-0700 directory (not world-writable
// /tmp directly) so another local user can neither squat nor intercept;
// must stay in lockstep with kungfu_tpu/comm/host.py unix_sock_path.
// "" = no safe directory available (another user pre-created it, say);
// callers then skip the unix listener / fall back to TCP
std::string unix_sock_dir() {
    const char *env = ::getenv("KF_SOCK_DIR");
    std::string dir =
        env != nullptr && env[0] != '\0'
            ? std::string(env)
            : "/tmp/kf-tpu-" + std::to_string(::getuid());
    ::mkdir(dir.c_str(), 0700);
    // an existing dir must actually be OURS and private — mkdir's EEXIST
    // says nothing about who owns it (a squatter could pre-create it 0777
    // and then swap sockfiles under us)
    struct stat st;
    if (::lstat(dir.c_str(), &st) != 0 || !S_ISDIR(st.st_mode) ||
        st.st_uid != ::getuid() || (st.st_mode & 0077) != 0) {
        return "";
    }
    return dir;
}

std::string unix_sock_path(const std::string &host, uint16_t port) {
    std::string dir = unix_sock_dir();
    if (dir.empty()) { return ""; }
    return dir + "/" + host + "-" + std::to_string(port) + ".sock";
}

// deep socket buffers: a sender must be able to dump a full default
// chunk (1 MiB) and move on instead of context-switching every ~208 KiB
// (the kernel default) while the single-core receiver drains
void set_deep_buffers(int fd) {
    int sz = 4 << 20;
    ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &sz, sizeof(sz));
    ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &sz, sizeof(sz));
}

int connect_unix_once(const std::string &path, double timeout_s) {
    if (path.empty()) { return -1; }
    int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) { return -1; }
    set_deep_buffers(fd);
    if (timeout_s > 0) {
        struct timeval tv;
        tv.tv_sec = static_cast<long>(timeout_s);
        tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    }
    struct sockaddr_un addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    if (path.size() >= sizeof(addr.sun_path)) {
        ::close(fd);
        return -1;
    }
    std::strncpy(addr.sun_path, path.c_str(), sizeof(addr.sun_path) - 1);
    if (::connect(fd, reinterpret_cast<struct sockaddr *>(&addr), sizeof(addr)) != 0) {
        ::close(fd);
        return -1;
    }
    return fd;
}

int connect_once(const std::string &host, uint16_t port, double timeout_s) {
    // peer specs may carry hostnames, not just dotted quads (the Python
    // backend resolves via create_connection) — use getaddrinfo
    struct addrinfo hints;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo *res = nullptr;
    if (::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res) != 0 ||
        res == nullptr) {
        return -1;
    }
    int fd = -1;
    for (struct addrinfo *ai = res; ai != nullptr; ai = ai->ai_next) {
        fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) { continue; }
        if (timeout_s > 0) {
            struct timeval tv;
            tv.tv_sec = static_cast<long>(timeout_s);
            tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
            ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
            ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        }
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) { break; }
        ::close(fd);
        fd = -1;
    }
    ::freeaddrinfo(res);
    if (fd < 0) { return -1; }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    set_deep_buffers(fd);
    return fd;
}

struct QueueKey {
    uint8_t conn_type;
    std::string src;
    std::string name;
    uint32_t token;  // 0 for non-collective
    bool operator<(const QueueKey &o) const {
        if (conn_type != o.conn_type) { return conn_type < o.conn_type; }
        if (src != o.src) { return src < o.src; }
        if (name != o.name) { return name < o.name; }
        return token < o.token;
    }
};

struct PoolEntry {
    std::mutex mu;      // serializes senders; held across connect retries
    std::mutex fd_mu;   // guards fd open/close handoff; never held long
    int fd_ = -1;       // guarded_by(fd_mu)
    // ::close happens only under fd_mu (or in the destructor, when the
    // last shared_ptr holder is by construction the only thread left);
    // reset_connections only ever shutdown()s under fd_mu, so it can
    // neither race a sender's close nor hit a kernel-recycled fd number
    ~PoolEntry() {
        if (fd_ >= 0) { ::close(fd_); }
    }
    void retire_fd() {
        std::lock_guard<std::mutex> lk(fd_mu);
        if (fd_ >= 0) {
            ::close(fd_);
            fd_ = -1;
        }
    }
    void install_fd(int new_fd) {
        std::lock_guard<std::mutex> lk(fd_mu);
        fd_ = new_fd;
    }
};

struct ConnSlot {
    int stream_fd_ = -1;  // guarded_by(conns_mu_)  (writes; the stream
                          // loop reads its own fd lock-free by design)
    std::thread thread;
    std::atomic<bool> done{false};
};

// a registered zero-copy receive destination (the reference's
// RecvInto/WaitRecvBuf, handler/collective.go:34-65, minus the wire flag:
// registration is receiver-side only, so the format stays compatible).
// Owned by the recv_into stack frame; the map holds a borrowed pointer.
struct RegBuf {
    uint8_t *buf;
    uint32_t cap;
    uint32_t got = 0;
    // 0 waiting, 1 filled, 2 failed (conn dropped mid-read), 3 claimed
    // (stream thread is writing into buf — the owner must not return).
    // While the RegBuf is REACHABLE through the regbufs_ map, state
    // transitions happen under q_mu_; once deregistered it is owned by
    // a single frame again.
    int state = 0;  // guarded_by(q_mu_)
};

class Channel {
  public:
    Channel(std::string self_spec, const std::string &bind_host, uint16_t port,
            uint32_t token, bool use_unix)
        : self_(std::move(self_spec)), token_(token), use_unix_(use_unix) {
        auto pos = self_.rfind(':');
        self_host_ = pos == std::string::npos ? self_ : self_.substr(0, pos);
        listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
        if (listen_fd_ < 0) { return; }
        int one = 1;
        ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
        struct sockaddr_in addr;
        std::memset(&addr, 0, sizeof(addr));
        addr.sin_family = AF_INET;
        addr.sin_port = htons(port);
        if (bind_host.empty() || bind_host == "0.0.0.0") {
            addr.sin_addr.s_addr = INADDR_ANY;
        } else if (::inet_pton(AF_INET, bind_host.c_str(), &addr.sin_addr) != 1) {
            ::close(listen_fd_);
            listen_fd_ = -1;
            return;
        }
        if (::bind(listen_fd_, reinterpret_cast<struct sockaddr *>(&addr),
                   sizeof(addr)) != 0 ||
            ::listen(listen_fd_, 128) != 0) {
            ::close(listen_fd_);
            listen_fd_ = -1;
            return;
        }
        if (use_unix_) {
            // composed server: a second listener on the colocated-peer
            // sockfile (reference runs TCP and unix listeners together,
            // rchannel/server/composed)
            unix_path_ = unix_sock_path(self_host_, port);
            if (unix_path_.empty()) { use_unix_ = false; }
        }
        // close_all() wakes blocked accept()s through this pipe: the
        // shutdown(listen_fd) trick only works for TCP listeners — a
        // blocked accept on an AF_UNIX listener is NOT woken by
        // shutdown on Linux, which left close_all() hanging forever
        // whenever the unix listener was idle (found by the TSan churn
        // stress).  accept loops poll {listener, wake_pipe} instead.
        if (::pipe(wake_pipe_) != 0) { wake_pipe_[0] = wake_pipe_[1] = -1; }
        if (use_unix_) {
            ::unlink(unix_path_.c_str());
            unix_listen_fd_ = ::socket(AF_UNIX, SOCK_STREAM, 0);
            if (unix_listen_fd_ >= 0) {
                struct sockaddr_un ua;
                std::memset(&ua, 0, sizeof(ua));
                ua.sun_family = AF_UNIX;
                std::strncpy(ua.sun_path, unix_path_.c_str(), sizeof(ua.sun_path) - 1);
                if (::bind(unix_listen_fd_, reinterpret_cast<struct sockaddr *>(&ua),
                           sizeof(ua)) != 0 ||
                    ::listen(unix_listen_fd_, 128) != 0) {
                    ::close(unix_listen_fd_);
                    unix_listen_fd_ = -1;  // TCP-only; not fatal
                }
            }
        }
        running_ = true;
        accept_thread_ = std::thread([this] { accept_loop(listen_fd_, true); });
        if (unix_listen_fd_ >= 0) {
            unix_accept_thread_ =
                std::thread([this] { accept_loop(unix_listen_fd_, false); });
        }
    }

    bool ok() const { return listen_fd_ >= 0; }

    // RAII in-flight marker; declare FIRST in an entry point so its
    // release (and the close_all wakeup) runs after every lock is gone.
    // The count changes ONLY under q_mu_ — the same mutex close_all's
    // drain predicate evaluates under — so (a) an entry that raced past
    // the predicate load cannot be missed, and (b) the releasing thread
    // cannot touch a freed channel: while it holds q_mu_ for the
    // decrement, close_all is still inside its cv_ wait.  Entries are
    // REFUSED once running_ is false (`ok` = false; callers return
    // their closed status) — a late send must not dial out and install
    // fresh pool fds on a channel being torn down.
    struct ApiGuard {
        Channel *ch;
        bool ok;
        // force=true: count the entry even while closing (never refuse)
        // — for calls whose CLEANUP contract must hold during the close
        // window (recv_cancel: a registration may still be claimed by a
        // stream thread that close_all has not joined yet)
        explicit ApiGuard(Channel *c, bool force = false) : ch(c), ok(false) {
            std::lock_guard<std::mutex> lk(ch->q_mu_);
            if (!force && !ch->running_.load()) { return; }
            ++ch->api_inflight_;
            ok = true;
        }
        ~ApiGuard() {
            if (!ok) { return; }
            std::lock_guard<std::mutex> lk(ch->q_mu_);
            if (--ch->api_inflight_ == 0) { ch->cv_.notify_all(); }
        }
    };

    ~Channel() { close_all(); }

    void close_all() {
        {
            // running_ flips under q_mu_ and the wakeup is sent under it
            // too, so a receiver that checked running_ and is about to
            // wait cannot miss the shutdown notification
            std::lock_guard<std::mutex> lk(q_mu_);
            if (!running_.exchange(false)) {
                // never started or already closed; still reap a half-open fd
                if (listen_fd_ >= 0) { ::close(listen_fd_); listen_fd_ = -1; }
                return;
            }
            cv_.notify_all();
        }
        // wake the accept loops (pipe write covers the AF_UNIX listener,
        // which shutdown() does not wake; shutdown stays as belt and
        // braces for the TCP one), then wait until both threads have
        // exited so the loop can never accept() on an fd number the
        // kernel recycled for another socket
        if (wake_pipe_[1] >= 0) {
            char one = 1;
            (void)!::write(wake_pipe_[1], &one, 1);
        }
        ::shutdown(listen_fd_, SHUT_RDWR);
        if (unix_listen_fd_ >= 0) { ::shutdown(unix_listen_fd_, SHUT_RDWR); }
        if (accept_thread_.joinable()) { accept_thread_.join(); }
        if (unix_accept_thread_.joinable()) { unix_accept_thread_.join(); }
        ::close(listen_fd_);
        if (unix_listen_fd_ >= 0) {
            ::close(unix_listen_fd_);
            ::unlink(unix_path_.c_str());
            unix_listen_fd_ = -1;
        }
        {
            std::lock_guard<std::mutex> lk(conns_mu_);
            for (auto &slot : conns_) {
                if (slot->stream_fd_ >= 0) { ::shutdown(slot->stream_fd_, SHUT_RDWR); }
            }
        }
        // stream loops close their own fds on exit; join them all.
        // After the joins every thread that could touch conns_ (the
        // accept loops and the stream loops themselves) has exited, so
        // the clear is provably single-threaded — lock-free by design:
        for (auto &slot : conns_) {
            if (slot->thread.joinable()) { slot->thread.join(); }
        }
        conns_.clear();  // kflint: allow(lock-discipline)
        reset_connections_impl();  // running_ is false; the gated public
        // entry would refuse, but the pool must still be torn down
        listen_fd_ = -1;
        for (int i = 0; i < 2; ++i) {
            if (wake_pipe_[i] >= 0) {
                ::close(wake_pipe_[i]);
                wake_pipe_[i] = -1;
            }
        }
        // a blocked receiver woke with rc=2 (closed); wait until every
        // recv call AND every other in-flight API entry has actually
        // left before the caller may delete us
        std::unique_lock<std::mutex> lk(q_mu_);
        while (recv_inflight_ != 0 || api_inflight_ != 0) {
            if (cv_.wait_until(lk, wait_clock::now() +
                                       std::chrono::milliseconds(200)) ==
                std::cv_status::timeout) {
                // re-sweep: shut down any pool fd a racing send managed
                // to install anyway, so its blocked writev unblocks and
                // the in-flight call can drain
                lk.unlock();
                reset_connections_impl();
                lk.lock();
            }
        }
    }

    void set_token(uint32_t token) {
        ApiGuard api{this};
        if (!api.ok) { return; }
        std::lock_guard<std::mutex> lk(q_mu_);
        token_ = token;
        for (auto it = queues_.begin(); it != queues_.end();) {
            if (it->first.conn_type == kConnCollective && it->first.token < token) {
                it = queues_.erase(it);
            } else {
                ++it;
            }
        }
    }

    uint32_t token() const { return token_.load(); }

    void set_control_cb(msg_cb cb) { control_cb_ = cb; }
    void set_p2p_cb(msg_cb cb) { p2p_cb_ = cb; }

    // 0 ok, -1 unreachable, -3 payload over kMaxFrame
    int send(const std::string &peer, const std::string &name,
             const uint8_t *payload, uint32_t len, int conn_type, int retries) {
        ApiGuard api{this};
        if (!api.ok) { return -1; }  // closed: unreachable by definition
        if (len > kMaxFrame) { return -3; }
        std::string host;
        uint16_t port = 0;
        if (!split_peer(peer, host, port)) { return -1; }
        {
            std::lock_guard<std::mutex> lk(stats_mu_);
            egress_[peer] += len;
        }
        // header staged separately; the payload goes straight from the
        // caller's buffer to the kernel via writev (no MB-scale memcpy)
        std::string head = encode_head(token_.load(), static_cast<uint8_t>(conn_type),
                                       self_, name, len);
        std::shared_ptr<PoolEntry> entry;
        {
            std::lock_guard<std::mutex> lk(pool_mu_);
            auto &slot = pool_[peer];
            if (!slot) { slot = std::make_shared<PoolEntry>(); }
            entry = slot;
        }
        std::lock_guard<std::mutex> lk(entry->mu);
        // a connect that finishes after close_all's pool sweep must not
        // install a socket nothing will ever shut down (the close drain
        // would then hang behind a writev blocked on backpressure)
        auto install_open = [&](int fd) -> bool {
            if (!running_.load()) { ::close(fd); return false; }
            entry->install_fd(fd);
            return true;
        };
        if (entry->fd_ < 0) {
            int fd = connect_retry(host, port, retries);
            if (fd < 0 || !install_open(fd)) { return -1; }
        }
        if (!writev_all(entry->fd_, head.data(), head.size(), payload, len)) {
            // stale pooled socket (peer restarted): reconnect once.
            // retire before the (potentially long) reconnect so a
            // concurrent reset_connections sees fd=-1, not a dead number
            entry->retire_fd();
            int fd = connect_retry(host, port, retries);
            if (fd < 0 || !install_open(fd)) { return -1; }
            if (!writev_all(entry->fd_, head.data(), head.size(), payload, len)) {
                entry->retire_fd();
                return -1;
            }
        }
        return 0;
    }

    // 0 ok (out/out_len set, caller frees), 1 timeout, 2 closed.
    // timeout_s < 0 means wait forever (a huge finite value would
    // overflow duration_cast into a deadline in the past).
    int recv(const std::string &src, const std::string &name, int conn_type,
             double timeout_s, uint8_t **out, uint32_t *out_len) {
        QueueKey key{static_cast<uint8_t>(conn_type), src, name,
                     conn_type == kConnCollective ? token_.load() : 0};
        const bool forever = timeout_s < 0;
        std::unique_lock<std::mutex> lk(q_mu_);
        // close_all() blocks on this counter before the channel is freed
        ++recv_inflight_;
        struct Guard {
            Channel *ch;
            ~Guard() {
                if (--ch->recv_inflight_ == 0) { ch->cv_.notify_all(); }
            }
        } guard{this};
        auto deadline =
            wait_clock::now() +
            (forever ? wait_clock::duration::zero()
                     : std::chrono::duration_cast<wait_clock::duration>(
                           std::chrono::duration<double>(timeout_s)));
        for (;;) {
            auto it = queues_.find(key);
            if (it != queues_.end() && !it->second.empty()) {
                std::string payload = std::move(it->second.front());
                it->second.pop_front();
                // copy outside q_mu_: a multi-MB p2p blob must not
                // head-of-line block dispatch and every other recv
                lk.unlock();
                *out_len = static_cast<uint32_t>(payload.size());
                *out = static_cast<uint8_t *>(::malloc(payload.size() ? payload.size() : 1));
                std::memcpy(*out, payload.data(), payload.size());
                lk.lock();  // Guard's decrement runs under q_mu_
                return 0;
            }
            if (!running_.load()) { return 2; }
            if (forever) {
                cv_.wait(lk);
            } else if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
                return 1;
            }
        }
    }

    // Pre-register a receive buffer for (src, name): the stream thread
    // writes the payload straight into rb->buf on arrival (zero-copy),
    // BEFORE the caller blocks in recv_await — so a sender that races
    // ahead of the receiver still lands in place instead of detouring
    // through the queue (allocation + two copies).  If a matching payload
    // is already queued it is consumed immediately (rb->state = 1).
    // 0 ok, 2 closed, -2 queued-size mismatch (payload left queued),
    // -3 duplicate registration for the key.
    // The caller MUST follow up with recv_await or recv_cancel on the
    // same rb — the map holds a raw pointer into the caller's frame.
    int recv_register(const std::string &src, const std::string &name,
                      int conn_type, RegBuf *rb) {
        ApiGuard api{this};
        if (!api.ok) { return 2; }  // closed
        QueueKey key{static_cast<uint8_t>(conn_type), src, name,
                     conn_type == kConnCollective ? token_.load() : 0};
        std::unique_lock<std::mutex> lk(q_mu_);
        if (!running_.load()) { return 2; }
        auto it = queues_.find(key);
        if (it != queues_.end() && !it->second.empty()) {
            if (it->second.front().size() != rb->cap) { return -2; }
            std::string payload = std::move(it->second.front());
            it->second.pop_front();
            // copy outside q_mu_ (an MB-scale memcpy under the global
            // queue lock would stall every stream thread); rb is not in
            // the map, so no other thread can touch it — single-owner
            // writes, deliberately outside the lock:
            lk.unlock();
            std::memcpy(rb->buf, payload.data(), payload.size());
            rb->got = rb->cap;
            rb->state = 1;  // kflint: allow(lock-discipline)
            return 0;
        }
        if (!regbufs_.emplace(key, rb).second) { return -3; }
        return 0;
    }

    // Abandon a registration made by recv_register (error-path cleanup).
    // Blocks while the stream thread holds a claim on the buffer — after
    // return, no live pointer to rb remains anywhere in the channel.
    void recv_cancel(const std::string &src, const std::string &name,
                     int conn_type, RegBuf *rb) {
        // forced: even mid-close a stream thread may hold a claim on rb
        // (state 3) until close_all joins it — returning early would let
        // the caller free rb under that live pointer
        ApiGuard api{this, /*force=*/true};
        QueueKey key{static_cast<uint8_t>(conn_type), src, name,
                     conn_type == kConnCollective ? token_.load() : 0};
        std::unique_lock<std::mutex> lk(q_mu_);
        while (rb->state == 3) { cv_.wait(lk); }
        auto it = regbufs_.find(key);
        if (it != regbufs_.end() && it->second == rb) { regbufs_.erase(it); }
    }

    // Wait for a buffer registered with recv_register to fill.
    // 0 ok, 1 timeout, 2 closed, -2 queued-size mismatch.  On ANY return
    // the registration is gone (no dangling pointer).
    int recv_await(const std::string &src, const std::string &name,
                   int conn_type, double timeout_s, RegBuf *rb,
                   uint32_t *got) {
        QueueKey key{static_cast<uint8_t>(conn_type), src, name,
                     conn_type == kConnCollective ? token_.load() : 0};
        const bool forever = timeout_s < 0;
        std::unique_lock<std::mutex> lk(q_mu_);
        ++recv_inflight_;
        struct Guard {
            Channel *ch;
            ~Guard() {
                if (--ch->recv_inflight_ == 0) { ch->cv_.notify_all(); }
            }
        } guard{this};
        auto deadline =
            wait_clock::now() +
            (forever ? wait_clock::duration::zero()
                     : std::chrono::duration_cast<wait_clock::duration>(
                           std::chrono::duration<double>(timeout_s)));
        auto deregister = [&] {
            auto it = regbufs_.find(key);
            if (it != regbufs_.end() && it->second == rb) { regbufs_.erase(it); }
        };
        for (;;) {
            // resolution order matters: while CLAIMED (state 3) the stream
            // thread is writing into buf and holds a pointer to the
            // caller's frame — nothing may return until the claim resolves
            if (rb->state == 1) {
                deregister();
                *got = rb->got;
                return 0;
            }
            if (rb->state == 2) {
                deregister();
                return 2;
            }
            if (rb->state == 0) {
                // a queued payload (arrived with a non-matching key state,
                // or a duplicate keyed send) wins over waiting
                auto it = queues_.find(key);
                if (it != queues_.end() && !it->second.empty()) {
                    deregister();
                    if (it->second.front().size() != rb->cap) { return -2; }
                    std::string payload = std::move(it->second.front());
                    it->second.pop_front();
                    lk.unlock();
                    std::memcpy(rb->buf, payload.data(), payload.size());
                    lk.lock();
                    *got = rb->cap;
                    return 0;
                }
                if (!running_.load()) {
                    deregister();
                    return 2;
                }
            }
            if (forever || rb->state == 3) {
                cv_.wait(lk);
            } else if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
                if (rb->state == 0) {
                    deregister();
                    return 1;
                }
            }
        }
    }

    // Zero-copy receive into a caller-owned buffer (the reference's
    // registered-buffer RecvInto, handler/collective.go:34-65).
    // 0 ok, 1 timeout, 2 closed, -2 size mismatch (payload left queued —
    // caller falls back to recv()).
    int recv_into(const std::string &src, const std::string &name,
                  int conn_type, double timeout_s, uint8_t *buf, uint32_t cap,
                  uint32_t *got) {
        QueueKey key{static_cast<uint8_t>(conn_type), src, name,
                     conn_type == kConnCollective ? token_.load() : 0};
        const bool forever = timeout_s < 0;
        std::unique_lock<std::mutex> lk(q_mu_);
        ++recv_inflight_;
        struct Guard {
            Channel *ch;
            ~Guard() {
                if (--ch->recv_inflight_ == 0) { ch->cv_.notify_all(); }
            }
        } guard{this};
        auto deadline =
            wait_clock::now() +
            (forever ? wait_clock::duration::zero()
                     : std::chrono::duration_cast<wait_clock::duration>(
                           std::chrono::duration<double>(timeout_s)));
        RegBuf rb{buf, cap};
        bool registered = false;
        auto deregister = [&] {
            if (registered) {
                auto it = regbufs_.find(key);
                if (it != regbufs_.end() && it->second == &rb) { regbufs_.erase(it); }
                registered = false;
            }
        };
        for (;;) {
            // resolution order matters: while CLAIMED (state 3) the stream
            // thread is writing into buf and holds a pointer to this stack
            // frame — nothing (queue hits, timeouts, shutdown) may return
            // until the claim resolves to filled/failed.
            if (rb.state == 1) {
                deregister();
                *got = rb.got;
                return 0;
            }
            if (rb.state == 2) {
                // sender connection died mid-fill: the buffer holds a torn
                // payload and the message is gone — surface as closed
                deregister();
                return 2;
            }
            if (rb.state == 0) {
                // a queued payload (arrived before registration, or a
                // duplicate keyed send) wins over waiting
                auto it = queues_.find(key);
                if (it != queues_.end() && !it->second.empty()) {
                    deregister();
                    if (it->second.front().size() != cap) { return -2; }
                    std::string payload = std::move(it->second.front());
                    it->second.pop_front();
                    lk.unlock();
                    std::memcpy(buf, payload.data(), payload.size());
                    lk.lock();
                    *got = cap;
                    return 0;
                }
                if (!running_.load()) {
                    deregister();
                    return 2;
                }
                if (!registered) {
                    registered = regbufs_.emplace(key, &rb).second;
                }
            }
            if (forever || rb.state == 3) {
                cv_.wait(lk);
            } else if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
                if (rb.state == 0) {
                    deregister();
                    return 1;
                }
            }
        }
    }

    int ping(const std::string &peer, double timeout_s) {
        ApiGuard api{this};
        if (!api.ok) { return 1; }  // closed: not reachable
        std::string host;
        uint16_t port = 0;
        if (!split_peer(peer, host, port)) { return -1; }
        int fd = connect_once(host, port, timeout_s);
        if (fd < 0) { return -1; }
        std::string data =
            encode_msg(token_.load(), kConnPing, self_, "ping", nullptr, 0);
        Msg reply;
        int rc = (write_all(fd, data.data(), data.size()) && decode_msg(fd, reply))
                     ? 0
                     : -1;
        ::close(fd);
        return rc;
    }

    void reset_connections() {
        ApiGuard api{this};
        if (!api.ok) { return; }  // close_all resets the pool itself
        reset_connections_impl();
    }

    void reset_connections_impl() {
        std::vector<std::shared_ptr<PoolEntry>> entries;
        {
            std::lock_guard<std::mutex> lk(pool_mu_);
            for (auto &kv : pool_) { entries.push_back(kv.second); }
            pool_.clear();
        }
        // shutdown (not close) without taking the per-entry *send* lock:
        // a sender stuck retrying toward a dead peer must not block the
        // reset.  fd_mu makes the read-and-shutdown atomic against a
        // sender's close-and-replace, and the actual close stays with
        // the last shared_ptr holder (PoolEntry destructor)
        for (auto &e : entries) {
            std::lock_guard<std::mutex> lk(e->fd_mu);
            if (e->fd_ >= 0) { ::shutdown(e->fd_, SHUT_RDWR); }
        }
    }

    // newline-separated "src bytes" ingress totals; returns bytes written
    int ingress_snapshot(char *out, int cap) {
        ApiGuard api{this};
        if (!api.ok) { return 0; }
        return counter_snapshot(ingress_, out, cap);
    }

    // egress totals — counted in send() so traffic from the native engine
    // executor (which never crosses the python send wrapper) is included
    int egress_snapshot(char *out, int cap) {
        ApiGuard api{this};
        if (!api.ok) { return 0; }
        return counter_snapshot(egress_, out, cap);
    }

    int counter_snapshot(const std::map<std::string, uint64_t> &counters,
                         char *out, int cap) {
        std::string s;
        {
            std::lock_guard<std::mutex> lk(stats_mu_);
            for (auto &kv : counters) {
                s += kv.first + " " + std::to_string(kv.second) + "\n";
            }
        }
        int n = static_cast<int>(s.size());
        if (n >= cap) { return -n; }  // caller retries with bigger buffer
        std::memcpy(out, s.data(), s.size());
        out[n] = '\0';
        return n;
    }

  private:
    int connect_retry(const std::string &host, uint16_t port, int retries) {
        const bool colocated = use_unix_ && host == self_host_;
        for (int i = 0; i < retries && running_.load(); ++i) {
            if (colocated) {
                int fd = connect_unix_once(unix_sock_path(host, port), 10.0);
                if (fd >= 0) { return fd; }
                // fall through: peer may be TCP-only (e.g. python backend
                // with unix disabled)
            }
            int fd = connect_once(host, port, 10.0);
            if (fd >= 0) { return fd; }
            // reference: 500 x 200ms (config.go:16-18)
            std::this_thread::sleep_for(std::chrono::milliseconds(200));
        }
        return -1;
    }

    void accept_loop(int lfd, bool is_tcp) {
        while (running_.load()) {
            // poll before accept: the wake pipe is the portable shutdown
            // signal (a blocked accept on an AF_UNIX listener survives
            // shutdown()); the byte is never drained, so one write wakes
            // both accept loops
            struct pollfd pfds[2];
            pfds[0].fd = lfd;
            pfds[0].events = POLLIN;
            pfds[1].fd = wake_pipe_[0];
            pfds[1].events = POLLIN;
            int nfds = wake_pipe_[0] >= 0 ? 2 : 1;
            int pr = ::poll(pfds, nfds, wake_pipe_[0] >= 0 ? -1 : 200);
            if (!running_.load()) { return; }
            if (pr <= 0 || (pfds[0].revents & POLLIN) == 0) { continue; }
            int fd = ::accept(lfd, nullptr, nullptr);
            if (fd < 0) {
                if (!running_.load()) { return; }
                continue;
            }
            if (is_tcp) {
                int one = 1;
                ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            }
            set_deep_buffers(fd);
            {
                std::lock_guard<std::mutex> lk(conns_mu_);
                // reap finished connections so short-lived clients (pings
                // arrive on a fresh connection each) don't grow the
                // registry — their fds were closed by their stream loops
                for (auto it = conns_.begin(); it != conns_.end();) {
                    if ((*it)->done.load()) {
                        (*it)->thread.join();
                        it = conns_.erase(it);
                    } else {
                        ++it;
                    }
                }
                auto slot = std::make_shared<ConnSlot>();
                slot->stream_fd_ = fd;
                slot->thread = std::thread([this, slot] { stream_loop(slot.get()); });
                conns_.push_back(std::move(slot));
            }
        }
    }

    // one pooled client sends many messages per connection (reference
    // Stream(), handler.go:30-41); the stream loop owns its fd's close.
    // The close runs under conns_mu_ — the same lock close_all() holds
    // while shutdown()ing open fds — so a shutdown can never hit an fd
    // number the kernel has already recycled for an unrelated socket.
    void stream_loop(ConnSlot *slot) {
        // any exception (bad_alloc on a huge-but-legal frame, etc.) drops
        // THIS connection instead of std::terminate'ing the whole worker
        try {
            Msg m;
            uint32_t plen = 0;
            while (running_.load() && decode_head(slot->stream_fd_, m, plen)) {
                bool consumed = false;
                if (!read_payload(slot->stream_fd_, m, plen, consumed)) { break; }
                if (!consumed) { dispatch(m, slot->stream_fd_); }
            }
        } catch (...) {
        }
        {
            std::lock_guard<std::mutex> lk(conns_mu_);
            ::close(slot->stream_fd_);
            slot->stream_fd_ = -1;
        }
        // done flips only after the fd is retired; the accept loop joins
        // (reaps) exclusively done slots, so it never blocks on a thread
        // that is itself waiting for conns_mu_
        slot->done.store(true);
    }

    // read the payload off the socket — directly into a registered
    // receive buffer when one matches (zero-copy path: no allocation, no
    // queue hop, no malloc'd copy for the ctypes boundary), else into
    // m.payload for normal dispatch.  Runs on the stream thread.
    bool read_payload(int fd, Msg &m, uint32_t plen, bool &consumed) {
        consumed = false;
        // p2p registrations (the gossip pull path) key on token 0 — p2p
        // traffic is not epoch-fenced (matches recv/recv_into/QueueKey)
        if (m.conn_type == kConnCollective || m.conn_type == kConnPeerToPeer) {
            std::unique_lock<std::mutex> lk(q_mu_);
            if (m.conn_type != kConnCollective || m.token >= token_.load()) {
                auto it = regbufs_.find(QueueKey{
                    m.conn_type, m.src, m.name,
                    m.conn_type == kConnCollective ? m.token : 0});
                if (it != regbufs_.end() && it->second->state == 0 &&
                    it->second->cap == plen) {
                    RegBuf *rb = it->second;
                    rb->state = 3;  // claimed: owner must wait for us
                    lk.unlock();
                    bool ok = plen == 0 || read_exact(fd, rb->buf, plen);
                    lk.lock();
                    rb->got = plen;
                    rb->state = ok ? 1 : 2;
                    cv_.notify_all();
                    {
                        std::lock_guard<std::mutex> slk(stats_mu_);
                        ingress_[m.src] += plen;
                    }
                    consumed = true;
                    return ok;
                }
            }
        }
        m.payload.resize(plen);
        return plen == 0 || read_exact(fd, &m.payload[0], plen);
    }

    void dispatch(Msg &m, int fd) {
        {
            std::lock_guard<std::mutex> lk(stats_mu_);
            ingress_[m.src] += m.payload.size();
        }
        if (m.conn_type == kConnPing) {
            std::string reply =
                encode_msg(token_.load(), kConnPing, self_, m.name, nullptr, 0);
            write_all(fd, reply.data(), reply.size());
            return;
        }
        if (m.conn_type == kConnControl && control_cb_ != nullptr) {
            if (control_cb_(m.name.c_str(),
                            reinterpret_cast<const uint8_t *>(m.payload.data()),
                            static_cast<uint32_t>(m.payload.size()),
                            m.src.c_str()) == 0) {
                return;
            }
        }
        if (m.conn_type == kConnPeerToPeer && p2p_cb_ != nullptr &&
            m.name.rfind("req.", 0) == 0) {
            if (p2p_cb_(m.name.c_str(),
                        reinterpret_cast<const uint8_t *>(m.payload.data()),
                        static_cast<uint32_t>(m.payload.size()),
                        m.src.c_str()) == 0) {
                return;
            }
        }
        std::lock_guard<std::mutex> lk(q_mu_);
        uint32_t qtoken = 0;
        if (m.conn_type == kConnCollective) {
            // fencing: queue under the sender's epoch; a stale-epoch
            // arrival (older than current) can never be read — drop it.
            // A future-epoch arrival is preserved (the sender already
            // moved on and will not retry).
            if (m.token < token_.load()) { return; }
            qtoken = m.token;
        }
        queues_[QueueKey{m.conn_type, m.src, m.name, qtoken}].push_back(
            std::move(m.payload));
        cv_.notify_all();
    }

    std::string self_;
    std::string self_host_;
    std::atomic<uint32_t> token_;
    std::atomic<bool> running_{false};
    bool use_unix_ = false;
    int listen_fd_ = -1;
    int unix_listen_fd_ = -1;
    int wake_pipe_[2] = {-1, -1};  // close_all -> accept_loop wakeup
    std::string unix_path_;
    std::thread accept_thread_;
    std::thread unix_accept_thread_;

    std::mutex conns_mu_;
    std::vector<std::shared_ptr<ConnSlot>> conns_;  // guarded_by(conns_mu_)

    std::mutex q_mu_;
    std::condition_variable cv_;
    std::map<QueueKey, std::deque<std::string>> queues_;  // guarded_by(q_mu_)
    std::map<QueueKey, RegBuf *> regbufs_;  // guarded_by(q_mu_)  borrowed ptrs
    int recv_inflight_ = 0;  // guarded_by(q_mu_)
    // in-flight count for API entries NOT covered by recv_inflight_
    // (send / recv_register / recv_cancel / ping / ...): close_all()
    // drains BOTH before the caller may delete the channel — a thread
    // still inside send() while another thread closed the channel was
    // a use-after-free (gossip puller vs. peer teardown).  Guarded by
    // q_mu_ (see ApiGuard for why atomicity alone is not enough).
    int api_inflight_ = 0;  // guarded_by(q_mu_)

    std::mutex pool_mu_;
    std::map<std::string, std::shared_ptr<PoolEntry>> pool_;  // guarded_by(pool_mu_)

    std::mutex stats_mu_;
    std::map<std::string, uint64_t> ingress_;  // guarded_by(stats_mu_)
    std::map<std::string, uint64_t> egress_;   // guarded_by(stats_mu_)

    msg_cb control_cb_ = nullptr;
    msg_cb p2p_cb_ = nullptr;
};

// ---------------------------------------------------------------------
// Native graph-collective executor — the reference's runGraphs hot loop
// (srcs/go/kungfu/session/session.go:222-321) run entirely in C++: chunk
// split (np.array_split-compatible so python/native peers interoperate),
// chunk→graph-pair hash, recv/accumulate(send) reduce stage, broadcast
// stage.  Receives use the channel's registered-buffer path; accumulation
// calls the native reduce kernel (reduce.cpp, same .so).  One ctypes
// crossing per COLLECTIVE instead of per message.
// ---------------------------------------------------------------------

struct MeGraph {
    // me-centric adjacency of one (reduce, bcast) pair
    bool r_selfloop = false;
    std::vector<int32_t> r_prevs, r_nexts;
    bool b_selfloop = false;
    std::vector<int32_t> b_prevs, b_nexts;
};

uint64_t engine_name_hash(const std::string &name) {
    // must match kungfu_tpu.comm.engine.name_based_hash (sum of ord^2)
    uint64_t h = 0;
    for (unsigned char c : name) { h += uint64_t(c) * uint64_t(c); }
    return h;
}

}  // namespace

extern "C" {

// from reduce.cpp (same shared object)
int kf_transform2(void *dst, const void *src, int64_t n, int32_t dtype,
                  int32_t op);
}

namespace {

// returns 0 ok, 1 timeout, 2 closed, -1 bad args, -4 reduce error
int engine_run_chunk(Channel *ch, const std::vector<std::string> &peers,
                     const MeGraph &g, uint8_t *chunk, uint64_t chunk_bytes,
                     int64_t elems, int32_t dtype, int32_t op,
                     const std::string &tag, double timeout_s,
                     std::vector<uint8_t> &scratch) {
    const std::string rtag = tag + ".r";
    const std::string btag = tag + ".b";
    uint32_t got = 0;
    const bool have = g.r_selfloop;  // chunk already holds our contribution
    const size_t nprev = g.r_prevs.size();

    // pre-register reduce-phase receives before touching the wire: a
    // peer that sends before we get around to its recv lands straight in
    // its target buffer instead of detouring through the queue (an
    // allocation plus two full copies per miss).  Registration runs a
    // SLIDING WINDOW of kRegWindow buffers — high-fan-in graphs (a STAR
    // root at np=64) would otherwise hold O(fan_in * chunk) scratch;
    // the window keeps the zero-copy overlap with O(1) extra memory.
    constexpr size_t kRegWindow = 4;
    std::vector<RegBuf> rbs(nprev);
    std::vector<uint8_t *> tgt(nprev, nullptr);
    const size_t n_scratch = std::min(nprev, kRegWindow);
    if (scratch.size() < n_scratch * chunk_bytes) {
        scratch.resize(n_scratch * chunk_bytes);
    }
    std::vector<uint8_t *> free_slots;
    for (size_t s_i = 0; s_i < n_scratch; ++s_i) {
        free_slots.push_back(scratch.data() + s_i * chunk_bytes);
    }
    int rc = 0;
    size_t reg_hi = 0;  // prevs [await_i, reg_hi) are registered
    auto register_next = [&]() -> int {
        auto &rb = rbs[reg_hi];
        if (!have && reg_hi == 0) {
            tgt[reg_hi] = chunk;  // first contribution lands in place
        } else {
            tgt[reg_hi] = free_slots.back();
            free_slots.pop_back();
        }
        rb.buf = tgt[reg_hi];
        rb.cap = static_cast<uint32_t>(chunk_bytes);
        int r = ch->recv_register(peers[g.r_prevs[reg_hi]], rtag,
                                  kConnCollective, &rb);
        if (r == 0) { ++reg_hi; }
        return r;
    };
    auto cancel_tail = [&](size_t from) {
        // error path: every outstanding registration must be withdrawn
        // before the stack frame holding the RegBufs unwinds
        for (size_t j = from; j < reg_hi; ++j) {
            ch->recv_cancel(peers[g.r_prevs[j]], rtag, kConnCollective, &rbs[j]);
        }
    };
    while (reg_hi < nprev) {
        const bool needs_slot = have || reg_hi > 0;  // else lands in chunk
        if (needs_slot && free_slots.empty()) { break; }
        rc = register_next();
        if (rc != 0) {
            cancel_tail(0);
            return rc == -3 ? -1 : rc;
        }
    }
    for (size_t i = 0; i < nprev; ++i) {
        rc = ch->recv_await(peers[g.r_prevs[i]], rtag, kConnCollective,
                            timeout_s, &rbs[i], &got);
        if (rc != 0) {
            cancel_tail(i + 1);
            return rc;
        }
        if (tgt[i] != chunk) {
            if (kf_transform2(chunk, tgt[i], elems, dtype, op) != 0) {
                cancel_tail(i + 1);
                return -4;
            }
            free_slots.push_back(tgt[i]);  // slot drained, reusable
        }
        while (reg_hi < nprev && !free_slots.empty()) {
            rc = register_next();
            if (rc != 0) {
                cancel_tail(i + 1);
                return rc == -3 ? -1 : rc;
            }
        }
    }
    for (int32_t nxt : g.r_nexts) {
        if (ch->send(peers[nxt], rtag, chunk,
                     static_cast<uint32_t>(chunk_bytes), kConnCollective,
                     500) != 0) {
            return 2;
        }
    }
    // the broadcast receive reuses the chunk buffer, so it registers only
    // after the reduce sends complete (our bcast parent cannot have the
    // result earlier anyway — it transitively needs our contribution)
    if (!g.b_selfloop && !g.b_prevs.empty()) {
        rc = ch->recv_into(peers[g.b_prevs[0]], btag, kConnCollective,
                           timeout_s, chunk,
                           static_cast<uint32_t>(chunk_bytes), &got);
        if (rc != 0) { return rc; }
    }
    for (int32_t nxt : g.b_nexts) {
        if (ch->send(peers[nxt], btag, chunk,
                     static_cast<uint32_t>(chunk_bytes), kConnCollective,
                     500) != 0) {
            return 2;
        }
    }
    return 0;
}

}  // namespace

extern "C" {

void *kf_host_create(const char *self_spec, const char *bind_host,
                     uint32_t port, uint32_t token, int use_unix) {
    auto *ch = new Channel(self_spec, bind_host ? bind_host : "",
                           static_cast<uint16_t>(port), token, use_unix != 0);
    if (!ch->ok()) {
        delete ch;
        return nullptr;
    }
    return ch;
}

void kf_host_close(void *h) {
    auto *ch = static_cast<Channel *>(h);
    ch->close_all();
    delete ch;
}

void kf_host_set_token(void *h, uint32_t token) {
    static_cast<Channel *>(h)->set_token(token);
}

uint32_t kf_host_token(void *h) { return static_cast<Channel *>(h)->token(); }

int kf_host_send(void *h, const char *peer, const char *name,
                 const uint8_t *payload, uint32_t len, int conn_type,
                 int retries) {
    return static_cast<Channel *>(h)->send(peer, name, payload, len, conn_type,
                                           retries);
}

int kf_host_recv(void *h, const char *src, const char *name, int conn_type,
                 double timeout_s, uint8_t **out, uint32_t *out_len) {
    return static_cast<Channel *>(h)->recv(src, name, conn_type, timeout_s, out,
                                           out_len);
}

void kf_host_buf_free(uint8_t *p) { ::free(p); }

// 0 ok, 1 timeout, 2 closed, -2 size mismatch (payload queued; fall back
// to kf_host_recv)
int kf_host_recv_into(void *h, const char *src, const char *name,
                      int conn_type, double timeout_s, uint8_t *buf,
                      uint32_t cap, uint32_t *got) {
    return static_cast<Channel *>(h)->recv_into(src, name, conn_type,
                                                timeout_s, buf, cap, got);
}

// Staged zero-copy receive for request/response pulls: register the
// destination buffer BEFORE dispatching the request, so the response
// streams socket->buf even when it races the receiver (recv_into
// registers after the caller's send — a fast responder then detours
// through the queue, costing an alloc + two copies on a ~100 MiB blob).
// Returns an opaque handle for kf_host_recv_finish / kf_host_recv_abort,
// or null with *rc_out set: 2 closed, -2 queued-size-mismatch (payload
// left queued; fall back to kf_host_recv), -3 duplicate registration.
// rc_out 0 with a non-null handle may ALREADY be filled (a queued
// payload of the right size was consumed at register time) — finish
// resolves either way.  The buffer MUST stay alive and unwritten until
// finish/abort returns.
void *kf_host_recv_begin(void *h, const char *src, const char *name,
                         int conn_type, uint8_t *buf, uint32_t cap,
                         int *rc_out) {
    auto *rb = new RegBuf{buf, cap};
    int rc = static_cast<Channel *>(h)->recv_register(src, name, conn_type, rb);
    *rc_out = rc;
    if (rc != 0) {
        delete rb;
        return nullptr;
    }
    return rb;
}

// 0 ok (*got set), 1 timeout, 2 closed, -2 queued-size-mismatch.  The
// handle is consumed on every return (recv_await guarantees no live
// pointer remains in the channel).
int kf_host_recv_finish(void *h, const char *src, const char *name,
                        int conn_type, double timeout_s, void *rbp,
                        uint32_t *got) {
    auto *rb = static_cast<RegBuf *>(rbp);
    int rc = static_cast<Channel *>(h)->recv_await(src, name, conn_type,
                                                   timeout_s, rb, got);
    delete rb;
    return rc;
}

// Abandon a registration (e.g. the request send failed); consumes the
// handle after any in-flight claim on the buffer resolves.
void kf_host_recv_abort(void *h, const char *src, const char *name,
                        int conn_type, void *rbp) {
    auto *rb = static_cast<RegBuf *>(rbp);
    static_cast<Channel *>(h)->recv_cancel(src, name, conn_type, rb);
    delete rb;
}

int kf_host_ping(void *h, const char *peer, double timeout_s) {
    return static_cast<Channel *>(h)->ping(peer, timeout_s);
}

void kf_host_reset_connections(void *h) {
    static_cast<Channel *>(h)->reset_connections();
}

void kf_host_set_control_cb(void *h, msg_cb cb) {
    static_cast<Channel *>(h)->set_control_cb(cb);
}

void kf_host_set_p2p_cb(void *h, msg_cb cb) {
    static_cast<Channel *>(h)->set_p2p_cb(cb);
}

int kf_host_ingress_snapshot(void *h, char *out, int cap) {
    return static_cast<Channel *>(h)->ingress_snapshot(out, cap);
}

int kf_host_egress_snapshot(void *h, char *out, int cap) {
    return static_cast<Channel *>(h)->egress_snapshot(out, cap);
}

// Chunked graph allreduce over the channel, fully native (one ctypes
// crossing per collective).  buf is reduced IN PLACE.
//
//   peers_csv:    "host:port,..." in rank order
//   graph_data:   per pair [r_selfloop, n_rp, rp..., n_rn, rn...,
//                           b_selfloop, n_bp, bp..., n_bn, bn...] (i32),
//                 me-centric adjacency; pair_offsets[n_pairs+1] slices it
//   hash_mode:    0 = chunk-index round robin, 1 = name hash (shard.go)
//   stats_out:    [n_pairs*2] += (bytes, seconds) per pair (may be null)
//
// returns 0 ok, 1 timeout, 2 closed/unreachable, -1 bad args, -4 reduce
int kf_engine_all_reduce(void *h, const char *peers_csv, uint8_t *buf,
                         uint64_t nbytes, int64_t elem_size, int32_t dtype,
                         int32_t op, const int32_t *graph_data,
                         const int32_t *pair_offsets, int32_t n_pairs,
                         const char *tag, int32_t hash_mode,
                         uint64_t chunk_size, double timeout_s,
                         int32_t max_threads, double *stats_out) {
    auto *ch = static_cast<Channel *>(h);
    if (n_pairs <= 0 || elem_size <= 0 || nbytes % elem_size != 0) {
        return -1;
    }
    std::vector<std::string> peers;
    {
        std::string s(peers_csv);
        size_t pos = 0;
        while (pos <= s.size()) {
            size_t c = s.find(',', pos);
            if (c == std::string::npos) { c = s.size(); }
            if (c > pos) { peers.emplace_back(s.substr(pos, c - pos)); }
            pos = c + 1;
        }
    }
    std::vector<MeGraph> graphs(n_pairs);
    for (int32_t p = 0; p < n_pairs; ++p) {
        const int32_t *d = graph_data + pair_offsets[p];
        MeGraph &g = graphs[p];
        size_t i = 0;
        g.r_selfloop = d[i++] != 0;
        for (int32_t k = d[i++]; k > 0; --k) { g.r_prevs.push_back(d[i++]); }
        for (int32_t k = d[i++]; k > 0; --k) { g.r_nexts.push_back(d[i++]); }
        g.b_selfloop = d[i++] != 0;
        for (int32_t k = d[i++]; k > 0; --k) { g.b_prevs.push_back(d[i++]); }
        for (int32_t k = d[i++]; k > 0; --k) { g.b_nexts.push_back(d[i++]); }
    }

    // chunk boundaries must replicate np.array_split over ELEMENTS so
    // python-backend peers slice identically
    const uint64_t total_elems = nbytes / uint64_t(elem_size);
    uint64_t n_chunks = (nbytes + chunk_size - 1) / chunk_size;
    if (n_chunks == 0) { n_chunks = 1; }
    if (n_chunks > total_elems && total_elems > 0) { n_chunks = total_elems; }
    const uint64_t base = total_elems / n_chunks;
    const uint64_t rem = total_elems % n_chunks;

    std::mutex stats_mu;
    std::atomic<int> first_err{0};
    const std::string tag_s(tag);
    const uint64_t name_h = engine_name_hash(tag_s);

    auto run_chunk = [&](uint64_t ci, uint64_t elem_off, uint64_t elems,
                         std::vector<uint8_t> &scratch) {
        const int32_t gi = static_cast<int32_t>(
            (hash_mode == 1 ? name_h : ci) % uint64_t(n_pairs));
        uint8_t *cbuf = buf + elem_off * uint64_t(elem_size);
        const uint64_t cbytes = elems * uint64_t(elem_size);
        auto t0 = std::chrono::steady_clock::now();
        int rc = engine_run_chunk(ch, peers, graphs[gi], cbuf, cbytes,
                                  static_cast<int64_t>(elems), dtype, op,
                                  tag_s + ".c" + std::to_string(ci), timeout_s,
                                  scratch);
        if (rc != 0) {
            int expect = 0;
            first_err.compare_exchange_strong(expect, rc);
            return;
        }
        if (stats_out != nullptr) {
            double dt = std::chrono::duration<double>(
                            std::chrono::steady_clock::now() - t0)
                            .count();
            std::lock_guard<std::mutex> lk(stats_mu);
            stats_out[2 * gi] += double(cbytes);
            stats_out[2 * gi + 1] += dt;
        }
    };

    if (n_chunks == 1) {
        std::vector<uint8_t> scratch;
        run_chunk(0, 0, total_elems, scratch);
        return first_err.load();
    }
    const int nthreads = std::max(
        1, std::min<int>(max_threads > 0 ? max_threads : 8,
                         static_cast<int>(n_chunks)));
    std::atomic<uint64_t> next{0};
    std::vector<std::thread> workers;
    workers.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
        workers.emplace_back([&] {
            std::vector<uint8_t> scratch;
            for (;;) {
                uint64_t ci = next.fetch_add(1);
                if (ci >= n_chunks) { return; }
                uint64_t off = ci < rem ? ci * (base + 1)
                                        : rem * (base + 1) + (ci - rem) * base;
                uint64_t elems = ci < rem ? base + 1 : base;
                run_chunk(ci, off, elems, scratch);
            }
        });
    }
    for (auto &w : workers) { w.join(); }
    return first_err.load();
}

}  // extern "C"
