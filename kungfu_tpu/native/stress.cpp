// TSan stress driver for the native transport (transport.cpp).
//
// Exercises the paths the CHANGELOG fixed after the fact — teardown
// use-after-free (close_all racing in-flight send/recv) and the racing
// send hang — as a standalone, fully TSan-instrumented binary.
// (Instrumenting only the dlopen'd .so under an uninstrumented python
// is unsupported: the TSan runtime must be present at process start,
// which is why this is a binary and not a pytest plugin.)
//
// Build + run:   make -C kungfu_tpu/native stress && ./kfstress-tsan
// The pytest wrapper (tests/test_native_sanitize.py, -m slow) asserts
// exit code 0 and no "WARNING: ThreadSanitizer" on stderr.

#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void *kf_host_create(const char *self_spec, const char *bind_host,
                     uint32_t port, uint32_t token, int use_unix);
void kf_host_close(void *h);
void kf_host_set_token(void *h, uint32_t token);
int kf_host_send(void *h, const char *peer, const char *name,
                 const uint8_t *payload, uint32_t len, int conn_type,
                 int retries);
int kf_host_recv(void *h, const char *src, const char *name, int conn_type,
                 double timeout_s, uint8_t **out, uint32_t *out_len);
void kf_host_buf_free(uint8_t *p);
int kf_host_recv_into(void *h, const char *src, const char *name,
                      int conn_type, double timeout_s, uint8_t *buf,
                      uint32_t cap, uint32_t *got);
int kf_host_ping(void *h, const char *peer, double timeout_s);
void kf_host_reset_connections(void *h);
}

namespace {

constexpr int kConnCollective = 3;
constexpr int kConnPeerToPeer = 4;
constexpr uint32_t kMsgBytes = 8192;
constexpr int kMsgsPerThread = 12;

std::atomic<int> failures{0};

void fail(const char *what) {
    std::fprintf(stderr, "stress: FAIL %s\n", what);
    failures.fetch_add(1);
}

std::string spec(uint16_t port) {
    return "127.0.0.1:" + std::to_string(port);
}

void sender(void *ch, const std::string &peer, int tid, int conn_type) {
    std::vector<uint8_t> payload(kMsgBytes, static_cast<uint8_t>(tid));
    std::string name = "m" + std::to_string(tid);
    for (int i = 0; i < kMsgsPerThread; ++i) {
        if (kf_host_send(ch, peer.c_str(), name.c_str(), payload.data(),
                         kMsgBytes, conn_type, 50) != 0) {
            fail("send");
            return;
        }
    }
}

void receiver(void *ch, const std::string &src, int tid, int conn_type) {
    std::string name = "m" + std::to_string(tid);
    for (int i = 0; i < kMsgsPerThread; ++i) {
        if (i % 2 == 0) {
            uint8_t *out = nullptr;
            uint32_t n = 0;
            int rc = kf_host_recv(ch, src.c_str(), name.c_str(), conn_type,
                                  20.0, &out, &n);
            if (rc != 0 || n != kMsgBytes) {
                fail("recv");
                return;
            }
            kf_host_buf_free(out);
        } else {
            std::vector<uint8_t> buf(kMsgBytes);
            uint32_t got = 0;
            int rc = kf_host_recv_into(ch, src.c_str(), name.c_str(),
                                       conn_type, 20.0, buf.data(), kMsgBytes,
                                       &got);
            if (rc != 0 || got != kMsgBytes) {
                fail("recv_into");
                return;
            }
        }
    }
}

// late traffic toward a channel being closed: sends must fail cleanly
// (refused/unreachable), never crash or wedge the closing thread
void late_sender(void *ch, const std::string &peer, std::atomic<bool> *stop) {
    uint8_t b[64] = {0};
    while (!stop->load()) {
        kf_host_send(ch, peer.c_str(), "late", b, sizeof(b), kConnPeerToPeer, 1);
    }
}

// a receiver parked forever: close_all must wake it with rc=2 (closed)
void parked_receiver(void *ch, const std::string &src) {
    uint8_t *out = nullptr;
    uint32_t n = 0;
    int rc = kf_host_recv(ch, src.c_str(), "never", kConnPeerToPeer, -1.0,
                          &out, &n);
    if (rc == 0) { kf_host_buf_free(out); }
}

void run_round(int round, uint16_t port_a, uint16_t port_b) {
    const bool use_unix = round % 2 == 1;
    const std::string sa = spec(port_a), sb = spec(port_b);
    void *a = kf_host_create(sa.c_str(), "127.0.0.1", port_a, 0, use_unix);
    void *b = kf_host_create(sb.c_str(), "127.0.0.1", port_b, 0, use_unix);
    if (a == nullptr || b == nullptr) {
        fail("create");
        if (a != nullptr) { kf_host_close(a); }
        if (b != nullptr) { kf_host_close(b); }
        return;
    }

    std::vector<std::thread> ts;
    for (int t = 0; t < 4; ++t) {
        const int ct = t % 2 == 0 ? kConnCollective : kConnPeerToPeer;
        ts.emplace_back(sender, a, sb, t, ct);
        ts.emplace_back(receiver, b, sa, t, ct);
    }
    for (int t = 4; t < 6; ++t) {
        ts.emplace_back(sender, b, sa, t, kConnPeerToPeer);
        ts.emplace_back(receiver, a, sb, t, kConnPeerToPeer);
    }
    ts.emplace_back([&] {
        for (int i = 0; i < 4; ++i) {
            if (kf_host_ping(a, sb.c_str(), 5.0) != 0) { fail("ping"); }
            std::this_thread::sleep_for(std::chrono::milliseconds(5));
        }
    });
    // connection churn mid-traffic: pooled sender fds get shutdown()
    // under the senders' feet, forcing the stale-socket reconnect path
    ts.emplace_back([&] {
        for (int i = 0; i < 3; ++i) {
            std::this_thread::sleep_for(std::chrono::milliseconds(10));
            kf_host_reset_connections(a);
            kf_host_reset_connections(b);
        }
    });
    for (auto &t : ts) { t.join(); }

    // teardown race: close B under live late traffic + a parked recv
    std::atomic<bool> stop{false};
    std::thread late(late_sender, a, sb, &stop);
    std::thread parked(parked_receiver, b, sa);
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    kf_host_close(b);  // must drain in-flight API entries, wake the recv
    stop.store(true);
    late.join();
    parked.join();
    kf_host_close(a);
}

}  // namespace

int main(int argc, char **argv) {
    int rounds = argc > 1 ? std::atoi(argv[1]) : 4;
    // ports: keep clear of the runner/worker defaults and vary per pid
    // so parallel CI shards don't collide
    uint16_t base = static_cast<uint16_t>(42000 + (::getpid() % 500) * 16);
    for (int r = 0; r < rounds; ++r) {
        run_round(r, static_cast<uint16_t>(base + 2 * r),
                  static_cast<uint16_t>(base + 2 * r + 1));
        std::fprintf(stderr, "stress: round %d ok\n", r);
    }
    if (failures.load() != 0) {
        std::fprintf(stderr, "stress: %d failure(s)\n", failures.load());
        return 1;
    }
    std::fprintf(stderr, "stress: all rounds clean\n");
    return 0;
}
