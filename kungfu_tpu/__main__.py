"""``python -m kungfu_tpu`` launches the runner CLI.

Parity with the reference's embedded launcher (``python -m kungfu.cmd``
invokes the built-in ``kungfu_run_main``, ``cmd/__init__.py:7-9``) — no
separately installed binary needed to launch a job.
"""

import sys

from kungfu_tpu.runner.cli import main

if __name__ == "__main__":
    sys.exit(main())
