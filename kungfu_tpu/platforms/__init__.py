from kungfu_tpu.platforms.tpu_pod import (  # noqa: F401
    PodInfo,
    multislice_communicator,
    parse_tpu_pod_env,
    slice_device_groups,
    slice_mesh_layout,
)
