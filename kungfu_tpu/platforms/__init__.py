from kungfu_tpu.platforms.tpu_pod import PodInfo, parse_tpu_pod_env  # noqa: F401
