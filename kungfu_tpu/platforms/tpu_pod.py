"""TPU pod/multislice environment adapter.

The platform-adapter slot of the reference's cloud integration
(``srcs/go/platforms/modelarts/modelarts.go:1`` — read the scheduler's
env contract, produce self identity + the peer list) — re-targeted at
the platform this framework actually runs on: GKE/GCE TPU pods.  The
TPU runtime/scheduler publishes:

=============================  =========================================
``TPU_WORKER_HOSTNAMES``       comma-separated host list, rank order
``TPU_WORKER_ID``              this host's index in that list
``MEGASCALE_COORDINATOR_ADDRESS``  multislice coordinator (slice 0 host 0)
``MEGASCALE_SLICE_ID`` /
``MEGASCALE_NUM_SLICES``       multislice identity (optional)
=============================  =========================================

``parse_tpu_pod_env`` turns that contract into the launcher's inputs — a
:class:`~kungfu_tpu.plan.hostspec.HostList` (one worker slot per host:
one jax process drives all local chips), this runner's self host, and
the coordinator — so ``kfrun -platform tpu-pod`` needs no ``-H``/
``-self`` flags inside a pod.  Mirrors the reference's validation: both
identity envs required, index bounds checked.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from kungfu_tpu.plan.hostspec import HostList
from kungfu_tpu.utils import envs
from kungfu_tpu.utils.log import get_logger

_log = get_logger("tpu-pod")

WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
WORKER_ID = "TPU_WORKER_ID"
# the MEGASCALE_* contract is anchored in the env registry
# (utils/envs.py) like every other env this framework reads; these are
# aliases for the module's historical public names
MEGASCALE_COORDINATOR = envs.MEGASCALE_COORDINATOR
MEGASCALE_SLICE_ID = envs.MEGASCALE_SLICE_ID
MEGASCALE_NUM_SLICES = envs.MEGASCALE_NUM_SLICES


@dataclass(frozen=True)
class PodInfo:
    hosts: HostList          #: one slot per pod host, scheduler rank order
    self_host: str           #: this runner's host
    worker_id: int
    coordinator: str = ""    #: multislice coordinator addr ("" = single slice)
    slice_id: int = 0
    num_slices: int = 1

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)


def detected(env=None) -> bool:
    env = env if env is not None else os.environ
    return bool(env.get(WORKER_HOSTNAMES))


def parse_tpu_pod_env(env=None, slots_per_host: int = 1) -> Optional[PodInfo]:
    """Parse the pod contract; None when not running inside a TPU pod.
    Raises on a malformed contract (set but inconsistent), like the
    reference adapter."""
    env = env if env is not None else os.environ
    hostnames = env.get(WORKER_HOSTNAMES, "").strip()
    if not hostnames:
        return None
    names = [h.strip() for h in hostnames.split(",") if h.strip()]
    if not names:
        raise ValueError(f"{WORKER_HOSTNAMES} is set but empty")
    wid_s = env.get(WORKER_ID, "").strip()
    if not wid_s:
        if len(names) == 1:
            wid = 0  # single-host pod: the id env is often omitted
        else:
            raise ValueError(
                f"{WORKER_ID} not set but {WORKER_HOSTNAMES} lists "
                f"{len(names)} hosts"
            )
    else:
        wid = int(wid_s)
    if not 0 <= wid < len(names):
        raise ValueError(
            f"{WORKER_ID}={wid} outside the {len(names)}-host list"
        )
    hosts = HostList.parse(
        ",".join(f"{n}:{slots_per_host}" for n in names)
    )
    info = PodInfo(
        hosts=hosts,
        self_host=names[wid],
        worker_id=wid,
        coordinator=env.get(MEGASCALE_COORDINATOR, "").strip(),
        slice_id=int(env.get(MEGASCALE_SLICE_ID, "0") or 0),
        num_slices=int(env.get(MEGASCALE_NUM_SLICES, "1") or 1),
    )
    _log.info(
        "TPU pod: %d hosts, self=%s (id %d), slice %d/%d",
        info.num_hosts, info.self_host, wid, info.slice_id, info.num_slices,
    )
    return info


def slice_device_groups(devices=None, by: str = "slice"):
    """Group the global device list by slice, outer-sorted by slice id.

    ``by="slice"``: real multislice TPU — devices carry ``slice_index``
    (libtpu federates the slices through the MEGASCALE coordinator and
    every process sees all chips).  ``by="process"``: the emulation
    contract — one jax process per "slice" (CPU devices report a
    constant ``slice_index``, so the process index IS the slice id
    there, ``MEGASCALE_SLICE_ID`` = process id).
    """
    import jax

    devs = list(devices) if devices is not None else jax.devices()

    def slice_of(d):
        if by == "process":
            return d.process_index
        si = getattr(d, "slice_index", None)
        return si if si is not None else d.process_index

    groups = {}
    for d in devs:
        groups.setdefault(slice_of(d), []).append(d)
    return [groups[k] for k in sorted(groups)]


def slice_mesh_layout(num_slices: Optional[int] = None, devices=None):
    """``(devices_slice_major, per_slice)`` for a hierarchical mesh whose
    OUTER axis is the slice (DCN) and inner axis the within-slice chips
    (ICI).  Shared validation core of :func:`multislice_communicator`
    and :meth:`kungfu_tpu.peer.Peer.communicator`'s multislice path:

    * ``num_slices`` defaults to the ``MEGASCALE_NUM_SLICES`` contract
      and is validated against the devices actually visible; a mismatch
      raises (a half-joined multislice job must fail loudly, not
      silently train one slice);
    * when the contract disagrees with the ``slice_index`` grouping but
      matches the per-process grouping, the emulation contract applies
      (one jax process per "slice", ``MEGASCALE_SLICE_ID`` = process
      id — the CPU-mesh harness);
    * uneven slice sizes raise: multislice meshes need identical slices.
    """
    if num_slices is None:
        num_slices = int(
            os.environ.get(envs.MEGASCALE_NUM_SLICES, "0") or 0) or None
    groups = slice_device_groups(devices)
    if num_slices is not None and len(groups) != num_slices:
        # emulation: one jax process per slice (CPU devices report a
        # constant slice_index — regroup by the process contract)
        by_proc = slice_device_groups(devices, by="process")
        if len(by_proc) == num_slices:
            groups = by_proc
        else:
            raise ValueError(
                f"{envs.MEGASCALE_NUM_SLICES}={num_slices} but the device "
                f"world shows {len(groups)} slice group(s) "
                f"({len(by_proc)} process group(s))"
            )
    per = len(groups[0])
    if any(len(g) != per for g in groups):
        raise ValueError(
            f"uneven slice sizes {[len(g) for g in groups]} — multislice "
            "meshes need identical slices"
        )
    return [d for g in groups for d in g], per


def multislice_communicator(num_slices: Optional[int] = None, devices=None,
                            version: int = 0, **comm_kwargs):
    """Build a hierarchical Communicator whose OUTER mesh axis is the
    slice (DCN) and inner axis the within-slice chips (ICI) — the
    two-level topology the ``two_stage`` schedule decomposes over:
    reduce within each slice over ICI, exchange once across slices over
    DCN, broadcast back (SURVEY §5.8; reference local/cross split,
    ``session/strategy.go:176-210``).  Validation lives in
    :func:`slice_mesh_layout`; extra ``comm_kwargs`` (``cluster``,
    ``strategy``, ``on_strategy_change``) pass through so the Peer's
    mesh-epoch machinery builds slice-aware epochs through the same
    door."""
    from kungfu_tpu.comm.device import Communicator

    flat, per = slice_mesh_layout(num_slices, devices)
    return Communicator(devices=flat, local_size=per, version=version,
                        **comm_kwargs)
