"""Subprocess management with prefix-colored streaming and log files.

Parity with reference ``srcs/go/proc/proc.go`` (Proc spec → exec with
merged env) and ``srcs/go/utils/runner/local/local.go`` (run all procs,
per-proc colored stdout prefix, per-proc log files, fail-fast group wait).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

_COLORS = [32, 33, 34, 35, 36, 91, 92, 93, 94, 95]


@dataclass
class Proc:
    name: str
    prog: str
    args: List[str]
    envs: Dict[str, str] = field(default_factory=dict)
    log_dir: str = ""

    def cmdline(self) -> List[str]:
        return [self.prog] + list(self.args)


class _Running:
    def __init__(self, proc: Proc, popen: subprocess.Popen, pumps):
        self.proc = proc
        self.popen = popen
        self.pumps = pumps


def _pump(stream, sink, prefix: str, color: int, logfile):
    for raw in iter(stream.readline, b""):
        line = raw.decode(errors="replace")
        sink.write(f"\x1b[{color}m[{prefix}]\x1b[0m {line}")
        sink.flush()
        if logfile:
            logfile.write(line)
            logfile.flush()
    stream.close()
    if logfile:
        logfile.close()


def start_proc(proc: Proc, index: int = 0, quiet: bool = False) -> _Running:
    env = dict(os.environ)
    env.update(proc.envs)
    stdout_log = stderr_log = None
    if proc.log_dir:
        os.makedirs(proc.log_dir, exist_ok=True)
        stdout_log = open(os.path.join(proc.log_dir, f"{proc.name}.stdout.log"), "w")
        stderr_log = open(os.path.join(proc.log_dir, f"{proc.name}.stderr.log"), "w")
    popen = subprocess.Popen(
        proc.cmdline(),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        start_new_session=True,
    )
    color = _COLORS[index % len(_COLORS)]
    pumps = []
    if quiet:
        sink_out = open(os.devnull, "w")
        sink_err = sink_out
    else:
        sink_out, sink_err = sys.stdout, sys.stderr
    for stream, sink, logf in (
        (popen.stdout, sink_out, stdout_log),
        (popen.stderr, sink_err, stderr_log),
    ):
        t = threading.Thread(
            target=_pump, args=(stream, sink, proc.name, color, logf), daemon=True
        )
        t.start()
        pumps.append(t)
    return _Running(proc, popen, pumps)


def kill_group(running: _Running) -> None:
    try:
        os.killpg(os.getpgid(running.popen.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        pass


def run_all(procs: Sequence[Proc], quiet: bool = False, timeout: Optional[float] = None,
            fail_fast: bool = True) -> List[int]:
    """Run all procs; on any failure, kill the rest (fail-fast like the
    reference runner).  Returns exit codes in proc order.

    ``fail_fast=False`` (the shrink-to-survivors supervisor policy,
    ``kfrun -tolerate-failures``): a worker's death does NOT take the
    group down — the survivors are expected to exclude the dead peer
    in-flight (elastic/shrink.py) and run to completion."""
    running = [start_proc(p, i, quiet=quiet) for i, p in enumerate(procs)]
    codes: List[Optional[int]] = [None] * len(running)
    try:
        deadline = None if timeout is None else (timeout + time.time())
        pending = set(range(len(running)))
        while pending:
            for i in list(pending):
                r = running[i]
                try:
                    codes[i] = r.popen.wait(timeout=0.2)
                    pending.discard(i)
                    if codes[i] != 0 and fail_fast:
                        for j in pending:
                            kill_group(running[j])
                except subprocess.TimeoutExpired:
                    pass
            if deadline is not None and time.time() > deadline and pending:
                for j in pending:
                    kill_group(running[j])
                raise TimeoutError(f"procs {sorted(pending)} still running after {timeout}s")
    finally:
        for r in running:
            if r.popen.poll() is None:
                kill_group(r)
        for r in running:
            for t in r.pumps:
                t.join(timeout=2)
    return [c if c is not None else -1 for c in codes]
