"""Self-IP inference for multi-host launches.

Parity with the reference's NIC-based discovery
(``srcs/go/kungfu/runner/discovery.go``): a runner started with the same
command line on every host must figure out WHICH entry of the host list
it is.  The reference enumerates NICs and matches their addresses
against the host list; portable Python cannot enumerate NICs without
third-party deps, so the same question is answered with a BIND probe
per candidate: binding an ephemeral UDP socket to ``ip:0`` succeeds
exactly when ``ip`` is assigned to this machine (a routing probe would
under-detect — the kernel's source selection answers alias/secondary
addresses with the primary one).
"""

from __future__ import annotations

import socket
from typing import List

from kungfu_tpu.utils.log import get_logger

_log = get_logger("discovery")


def _is_local_addr(ip: str, family: int = socket.AF_INET) -> bool:
    """True when ``ip`` is assigned to this machine.

    Known limit: with ``net.ipv4.ip_nonlocal_bind=1`` (keepalived/HA
    boxes) EVERY address binds, so all candidates match and the
    ambiguity error tells the operator to pass ``-self`` explicitly —
    wrong-slot guessing is never silent."""
    try:
        with socket.socket(family, socket.SOCK_DGRAM) as s:
            s.bind((ip, 0))
            return True
    except OSError:
        return False


def infer_self_ip(hosts: List[str]) -> str:
    """The entry of ``hosts`` naming THIS machine.

    A candidate is ours when this machine can bind it (loopback and
    alias addresses included — this is exactly how the compose-style
    alias hosts resolve too).  Exactly one match is required: zero means
    the host list does not name this machine, several means the list
    contains multiple local addresses and the runner cannot know which
    slot it fills.
    """
    matches = []
    for h in hosts:
        try:
            family, *_, addr = socket.getaddrinfo(
                h, None, proto=socket.IPPROTO_UDP)[0]
            ip = addr[0]
        except OSError:
            _log.warning("cannot resolve host %r; skipping", h)
            continue
        if _is_local_addr(ip, family):
            matches.append(h)
    if len(matches) == 1:
        return matches[0]
    if not matches:
        raise RuntimeError(
            f"-self auto: none of {hosts} is an address of this machine"
        )
    raise RuntimeError(
        f"-self auto: {matches} all resolve to this machine — pass -self "
        "explicitly to pick the slot"
    )
