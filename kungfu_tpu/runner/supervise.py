"""``kfrun -restore-from`` — cold-restart supervision over the durable
manifest plane.

MonitoredRun (``runner/monitored.py``) survives *partial* failures by
heartbeat detection and epoch-checkpoint replay; it is useless against a
whole-job preemption, where every worker (and every heartbeat source)
dies in the same instant.  PersistRun covers that case with the weakest
possible machinery: it knows nothing about epochs, detectors, or worker
health — only exit codes and the manifest directory.

Policy per round:

* every worker exits 0 → the job finished; success.
* every worker exits :data:`~kungfu_tpu.chaos.inject.DIE_EXIT_CODE`
  (the injected/real preemption code) AND a complete manifest exists
  under the persist root → relaunch the whole group.  Workers come up
  with ``KF_PERSIST_RESTORE=1`` already set, agree on the newest
  complete manifest (``PersistPlane.agree_manifest``), and resume from
  it — onto whatever world size THIS launch has, because restore is
  pure ``reshard_plan`` re-carving (docs/persistence.md).
* anything else (mixed codes, a crash that is not a preemption, no
  restorable manifest) → fail; supervision must not paper over bugs.

Relaunches strip ``preempt`` clauses from the workers' ``KF_CHAOS_SPEC``
— the chaos preemption models ONE eviction event; replaying it every
round would preempt the job forever and the goodput experiment would
never terminate.  Other clauses (delay, reset, …) survive the restart,
as real background faults would.
"""

from __future__ import annotations

from typing import List

from kungfu_tpu.chaos.inject import DIE_EXIT_CODE
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.runner.job import Job
from kungfu_tpu.runner.proc import run_all
from kungfu_tpu.utils.log import get_logger

_log = get_logger("persist-run")

#: relaunch budget — a job that gets preempted more often than this is
#: not making progress worth supervising (mirrors monitored.MAX_RESTARTS)
MAX_RELAUNCHES = 16


def strip_preempt(spec: str) -> str:
    """Drop ``preempt`` clauses from a raw ``KF_CHAOS_SPEC`` string,
    preserving every other clause verbatim (the spec round-trips
    textually — no parse/re-serialize drift)."""
    kept: List[str] = []
    for part in spec.split(";"):
        clause = part.strip()
        if not clause:
            continue
        kind = clause.split(":", 1)[0].strip()
        if kind == "preempt":
            continue
        kept.append(clause)
    return ";".join(kept)


def persist_run(ns, cluster: Cluster, job: Job) -> int:
    from kungfu_tpu.chaos import SPEC_ENV
    from kungfu_tpu.elastic.persist import newest_complete_manifest
    from kungfu_tpu.utils import envs

    root = job.extra_envs.get(envs.PERSIST_DIR, "")
    relaunches = 0
    while True:
        procs = job.create_procs(cluster, ns.self_host)
        if not procs:
            _log.warning("no workers for host %s", ns.self_host)
            return 0
        _log.info(
            "round %d: launching %d/%d workers (persist root %s)",
            relaunches, len(procs), cluster.size(), root,
        )
        # fail_fast off: a preemption kills every rank at the same step
        # boundary, but wall-clock skew means the first death must not
        # SIGTERM the rest — their own exit codes (43 vs crash) are the
        # evidence this supervisor decides on
        codes = run_all(procs, quiet=ns.quiet, timeout=ns.timeout or None,
                        fail_fast=False)
        if all(c == 0 for c in codes):
            _log.info("training finished")
            return 0
        if not all(c == DIE_EXIT_CODE for c in codes):
            _log.error(
                "workers failed with non-preemption codes %s — not "
                "relaunching (a crash is a bug, not an eviction)", codes,
            )
            return 1
        newest = newest_complete_manifest(root) if root else None
        if newest is None:
            _log.error(
                "whole job preempted (codes %s) but no complete manifest "
                "under %r — nothing durable to restart from", codes, root,
            )
            return 1
        relaunches += 1
        if relaunches > MAX_RELAUNCHES:
            _log.error("giving up after %d relaunches", MAX_RELAUNCHES)
            return 1
        spec = job.extra_envs.get(SPEC_ENV, "")
        if spec:
            stripped = strip_preempt(spec)
            if stripped != spec:
                if stripped:
                    job.extra_envs[SPEC_ENV] = stripped
                else:
                    del job.extra_envs[SPEC_ENV]
                _log.info("chaos spec after preemption: %r",
                          stripped or "(cleared)")
        _log.warning(
            "whole job preempted; relaunching round %d from manifest %s",
            relaunches, newest,
        )
