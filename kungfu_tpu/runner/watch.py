"""Elastic watch-mode runner.

Parity with reference ``runner/watch.go:23-135`` + ``runner/handler.go``:
the runner daemon listens for ``"update"`` control messages carrying a
Stage (version + cluster JSON) from workers mid-resize, diffs the old/new
worker lists for *this host*, kills removed workers and spawns added ones
with the new bootstrap env (version-fenced).  The job ends when all local
workers have exited.
"""

from __future__ import annotations

import json
import os
import queue
import time
import urllib.request
from typing import Dict, Optional, Set

from kungfu_tpu.comm.host import ConnType, bind_own_host_channel
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.plan.hostspec import DEFAULT_RUNNER_PORT
from kungfu_tpu.plan.peer import PeerID, parse_peer_id
from kungfu_tpu.runner.job import Job
from kungfu_tpu.runner.proc import kill_group, start_proc
from kungfu_tpu.utils.log import get_logger

_log = get_logger("watch")

#: natural-end grace window (seconds, ``KF_CONFIG_WATCH_GRACE``): how
#: long a runner whose local workers all exited cleanly waits for an
#: in-flight resize stage before concluding the job is over
WATCH_GRACE_ENV = "KF_CONFIG_WATCH_GRACE"
DEFAULT_WATCH_GRACE_S = 10.0


def _config_server_version(url: str, timeout: float = 3.0) -> Optional[int]:
    """The config server's current cluster version, or None when it
    cannot be reached (no server configured / transient outage)."""
    if not url:
        return None
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return int(json.loads(resp.read().decode())["version"])
    except (OSError, ValueError, KeyError):
        return None


def watch_run(ns, cluster: Cluster, job: Job) -> int:
    self_host = ns.self_host
    # bind THIS runner's address (wildcard fallback): compose-style local
    # clusters run one runner per loopback alias (127.0.0.<i>) on the
    # same machine, all on the runner port
    chan = bind_own_host_channel(PeerID(self_host, DEFAULT_RUNNER_PORT))
    stages: "queue.Queue[dict]" = queue.Queue()

    def on_control(name: str, payload: bytes, src: str):
        if name == "update":
            try:
                stages.put(json.loads(payload.decode()))
            except ValueError as e:
                _log.warning("bad update from %s: %s", src, e)
        elif name == "exit":
            stages.put({"exit": True})
        elif name == "done":
            # rank 0 finished cleanly: the job is over for every host,
            # including hosts holding no workers right now
            stages.put({"done": True})

    chan.on_control(on_control)

    running: Dict[PeerID, object] = {}
    killed: Set[PeerID] = set()
    version = 0
    seen_versions = {0}
    failures = 0
    idx = 0

    def spawn(worker: PeerID, cl: Cluster, v: int):
        nonlocal idx
        proc = job.new_proc(worker, cl, v)
        _log.info("spawning %s (v%d)", proc.name, v)
        running[worker] = start_proc(proc, idx, quiet=ns.quiet)
        idx += 1

    current = cluster
    device_world = job.world is not None
    initial = job.world if device_world else cluster.workers
    for w in initial.on_host(self_host):
        spawn(w, cluster, version)

    stop = False
    job_done = False
    natural_end_at = None
    try:
        while True:
            # poll exits
            for w, r in list(running.items()):
                code = r.popen.poll()
                if code is None:
                    continue
                del running[w]
                if w in killed:
                    killed.discard(w)
                    _log.info("worker %s terminated after removal", w)
                elif code != 0:
                    _log.error("worker %s exited %d", w, code)
                    failures += 1
                else:
                    _log.info("worker %s finished", w)
            if failures and running:
                for w, r in list(running.items()):
                    kill_group(r)
                    killed.add(w)
            if not running and stages.empty():
                # exit when: local workers failed (all killed above); the
                # job signalled completion; or the CURRENT cluster still
                # assigns this host workers and they all finished (the
                # pre-elastic natural end).  A host the schedule shrank to
                # zero must keep serving — a later stage may grow back.
                if failures or stop or job_done:
                    break
                if current.workers.on_host(self_host):
                    # natural end — but a shrink's detached workers can
                    # exit BEFORE rank 0's "update" for that stage reaches
                    # us (rank 0 may sit in compile/re-sync for a while
                    # before _notify_runners); give an in-flight stage a
                    # grace window, and when the window expires confirm
                    # against the config server: a version ahead of ours
                    # means a stage IS coming — keep serving, or this
                    # host is orphaned for every later re-grow
                    if natural_end_at is None:
                        grace = float(os.environ.get(
                            WATCH_GRACE_ENV, DEFAULT_WATCH_GRACE_S))
                        natural_end_at = time.monotonic() + grace
                    elif time.monotonic() >= natural_end_at:
                        # job.config_server carries the RESOLVED URL in
                        # builtin-config-server mode, where
                        # ns.config_server stays empty
                        cs_ver = _config_server_version(
                            getattr(job, "config_server", "")
                            or getattr(ns, "config_server", ""))
                        if cs_ver is not None and cs_ver != version:
                            _log.info(
                                "config server at v%d, we applied v%d — "
                                "stage in flight, extending grace",
                                cs_ver, version)
                            natural_end_at = None
                        else:
                            break
            else:
                natural_end_at = None
            # poll membership updates
            try:
                stage = stages.get(timeout=0.2)
            except queue.Empty:
                continue
            if stage.get("exit"):
                stop = True
                for w, r in list(running.items()):
                    kill_group(r)
                    killed.add(w)
                continue
            if stage.get("done"):
                job_done = True
                continue
            new_version = int(stage["version"])
            new_cluster = Cluster.from_json(json.dumps(stage["cluster"]))
            if new_version in seen_versions:
                # duplicate update for a known version: verify consistency
                # (reference handler.go:89-106 exits on inconsistency)
                if new_version == version and new_cluster.workers != current.workers:
                    _log.error("inconsistent update for version %d", new_version)
                    return 1
                continue
            seen_versions.add(new_version)
            _log.info(
                "stage v%d: %d -> %d workers", new_version, current.size(), new_cluster.size()
            )
            chan.set_token(new_version)
            old_local = set(current.workers.on_host(self_host))
            new_local = set(new_cluster.workers.on_host(self_host))
            if device_world:
                # provisioned world: in-world workers transition themselves
                # (active <-> standby) — the runner only kills/spawns slots
                # that leave/enter the provisioned world (normally none)
                world_local = set(job.world.on_host(self_host))
                removed = (old_local - new_local) - world_local
                added = (new_local - old_local) - world_local
            else:
                removed = old_local - new_local
                added = new_local - old_local
            for w in removed:
                r = running.get(w)
                if r is not None:
                    _log.info("killing removed worker %s", w)
                    kill_group(r)
                    killed.add(w)
            for w in sorted(added):
                try:
                    spawn(w, new_cluster, new_version)
                except ValueError as e:
                    # e.g. a grow beyond the provisioned device world:
                    # un-spawnable workers must not take down the healthy
                    # job (the peer side falls back to the full-world mesh)
                    _log.error("cannot spawn %s: %s", w, e)
            current, version = new_cluster, new_version
    finally:
        for w, r in list(running.items()):
            kill_group(r)
        if failures:
            # a runner idling with zero workers (shrunk-away host) has no
            # other way to learn the job died — rank 0 will never send
            # "done"; best-effort fan-out so peers don't hang
            me = PeerID(self_host, DEFAULT_RUNNER_PORT)
            for runner in current.runners:
                if runner == me:
                    continue
                try:
                    chan.send(runner, "exit", b"", ConnType.CONTROL, retries=1)
                except (ConnectionError, OSError):
                    pass
        chan.close()
    if failures:
        _log.error("%d worker(s) failed", failures)
        return 1
    return 0
