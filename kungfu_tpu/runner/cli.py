"""``kfrun`` — the kungfu-run analog.

Flag parity with reference ``srcs/go/kungfu/runner/flags.go:29-104`` (the
subset meaningful on TPU; ``-allow-nvlink`` has no analog; the reference's
NIC-based self discovery is ``-self auto``, ``runner/discovery.py``).
Dispatch parity with ``app/kungfu-run.go:18-116``:

* default: **SimpleRun** — spawn all local workers, wait
  (``runner/simple.go:13-21``);
* ``-w``: **WatchRun** — elastic runner daemon that diffs worker lists on
  membership change and spawns/kills accordingly (``runner/watch.go``);
* ``-auto-recover``: **MonitoredRun** — heartbeat failure detector +
  automatic relaunch (``runner/monitored.go``);
* ``-restore-from``: **PersistRun** — no reference analog: cold-restart
  supervision over the durable manifest plane (``runner/supervise.py``,
  ``elastic/persist.py``) for whole-job preemptions that leave no
  survivor to detect anything.

Examples::

    python -m kungfu_tpu.runner.cli -np 4 python3 train.py
    python -m kungfu_tpu.runner.cli -np 2 -H 127.0.0.1:4 -strategy RING python3 train.py
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

from kungfu_tpu.monitor.detector import DEFAULT_COMPILE_GRACE_S
from kungfu_tpu.plan import Cluster, HostList, parse_strategy
from kungfu_tpu.plan.hostfile import parse_hostfile
from kungfu_tpu.plan.hostspec import DEFAULT_RUNNER_PORT
from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.runner.job import Job
from kungfu_tpu.runner.proc import run_all
from kungfu_tpu.utils.log import get_logger

_log = get_logger("kfrun")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kfrun", description="launch kungfu_tpu workers"
    )
    p.add_argument("-np", type=int, default=None,
                   help="total number of workers (default 1; on a detected "
                        "TPU pod, one per pod host)")
    p.add_argument("-H", dest="hosts", default="", help="host spec list ip:slots,...")
    p.add_argument("-hostfile", default="", help="MPI-style hostfile")
    p.add_argument("-self", dest="self_host", default="127.0.0.1",
                   help="this runner's host ip; 'auto' probes which -H "
                        "entry this machine holds (reference NIC discovery)")
    p.add_argument("-strategy", default="AUTO", help="allreduce strategy name")
    p.add_argument("-w", dest="watch", action="store_true", help="elastic watch mode")
    p.add_argument("-device-world", dest="device_world", action="store_true",
                   help="provision ALL host-list slots as one jax.distributed "
                        "world; elastic resize re-carves the device mesh over "
                        "the active workers (live resize, no relaunch)")
    p.add_argument("-config-server", dest="config_server", default="", help="elastic config server URL")
    p.add_argument("-builtin-config-port", dest="builtin_config_port", type=int, default=0,
                   help="start a built-in config server on this port")
    p.add_argument("-auto-recover", dest="auto_recover", default="",
                   help="failure-detection period (e.g. 10s); enables MonitoredRun")
    p.add_argument("-device-strategy", dest="device_strategy", default="",
                   help="initial device allreduce schedule "
                        "(psum/two_stage/ring; empty = psum)")
    p.add_argument("-compile-grace", dest="compile_grace",
                   default=f"{int(DEFAULT_COMPILE_GRACE_S)}s",
                   help="stall allowance while a rank is known to be "
                        "compiling (first batch / post-resize re-jit)")
    p.add_argument("-port-range", dest="port_range", default="10000-11000")
    p.add_argument("-logdir", default="")
    p.add_argument("-q", dest="quiet", action="store_true", help="suppress worker output")
    p.add_argument("-timeout", type=float, default=0.0, help="job timeout seconds (0 = none)")
    p.add_argument("-backend", default=None, choices=["cpu", "tpu"],
                   help="worker device backend (default cpu = multi-process "
                        "test cluster; a detected cloud platform may set tpu)")
    p.add_argument("-platform", default="auto", choices=["auto", "none", "tpu-pod"],
                   help="cloud platform adapter: derive -H/-self/-backend from "
                        "the scheduler's env (TPU_WORKER_HOSTNAMES et al.); "
                        "'auto' uses it only when detected AND no -H given")
    p.add_argument("-n-epochs-flag", dest="n_epochs_flag", default="--n-epochs",
                   help="worker flag patched on auto-recovery restart")
    p.add_argument("-tolerate-failures", dest="tolerate_failures",
                   action="store_true",
                   help="do not kill the worker group when one worker dies; "
                        "survivors are expected to shrink-to-survivors "
                        "in-flight (docs/fault_tolerance.md).  The run "
                        "succeeds iff at least one worker exits 0")
    p.add_argument("-chaos", dest="chaos", default="",
                   help="deterministic fault-injection spec exported to "
                        "workers as KF_CHAOS_SPEC (kungfu_tpu/chaos/spec.py; "
                        "e.g. 'die:step=5,rank=1' kills rank 1 at step 5)")
    p.add_argument("-chaos-seed", dest="chaos_seed", type=int, default=None,
                   help="KF_CHAOS_SEED for the workers (delay jitter)")
    p.add_argument("-num-slices", dest="num_slices", type=int, default=0,
                   help="partition the workers into this many TPU slices "
                        "(slice-major contiguous).  Each worker's env gets "
                        "its MEGASCALE_SLICE_ID (+ NUM_SLICES and "
                        "KF_SLICE_RANKS), switching the peers to the "
                        "hierarchical ICI-within/DCN-across communicator "
                        "and slice-granular elasticity.  This is the CPU "
                        "emulation contract (docs/multislice.md); a real "
                        "pod's hosts already carry their MEGASCALE_* "
                        "identity and must not be re-stamped")
    p.add_argument("-persist-dir", dest="persist_dir", default="",
                   help="durable manifest root exported to workers as "
                        "KF_PERSIST_DIR: training loops that carry a "
                        "PersistPlane stream async per-rank shard "
                        "checkpoints there (docs/persistence.md)")
    p.add_argument("-restore-from", dest="restore_from", default="",
                   help="manifest root to cold-restart from: implies "
                        "-persist-dir DIR, sets KF_PERSIST_RESTORE=1 so "
                        "workers resume from the newest complete manifest "
                        "(onto THIS launch's world size — restore is "
                        "shape-agnostic), and supervises the job: a "
                        "whole-group preemption (every rank exits 43) "
                        "relaunches from the newest complete manifest. "
                        "An empty/fresh directory is a fresh start")
    p.add_argument("-monitor", dest="monitor", action="store_true",
                   help="live cluster observability plane: mount the "
                        "aggregator on the (builtin) config server, make "
                        "every worker push snapshots "
                        "(KF_CONFIG_ENABLE_CLUSTER_MONITOR), and enable "
                        "tracing + the network monitor so snapshots carry "
                        "collective spans and byte rates.  View with "
                        "scripts/kftop; starts an ephemeral builtin config "
                        "server when none is configured")
    p.add_argument("-sentinel", dest="sentinel", default="",
                   help="kf-sentinel judging plane: durable metrics "
                        "history + online regression/SLO-burn detectors "
                        "+ incident flight records under DIR "
                        "(KF_SENTINEL_DIR).  Implies -monitor; alerts at "
                        "/alerts and in kftop; replay offline with "
                        "scripts/kfhist --dir DIR --verdict")
    p.add_argument("-monitor-interval", dest="monitor_interval", type=float,
                   default=0.0,
                   help="snapshot push period seconds "
                        "(KF_CONFIG_MONITOR_PUSH_PERIOD; default 1)")
    p.add_argument("-trace", dest="trace", action="store_true",
                   help="enable scoped tracing + the flight-recorder "
                        "timeline in every worker (KF_CONFIG_ENABLE_TRACE)")
    p.add_argument("-trace-dump", dest="trace_dump", default="",
                   help="directory for per-rank timeline JSONL dumps "
                        "(KF_CONFIG_TRACE_DUMP; implies -trace).  Merge "
                        "and analyze with scripts/kftrace")
    p.add_argument("prog", help="worker program")
    p.add_argument("args", nargs=argparse.REMAINDER, help="worker program args")
    return p


def parse_port_range(spec: str):
    lo, hi = spec.split("-")
    return int(lo), int(hi)


def build_hostlist(ns) -> HostList:
    if ns.hostfile:
        return parse_hostfile(ns.hostfile)
    if ns.hosts:
        return HostList.parse(ns.hosts)
    return HostList.parse(f"{ns.self_host}:{max(ns.np or 1, 1)}")


def build_cluster(ns) -> Cluster:
    hl = build_hostlist(ns)
    return Cluster(
        hl.gen_runner_list(DEFAULT_RUNNER_PORT),
        hl.gen_peer_list(ns.np or 1, parse_port_range(ns.port_range)),
    )


def simple_run(ns, cluster: Cluster, job: Job) -> int:
    procs = job.create_procs(cluster, ns.self_host)
    if not procs:
        _log.warning("no workers for host %s", ns.self_host)
        return 0
    _log.info(
        "launching %d/%d workers on %s (strategy=%s)",
        len(procs), cluster.size(), ns.self_host, job.strategy,
    )
    codes = run_all(procs, quiet=ns.quiet, timeout=ns.timeout or None,
                    fail_fast=not ns.tolerate_failures)
    bad = [c for c in codes if c != 0]
    if bad and ns.tolerate_failures and len(bad) < len(codes):
        # dead workers are survivable by design: the survivors shrank
        # around them and finished — that IS the success criterion
        _log.warning(
            "%d worker(s) died (codes %s); survivors completed", len(bad), codes
        )
        return 0
    if bad:
        _log.error("workers failed: exit codes %s", codes)
        return 1
    return 0


def apply_platform(ns) -> None:
    """Fill -H/-self/-backend from a detected cloud platform contract
    (reference ``platforms/modelarts`` analog, TPU-pod flavored).

    ``auto`` applies only when the user gave NO topology (-H/-hostfile)
    and NO explicit -backend — any explicit flag opts out of the magic.
    ``tpu-pod`` (forced) lets the pod contract win outright."""
    if ns.platform == "none":
        return
    from kungfu_tpu.platforms import parse_tpu_pod_env

    if ns.platform == "auto" and (
        ns.hosts or ns.hostfile or ns.backend is not None
    ):
        return  # any explicit choice wins over detection
    info = parse_tpu_pod_env()
    if info is None:
        if ns.platform == "tpu-pod":
            raise SystemExit(
                "kfrun: -platform tpu-pod but TPU_WORKER_HOSTNAMES is not set"
            )
        return
    if ns.np is not None and ns.np > info.num_hosts:
        if ns.platform == "tpu-pod":
            raise SystemExit(
                f"kfrun: -np {ns.np} exceeds the detected TPU pod's "
                f"capacity ({info.num_hosts} hosts, 1 worker slot each)"
            )
        # auto mode: an explicit -np the pod can't host (1 slot/host)
        # means the user wants a local multi-process cluster, not the pod
        # topology — e.g. CPU-backend test runs on a TPU VM whose env
        # still carries the pod contract
        _log.info(
            "platform auto: detected TPU pod (%d hosts) cannot host "
            "-np %d; keeping the default localhost cluster",
            info.num_hosts, ns.np,
        )
        return
    ns.hosts = str(info.hosts)
    ns.hostfile = ""  # the pod contract IS the topology
    ns.self_host = info.self_host
    ns.backend = "tpu"
    if ns.np is None:
        # only the DEFAULT np expands to the whole pod; an explicit
        # `-np 1` (distinguishable now that the argparse default is
        # None) keeps its single worker
        ns.np = info.num_hosts
    if info.num_slices > 1:
        # cross-slice (DCN) device coordination is libtpu's, and on a
        # real pod TPU_WORKER_HOSTNAMES lists THIS slice's hosts only —
        # so the launcher must NOT partition them into synthetic slices.
        # Each worker inherits its host's true MEGASCALE_* identity from
        # the environment; `-num-slices` (the explicit flag) exists for
        # the emulation contract, where there is no env to inherit.
        if ns.num_slices > 0:
            raise SystemExit(
                "kfrun: -num-slices on a detected multislice pod would "
                "overwrite the hosts' real MEGASCALE_SLICE_ID — the pod "
                "env already carries slice identity (drop the flag)")
        _log.info(
            "multislice pod (slice %d/%d, coordinator %s): MEGASCALE "
            "envs pass through to workers", info.slice_id,
            info.num_slices, info.coordinator or "?",
        )
    _log.info(
        "platform tpu-pod: -H %s -self %s (np=%d)",
        ns.hosts, ns.self_host, ns.np,
    )


def main(argv: Optional[List[str]] = None) -> int:
    ns = build_parser().parse_args(argv)
    apply_platform(ns)
    if ns.self_host == "auto":
        # reference runner/discovery.go: same command line on every
        # host; each runner works out which -H entry it is
        if not (ns.hosts or ns.hostfile):
            raise SystemExit("kfrun: -self auto needs -H or -hostfile")
        from kungfu_tpu.runner.discovery import infer_self_ip

        try:
            ns.self_host = infer_self_ip(
                [h.ip for h in build_hostlist(ns).hosts])
        except RuntimeError as e:
            raise SystemExit(f"kfrun: {e}") from None
        _log.info("self host inferred: %s", ns.self_host)
    if ns.np is None:
        ns.np = 1
    if ns.backend is None:
        ns.backend = "cpu"
    strategy = parse_strategy(ns.strategy)
    if ns.device_strategy:
        from kungfu_tpu.ops.schedules import ALLREDUCE_SCHEDULES

        if ns.device_strategy not in ALLREDUCE_SCHEDULES:
            raise SystemExit(
                f"kfrun: unknown -device-strategy {ns.device_strategy!r}; "
                f"one of {ALLREDUCE_SCHEDULES}"
            )
    cluster = build_cluster(ns)

    if ns.sentinel:
        # the judge needs the aggregator it attaches to
        ns.monitor = True

    config_server_url = ns.config_server
    builtin = None
    if ns.builtin_config_port or (ns.monitor and not config_server_url):
        from kungfu_tpu.elastic.configserver import ConfigServer

        aggregator = None
        if ns.monitor:
            from kungfu_tpu.monitor.aggregator import (
                MIN_PUSH_PERIOD_S,
                STALE_PERIODS,
                ClusterAggregator,
            )

            if ns.monitor_interval > 0:
                # same floor the workers apply to the env value — a
                # below-floor interval must not give the aggregator a
                # tighter staleness clock than any worker can satisfy
                # (every healthy rank would render permanently STALE)
                ns.monitor_interval = max(ns.monitor_interval,
                                          MIN_PUSH_PERIOD_S)
            aggregator = ClusterAggregator(
                stale_after=(STALE_PERIODS * ns.monitor_interval
                             if ns.monitor_interval > 0 else None))
            if ns.sentinel:
                import os as _os

                from kungfu_tpu.monitor.sentinel import Sentinel
                from kungfu_tpu.utils.envs import SENTINEL_DIR

                root = _os.path.abspath(ns.sentinel)
                _os.makedirs(root, exist_ok=True)
                # publish the root so Sentinel.from_env picks up the
                # whole sentinel knob family (utils/envs.py) from the
                # environment
                _os.environ[SENTINEL_DIR] = root
                aggregator.attach_sentinel(Sentinel.from_env())
                _log.info("sentinel history -> %s "
                          "(replay: scripts/kfhist --dir %s --verdict)",
                          root, root)
        # -monitor with no config server still needs a push target: an
        # ephemeral builtin server carries the aggregator (port 0 = OS-
        # assigned, reflected in builtin.port)
        builtin = ConfigServer(port=ns.builtin_config_port, cluster=cluster,
                               aggregator=aggregator)
        builtin.start()
        config_server_url = f"http://127.0.0.1:{builtin.port}/get"
        _log.info("builtin config server at %s", config_server_url)
    elif ns.monitor:
        _log.info(
            "-monitor with an external config server: run it with "
            "`kf-config-server -monitor` so /push and /cluster exist there"
        )

    world = None
    if ns.device_world:
        hl = build_hostlist(ns)
        world = hl.gen_peer_list(hl.cap(), parse_port_range(ns.port_range))

    if ns.num_slices and ns.num_slices > 1:
        spawn_total = len(world) if world is not None else cluster.size()
        if spawn_total % ns.num_slices:
            raise SystemExit(
                f"kfrun: -num-slices {ns.num_slices} does not tile "
                f"{spawn_total} worker slot(s) — slices need identical "
                "worker counts")
        _log.info(
            "multislice: %d slice(s) x %d worker(s) (slice-major)",
            ns.num_slices, spawn_total // ns.num_slices,
        )

    if ns.tolerate_failures and (ns.auto_recover or ns.watch):
        # the monitored/watch runners have their own worker-death policy
        # (relaunch / respawn); silently ignoring the flag would promise
        # in-flight shrink and deliver a group kill instead
        raise SystemExit(
            "kfrun: -tolerate-failures applies to the simple runner only "
            "(-auto-recover relaunches on worker death, -w respawns via "
            "the config server)"
        )
    if ns.persist_dir and ns.restore_from:
        raise SystemExit(
            "kfrun: -persist-dir and -restore-from are exclusive — "
            "-restore-from already names the manifest root (and keeps "
            "persisting into it)"
        )
    if ns.restore_from and (ns.auto_recover or ns.watch):
        # both alternatives own worker-death policy; stacking them would
        # race two supervisors over the same corpses
        raise SystemExit(
            "kfrun: -restore-from is its own supervisor (cold restart "
            "from the durable manifest plane) and cannot combine with "
            "-auto-recover or -w"
        )
    chaos_envs = {}
    persist_root = ns.restore_from or ns.persist_dir
    if persist_root:
        import os as _os

        from kungfu_tpu.utils.envs import PERSIST_DIR, PERSIST_RESTORE

        persist_root = _os.path.abspath(persist_root)
        _os.makedirs(persist_root, exist_ok=True)
        chaos_envs[PERSIST_DIR] = persist_root
        if ns.restore_from:
            chaos_envs[PERSIST_RESTORE] = "1"
        _log.info("durable manifests -> %s", persist_root)
    if ns.monitor:
        from kungfu_tpu.monitor.aggregator import (
            PUSH_PERIOD_ENV,
            server_base,
        )
        from kungfu_tpu.utils.envs import (
            ENABLE_CLUSTER_MONITOR,
            ENABLE_MONITORING,
        )

        chaos_envs[ENABLE_CLUSTER_MONITOR] = "1"
        # byte rates for the snapshots; the net monitor is cheap
        chaos_envs[ENABLE_MONITORING] = "true"
        # online skew feeds on flight-recorder spans
        ns.trace = True
        if ns.monitor_interval > 0:
            chaos_envs[PUSH_PERIOD_ENV] = str(ns.monitor_interval)
        _log.info("live cluster view: scripts/kftop --server %s",
                  server_base(config_server_url))
    if ns.trace or ns.trace_dump:
        from kungfu_tpu.monitor.timeline import DUMP_ENV
        from kungfu_tpu.utils.trace import ENABLE_TRACE

        chaos_envs[ENABLE_TRACE] = "1"
        if ns.trace_dump:
            import os as _os

            dump_dir = _os.path.abspath(ns.trace_dump)
            _os.makedirs(dump_dir, exist_ok=True)
            chaos_envs[DUMP_ENV] = dump_dir
            _log.info("timeline dumps -> %s (merge: scripts/kftrace)",
                      dump_dir)
    if ns.chaos:
        # validate at the launcher so a typo'd spec dies here, not as a
        # mysteriously fault-free experiment in N worker logs
        from kungfu_tpu.chaos import SEED_ENV, SPEC_ENV, parse_spec

        try:
            parse_spec(ns.chaos)
        except ValueError as e:
            raise SystemExit(f"kfrun: bad -chaos spec: {e}") from None
        chaos_envs[SPEC_ENV] = ns.chaos
        if ns.chaos_seed is not None:
            chaos_envs[SEED_ENV] = str(ns.chaos_seed)
        _log.warning("fault injection armed: %s", ns.chaos)

    job = Job(
        prog=ns.prog,
        args=[a for a in ns.args if a != "--"],
        strategy=strategy,
        device_strategy=ns.device_strategy,
        config_server=config_server_url,
        log_dir=ns.logdir,
        parent=PeerID(ns.self_host, DEFAULT_RUNNER_PORT),
        backend=ns.backend,
        world=world,
        slices=max(ns.num_slices, 0),
        extra_envs=chaos_envs,
    )
    try:
        if ns.restore_from:
            from kungfu_tpu.runner.supervise import persist_run

            return persist_run(ns, cluster, job)
        if ns.auto_recover:
            from kungfu_tpu.runner.monitored import monitored_run

            return monitored_run(ns, cluster, job)
        if ns.watch:
            from kungfu_tpu.runner.watch import watch_run

            return watch_run(ns, cluster, job)
        return simple_run(ns, cluster, job)
    finally:
        if builtin is not None:
            builtin.stop()


if __name__ == "__main__":
    sys.exit(main())
