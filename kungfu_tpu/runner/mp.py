"""Single-machine multi-process launch without the CLI.

Parity with the reference's ``launch_multiprocess(f, np)``
(``kungfu/cmd/__init__.py:43-47``) + its ``SingleMachineEnv``
(``env/config.go:59``): spawn N worker processes on localhost, each with
the ``KF_*`` bootstrap contract set, running ``fn(rank, size)`` — the
programmatic alternative to ``kfrun`` for tests and notebooks.

Workers default to the CPU backend (each its own single-device world;
collectives ride the host-plane engine) — the same choice the CLI
launcher makes for multi-process single-host clusters, since N processes
cannot share one TPU chip.
"""

from __future__ import annotations

import errno
import multiprocessing as mp
import os
import socket
import time
from typing import Callable, Optional, Sequence

#: child exit code marking "my cluster port was stolen between the
#: parent's probe and my bind" — the one failure the parent retries
_PORT_RACE_EXIT = 97


def _free_ports(n: int) -> list:
    """Kernel-assigned ephemeral ports, held open together so concurrent
    launches get disjoint sets.  The close→child-bind window is still a
    TOCTOU against unrelated processes; a child losing that race exits
    with ``_PORT_RACE_EXIT`` and the parent retries with fresh ports."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _mp_entry(rank: int, ports: Sequence[int], fn, args, kwargs):
    from kungfu_tpu.utils import envs

    peers = ",".join(f"127.0.0.1:{p}" for p in ports)
    os.environ[envs.SELF_SPEC] = f"127.0.0.1:{ports[rank]}"
    os.environ[envs.INIT_PEERS] = peers
    # host-plane collectives; see module docstring
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("KF_JAX_PLATFORM", "cpu")
    try:
        fn(rank, len(ports), *args, **(kwargs or {}))
    except OSError as e:
        if e.errno == errno.EADDRINUSE:
            raise SystemExit(_PORT_RACE_EXIT)
        raise


def _stop_all(procs) -> None:
    for p in procs:
        if p.is_alive():
            p.terminate()
    for p in procs:
        if p.is_alive():
            p.join(5)
            if p.is_alive():  # SIGTERM ignored/masked: escalate
                p.kill()
                p.join(5)


def launch_multiprocess(fn: Callable, np_: int, *args,
                        timeout: Optional[float] = None, **kwargs) -> None:
    """Run ``fn(rank, size, *args, **kwargs)`` in ``np_`` spawned
    processes forming one localhost cluster.

    Fail-fast: the first worker that exits non-zero (or a shared
    ``timeout`` deadline expiring) terminates the rest — survivors
    blocked in a collective waiting for the dead peer must not hang the
    launcher.  Raises ``RuntimeError`` on any failure.  A worker that
    loses the ephemeral-port race retries the whole launch once with
    fresh ports (note: ranks that had already started may run twice).

    Uses the ``spawn`` start method — a fork would duplicate the parent's
    initialized JAX/backend state into every worker.
    """
    if np_ < 1:
        raise ValueError("np_ must be >= 1")
    for attempt in (0, 1):
        ports = _free_ports(np_)
        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(target=_mp_entry, args=(r, ports, fn, args, kwargs))
            for r in range(np_)
        ]
        for p in procs:
            p.start()
        deadline = None if timeout is None else time.monotonic() + timeout
        failure = None
        try:
            while True:
                codes = [p.exitcode for p in procs]
                bad = next((c for c in codes if c not in (None, 0)), None)
                if bad is not None:
                    failure = f"worker exited with code {bad}"
                    break
                if all(c == 0 for c in codes):
                    return  # every worker finished cleanly
                if deadline is not None and time.monotonic() > deadline:
                    failure = f"worker timed out after {timeout}s"
                    break
                time.sleep(0.05)
        finally:
            _stop_all(procs)
        if (attempt == 0
                and any(p.exitcode == _PORT_RACE_EXIT for p in procs)):
            continue  # stolen port: one retry with fresh ports
        raise RuntimeError(f"launch_multiprocess: {failure}")
