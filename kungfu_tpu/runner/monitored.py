"""MonitoredRun — failure detection + automatic restart.

Parity with the fork's ``runner/monitored.go:18-75``: run the job under the
heartbeat detector; when a worker is flagged down (begin-without-end past
the timeout, or the process dies), kill everything, rewrite ``--n-epochs``
to the remaining count, append ``--restart 1``, and relaunch.  Workers are
expected to checkpoint per epoch and reload on ``--restart 1`` (see
``examples/failure_recovery.py`` and :mod:`kungfu_tpu.checkpoint`).
"""

from __future__ import annotations

import re
import time
from typing import List, Optional

from kungfu_tpu.monitor.detector import (
    DEFAULT_DETECTOR_PORT,
    DetectorServer,
    query_detector,
)
from kungfu_tpu.monitor.signals import MONITOR_ADDR_ENV
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.runner.job import Job
from kungfu_tpu.runner.proc import kill_group, start_proc
from kungfu_tpu.utils.log import get_logger
from kungfu_tpu.utils.retry import jittered

_log = get_logger("monitored")

MAX_RESTARTS = 16


def parse_period(spec: str) -> float:
    """'10s' / '2m' / plain seconds."""
    m = re.fullmatch(r"(\d+(?:\.\d+)?)(s|m|ms)?", spec.strip())
    if not m:
        raise ValueError(f"bad period {spec!r}")
    v = float(m.group(1))
    unit = m.group(2) or "s"
    return v * {"s": 1.0, "m": 60.0, "ms": 0.001}[unit]


def patch_args(args: List[str], remaining_epochs: int, flag: str = "--n-epochs") -> List[str]:
    """Rewrite the epochs flag and mark the restart
    (reference ``monitored.go:52-66``)."""
    out = list(args)
    for i, a in enumerate(out):
        if a == flag and i + 1 < len(out):
            out[i + 1] = str(remaining_epochs)
            break
        if a.startswith(flag + "="):
            out[i] = f"{flag}={remaining_epochs}"
            break
    else:
        out += [flag, str(remaining_epochs)]
    # force --restart 1, overriding an explicit --restart 0 from the
    # original command line (a surviving 0 would skip checkpoint restore)
    for i, a in enumerate(out):
        if a == "--restart" and i + 1 < len(out):
            out[i + 1] = "1"
            break
        if a.startswith("--restart="):
            out[i] = "--restart=1"
            break
    else:
        out += ["--restart", "1"]
    return out


def find_epochs(args: List[str], flag: str = "--n-epochs") -> Optional[int]:
    for i, a in enumerate(args):
        if a == flag and i + 1 < len(args):
            return int(args[i + 1])
        if a.startswith(flag + "="):
            return int(a.split("=", 1)[1])
    return None


def _resolve_done_epochs(detector, self_host: str, main_host: str) -> int:
    """Completed-epoch count for the restart round.  Only the main host's
    detector receives heartbeats, so it is the authority; non-main hosts
    query it (retrying briefly — its down flag may lag a moment) and only
    fall back to the fan-out epoch if it is unreachable.  Every host must
    compute the SAME number or ranks relaunch with different --n-epochs
    and the job deadlocks in collectives."""
    if self_host == main_host:
        return detector.results.epoch_num or detector.min_epoch()
    deadline = time.time() + 10.0
    while time.time() < deadline:
        try:
            res = query_detector(main_host, detector.port)
            if res.get("down") or res.get("finished"):
                return int(res.get("epoch", 0))
        except OSError:
            pass
        # jittered: every non-main host polls the main detector at once
        # during a restart round
        time.sleep(jittered(0.5))
    _log.warning(
        "could not fetch authoritative epoch from %s; using fan-out value %d",
        main_host, detector.results.epoch_num,
    )
    return detector.results.epoch_num


def monitored_run(ns, cluster: Cluster, job: Job) -> int:
    period = parse_period(ns.auto_recover)
    self_host = ns.self_host
    hosts = cluster.runners.hosts()
    main_host = hosts[0]
    peer_hosts = [h for h in hosts if h != self_host]
    detector = DetectorServer(
        expected_ranks=cluster.size(),
        peer_hosts=peer_hosts,
        stall_timeout=period,
        compile_grace=parse_period(ns.compile_grace),
    ).start()
    job.extra_envs[MONITOR_ADDR_ENV] = f"{main_host}:{DEFAULT_DETECTOR_PORT}"

    total_epochs = find_epochs(job.args, ns.n_epochs_flag)
    args0 = list(job.args)
    restarts = 0
    epochs_done_total = 0  # cumulative across restart rounds
    try:
        while True:
            detector.reset(cluster.size())
            procs = job.create_procs(cluster, self_host)
            running = [start_proc(p, i, quiet=ns.quiet) for i, p in enumerate(procs)]
            _log.info(
                "round %d: %d workers (remaining args: %s)",
                restarts, len(running), " ".join(job.args),
            )
            while True:
                time.sleep(0.2)
                codes = [r.popen.poll() for r in running]
                if detector.results.finish_flag or all(c == 0 for c in codes):
                    # acceptance: success means the EPOCH CONTRACT was met,
                    # not merely that processes exited 0 — a restart round
                    # that silently retrained from scratch (restore
                    # failure) finishes "cleanly" having trained the wrong
                    # epochs (VERDICT round 1).  min_epoch is the min
                    # cumulative completed-epoch count across ranks.
                    # Only enforceable where epoch heartbeats actually
                    # arrive: the main host's detector (workers post only
                    # there).  Non-main hosts always see min_epoch()==0 and
                    # must not fail a healthy recovery; likewise a job that
                    # never signals epochs can still finish cleanly.
                    if total_epochs is not None and detector.min_epoch() > 0:
                        completed = max(detector.min_epoch(), epochs_done_total)
                        if completed < total_epochs:
                            _log.error(
                                "workers exited cleanly but completed only "
                                "%d/%d epochs — epoch contract violated",
                                completed, total_epochs,
                            )
                            return 1
                    _log.info("training finished")
                    return 0
                if any(c is not None and c != 0 for c in codes):
                    # local exit-code failure: other hosts' detectors only see
                    # heartbeat stalls, so fan the failure out explicitly to
                    # keep multi-host restart rounds in lockstep
                    detector.report_local_down()
                    break
                if detector.results.down_flag:
                    break
            for r in running:
                kill_group(r)
            for r in running:
                try:
                    r.popen.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    pass
            restarts += 1
            if restarts > MAX_RESTARTS:
                _log.error("giving up after %d restarts", MAX_RESTARTS)
                return 1
            # workers report *global* (cumulative) epoch numbers across
            # restarts, so the detector's min-epoch is cumulative too —
            # take the max, never add (adding double-counts on a second
            # failure and under-trains the job)
            done = _resolve_done_epochs(detector, self_host, main_host)
            epochs_done_total = max(epochs_done_total, done)
            if total_epochs is not None:
                remaining = max(total_epochs - epochs_done_total, 1)
                job.args = patch_args(args0, remaining, ns.n_epochs_flag)
            else:
                job.args = patch_args(args0, 1, ns.n_epochs_flag)
            _log.warning(
                "worker failure detected (%d epochs completed); restarting with %s",
                epochs_done_total, " ".join(job.args),
            )
    finally:
        detector.stop()
