"""Launcher / CLI — the ``kungfu-run`` analog (``kfrun``).

Parity with reference ``srcs/go/cmd/kungfu-run`` + ``srcs/go/kungfu/
{runner,job}`` + ``srcs/go/proc`` + ``srcs/go/utils/runner/local``:

* :mod:`kungfu_tpu.runner.proc` — subprocess specs with merged env,
  per-worker log files and colored prefix streaming;
* :mod:`kungfu_tpu.runner.job` — builds worker processes with the ``KF_*``
  bootstrap contract (device slotting included);
* :mod:`kungfu_tpu.runner.cli` — flag surface (``-np``, ``-H``,
  ``-strategy``, ``-w``, ``-config-server``, ``-auto-recover``, ...);
  dispatches SimpleRun / WatchRun (elastic) / MonitoredRun (auto-recover).

Invoke as ``python -m kungfu_tpu.runner.cli -np 4 python3 train.py`` or via
the ``kfrun`` console script.
"""

from kungfu_tpu.runner.proc import Proc, run_all
from kungfu_tpu.runner.job import Job

__all__ = ["Proc", "run_all", "Job"]
