"""Job → worker process construction.

Parity with reference ``srcs/go/kungfu/job/job.go:31-72``: build one Proc
per local worker with the full ``KF_*`` bootstrap env.  Device slotting:
where the reference assigned ``CUDA_VISIBLE_DEVICES`` per slot
(``cuda_visible_device.go``), the TPU build pins CPU-backend test workers
to their own virtual device world, and TPU workers get the standard
per-host TPU visibility (one worker process per host sees all local chips).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.plan.peer import PeerID
from kungfu_tpu.plan.peerlist import PeerList
from kungfu_tpu.plan.strategy import Strategy
from kungfu_tpu.runner.proc import Proc
from kungfu_tpu.utils import envs

#: jax.distributed coordinator port = first worker's (job-unique) peer
#: port + this offset, so two jobs sharing a host never collide
COORDINATOR_PORT_OFFSET = 20000


@dataclass
class Job:
    prog: str
    args: List[str]
    strategy: Strategy = Strategy.AUTO
    device_strategy: str = ""  # initial device allreduce schedule
    config_server: str = ""
    log_dir: str = ""
    parent: Optional[PeerID] = None
    extra_envs: Dict[str, str] = field(default_factory=dict)
    backend: str = "cpu"  # worker jax platform: "cpu" test clusters | "tpu"
    #: full provisioned worker-slot list (device-world elastic mode): the
    #: jax.distributed world is booted once over ALL slots; resize re-carves
    #: the mesh over the active subset (see Peer._carve_active_devices)
    world: Optional[PeerList] = None
    #: multislice worker partitioning (``kfrun -num-slices``): > 1 stamps
    #: each worker's env with its slice identity (slice-major contiguous,
    #: ``MEGASCALE_SLICE_ID = rank // ranks_per_slice`` — the tpu_pod
    #: emulation contract) plus ``MEGASCALE_NUM_SLICES``/``KF_SLICE_RANKS``
    #: so the peers build the hierarchical communicator and slice-granular
    #: elasticity with no user code change
    slices: int = 0
    #: ranks per slice, pinned at the FIRST spawn (0 = derive then): a
    #: watch-mode respawn after a resize passes the CURRENT cluster, and
    #: re-deriving from its size would stamp joiners with a different
    #: slice geometry than the incumbents hold
    slice_rps: int = 0
    job_start: float = field(default_factory=time.time)

    def new_proc(self, worker: PeerID, cluster: Cluster, version: int = 0) -> Proc:
        rank = cluster.workers.rank(worker)
        env = {
            envs.SELF_SPEC: str(worker),
            envs.INIT_PEERS: str(cluster.workers),
            envs.INIT_RUNNERS: str(cluster.runners),
            envs.INIT_CLUSTER_VERSION: str(version),
            envs.ALLREDUCE_STRATEGY: str(self.strategy),
            **({envs.DEVICE_STRATEGY: self.device_strategy}
               if self.device_strategy else {}),
            envs.JOB_START_TIMESTAMP: f"{self.job_start:.3f}",
            envs.PROC_START_TIMESTAMP: f"{time.time():.3f}",
        }
        if self.parent is not None:
            env[envs.PARENT_ID] = str(self.parent)
        if self.slices and self.slices > 1:
            # slice identity rides the STABLE spawn rank (world-slot index
            # in device-world mode): elastic reshuffles re-rank workers
            # but never move a process between slices
            spawn_list = self.world if self.world is not None else cluster.workers
            base_rank = (self.world.rank(worker) if self.world is not None
                         else rank)
            if self.slice_rps <= 0:
                # first spawn pins the geometry; later calls (watch-mode
                # respawns over a RESIZED cluster) reuse it — the slice
                # count follows the membership, ranks-per-slice never
                # changes (the elastic layer's whole-slice invariant)
                if len(spawn_list) % self.slices:
                    raise ValueError(
                        f"{len(spawn_list)} worker slot(s) cannot "
                        f"partition into {self.slices} slices")
                self.slice_rps = len(spawn_list) // self.slices
            rps = self.slice_rps
            if base_rank is None or len(spawn_list) % rps:
                raise ValueError(
                    f"{len(spawn_list)} worker slot(s) do not tile "
                    f"{rps}-rank slices")
            env[envs.MEGASCALE_NUM_SLICES] = str(len(spawn_list) // rps)
            env[envs.MEGASCALE_SLICE_ID] = str(base_rank // rps)
            env[envs.SLICE_RANKS] = str(rps)
        if self.config_server:
            env[envs.CONFIG_SERVER] = self.config_server
        if self.world is not None:
            # provisioned device world: EVERY slot (active or standby) joins
            # one jax.distributed world keyed by its stable world-slot index
            wr = self.world.rank(worker)
            if wr is None:
                raise ValueError(f"worker {worker} is not a provisioned world slot")
            first = self.world[0]
            coord_port = first.port + COORDINATOR_PORT_OFFSET
            if coord_port > 65535:
                coord_port = 20000 + (coord_port % 25536)
            env[envs.WORLD_PEERS] = str(self.world)
            env[envs.COORDINATOR] = f"{first.host}:{coord_port}"
            env[envs.NUM_PROCESSES] = str(len(self.world))
            env[envs.PROCESS_ID] = str(wr)
            if self.backend == "cpu":
                env["JAX_PLATFORMS"] = "cpu"
                env["KF_JAX_PLATFORM"] = "cpu"
                # extra_envs is merged last and may override this default
                env[envs.NUM_DEVICES] = "1"
        elif self.backend == "cpu":
            # each worker is its own single-device CPU world; collectives
            # run on the host channel (CollectiveEngine).  KF_JAX_PLATFORM
            # is applied via jax.config at kf.init() time — some
            # environments override the JAX_PLATFORMS env var in
            # sitecustomize, so the env var alone is not reliable.
            env["JAX_PLATFORMS"] = "cpu"
            env["KF_JAX_PLATFORM"] = "cpu"
        else:
            # TPU backend: workers form one jax.distributed world (device
            # plane over ICI/DCN — the NCCL-bootstrap analog).  Coordinator
            # is the first worker's host; peer.start() runs
            # jax.distributed.initialize from these envs.
            n = len(cluster.workers)
            if n > 1 and rank is not None:
                first = cluster.workers[0]
                coord_port = first.port + COORDINATOR_PORT_OFFSET
                if coord_port > 65535:
                    # user-supplied port ranges above 45535 would derive an
                    # impossible port and fail at jax.distributed init —
                    # wrap back into the dynamic range instead
                    coord_port = 20000 + (coord_port % 25536)
                env[envs.COORDINATOR] = f"{first.host}:{coord_port}"
                env[envs.NUM_PROCESSES] = str(n)
                env[envs.PROCESS_ID] = str(rank)
        # make the kungfu_tpu package importable in workers regardless of cwd
        import os as _os

        import kungfu_tpu as _pkg

        pkg_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(_pkg.__file__)))
        existing = _os.environ.get("PYTHONPATH", "")
        env["PYTHONPATH"] = pkg_root + (_os.pathsep + existing if existing else "")
        env.update(self.extra_envs)
        return Proc(
            name=f"worker-{rank}" if rank is not None else f"worker-{worker.port}",
            prog=self.prog,
            args=list(self.args),
            envs=env,
            log_dir=self.log_dir,
        )

    def create_procs(self, cluster: Cluster, self_host: str, version: int = 0) -> List[Proc]:
        """Procs for all workers on ``self_host``
        (reference ``job.go:74`` CreateProcs).  In device-world mode ALL
        provisioned slots are spawned — slots outside the initial worker
        list boot as standby peers."""
        spawn_list = self.world if self.world is not None else cluster.workers
        return [
            self.new_proc(w, cluster, version)
            for w in spawn_list
            if w.host == self_host
        ]
