"""SSH-based multi-host launch tools.

Parity with the reference's ``kungfu-distribute`` (run one command on
every host of ``-H`` in parallel over SSH,
``cmd/kungfu-distribute/kungfu-distribute.go:76-88``) and ``kungfu-rrun``
(launch a full static job remotely: one runner per host, each told who it
is, ``cmd/kungfu-rrun/rrun.go:18-44`` +
``utils/runner/remote/remote.go:22-60``).  Where the reference opens
go-crypto SSH sessions, we drive the system ``ssh`` binary through the
same prefix-colored process runner the local launcher uses — TPU pods
are reached through plain SSH, and subprocess-based SSH keeps auth
(agents, ProxyCommand, OS config) out of scope.

``--ssh`` swaps the transport binary; tests point it at a local shim
that executes the command in-process, which is how "multi-host" launch
is tested without machines (the reference's docker-compose trick, one
level cheaper).
"""

from __future__ import annotations

import argparse
import shlex
import sys
from typing import List, Optional

from kungfu_tpu.plan.hostspec import HostList, parse_host_list
from kungfu_tpu.runner.proc import Proc, run_all
from kungfu_tpu.utils.log import get_logger

_log = get_logger("remote")


def ssh_proc(
    host: str,
    command: List[str],
    user: str = "",
    ssh_prog: str = "ssh",
    name: Optional[str] = None,
    log_dir: str = "",
) -> Proc:
    """One remote command as a Proc: ``ssh [user@]host -- <command>``."""
    target = f"{user}@{host}" if user else host
    script = " ".join(shlex.quote(a) for a in command)
    return Proc(
        name=name or host,
        prog=ssh_prog,
        args=[target, script],
        log_dir=log_dir,
    )


def remote_run_all(
    procs: List[Proc], quiet: bool = False, timeout: Optional[float] = None
) -> int:
    """Run all remote procs in parallel, fail-fast; 0 iff all succeeded."""
    codes = run_all(procs, quiet=quiet, timeout=timeout)
    failed = [p.name for p, c in zip(procs, codes) if c != 0]
    if failed:
        _log.error("%d remote tasks failed: %s", len(failed), ", ".join(failed))
        return 1
    return 0


# -- kf-distribute ---------------------------------------------------------

def main_distribute(argv: Optional[List[str]] = None) -> int:
    """Run the same command once on every host of -H (file push, setup,
    cleanup — the reference uses it to distribute binaries)."""
    p = argparse.ArgumentParser(
        prog="kf-distribute",
        description="run a command on every host of -H in parallel over SSH",
    )
    p.add_argument("-H", dest="hosts", required=True,
                   help="host spec list ip:slots[:public_addr],...")
    p.add_argument("-u", dest="user", default="", help="ssh user name")
    p.add_argument("-logdir", default="", help="per-host log files directory")
    p.add_argument("-timeout", type=float, default=0.0)
    p.add_argument("-q", dest="quiet", action="store_true")
    p.add_argument("--ssh", dest="ssh_prog", default="ssh",
                   help="ssh-compatible transport binary")
    p.add_argument("prog")
    p.add_argument("args", nargs=argparse.REMAINDER)
    ns = p.parse_args(argv)

    hl = parse_host_list(ns.hosts)
    procs = [
        ssh_proc(
            h.public_addr or h.ip,
            [ns.prog] + ns.args,
            user=ns.user,
            ssh_prog=ns.ssh_prog,
            name=h.ip,
            log_dir=ns.logdir,
        )
        for h in hl.hosts
    ]
    _log.info("distributing %s to %d hosts", ns.prog, len(procs))
    return remote_run_all(procs, quiet=ns.quiet, timeout=ns.timeout or None)


# -- kf-rrun ---------------------------------------------------------------

def _runner_command(
    ns, hl: HostList, self_ip: str, python: str
) -> List[str]:
    cmd = [
        python, "-m", "kungfu_tpu.runner.cli",
        "-np", str(ns.np),
        "-H", str(hl),
        "-self", self_ip,
        "-strategy", ns.strategy,
        "-port-range", ns.port_range,
    ]
    if getattr(ns, "device_strategy", ""):
        cmd += ["-device-strategy", ns.device_strategy]
    if ns.logdir:
        cmd += ["-logdir", ns.logdir]
    if ns.quiet:
        cmd += ["-q"]
    cmd += [ns.prog] + ns.args
    return cmd


def main_rrun(argv: Optional[List[str]] = None) -> int:
    """Launch a full static job: one launcher per host over SSH, each
    pinned to its own -self identity (reference ``kungfu-rrun``)."""
    p = argparse.ArgumentParser(
        prog="kf-rrun",
        description="launch a multi-host job: one kfrun per host over SSH",
    )
    p.add_argument("-np", type=int, required=True, help="total workers")
    p.add_argument("-H", dest="hosts", required=True,
                   help="host spec list ip:slots[:public_addr],...")
    p.add_argument("-strategy", default="AUTO")
    p.add_argument("-device-strategy", dest="device_strategy", default="",
                   help="initial device allreduce schedule for all hosts")
    p.add_argument("-port-range", dest="port_range", default="10000-11000")
    p.add_argument("-u", dest="user", default="", help="ssh user name")
    p.add_argument("-logdir", default="", help="remote per-worker log dir")
    p.add_argument("-timeout", type=float, default=0.0)
    p.add_argument("-q", dest="quiet", action="store_true")
    p.add_argument("--ssh", dest="ssh_prog", default="ssh")
    p.add_argument("--python", default="python3",
                   help="python interpreter to invoke on the remote hosts")
    p.add_argument("prog")
    p.add_argument("args", nargs=argparse.REMAINDER)
    ns = p.parse_args(argv)

    hl = parse_host_list(ns.hosts)
    if ns.np > hl.cap():
        _log.error("-np %d exceeds host capacity %d", ns.np, hl.cap())
        return 1
    procs = [
        ssh_proc(
            h.public_addr or h.ip,
            _runner_command(ns, hl, h.ip, ns.python),
            user=ns.user,
            ssh_prog=ns.ssh_prog,
            name=h.ip,
            log_dir="",
        )
        for h in hl.hosts
    ]
    _log.info("launching %d workers across %d hosts", ns.np, len(procs))
    return remote_run_all(procs, quiet=False, timeout=ns.timeout or None)


if __name__ == "__main__":
    prog = sys.argv[0]
    if "rrun" in prog:
        sys.exit(main_rrun())
    sys.exit(main_distribute())
