"""Variable broadcast initialization.

Parity with reference ``kungfu/tensorflow/initializer`` (
``BroadcastGlobalVariablesOp/Hook/Callback``) and ``torch
broadcast_parameters``: make every worker start from (or re-sync to) rank
0's weights — at job start, and again after every elastic resize
(reference ``hooks/elastic.py:54``).

Two paths:

* :func:`broadcast_parameters` — host-side, process-to-process over the
  host channel (works while no mesh exists, e.g. right after a resize).
* :func:`device_broadcast` — in-jit ``ops.broadcast`` over the mesh axis
  (for stacked/simulated peers or per-device states).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kungfu_tpu import ops
from kungfu_tpu.ops.fuse import defuse, fuse


def broadcast_parameters(params, peer=None, root: int = 0, name: str = "bcast-params"):
    """Replace every worker's params with rank ``root``'s (host channel)."""
    if peer is None:
        from kungfu_tpu.python import init as _init

        peer = _init()
    if peer.size() <= 1 or peer.channel is None:
        return params
    buf, spec = fuse(params, dtype=jnp.float32)
    data = np.asarray(buf).tobytes() if peer.rank() == root else None
    # star broadcast rooted at `root`: reuse rank-0 rooted primitive by
    # rotating the peer list so `root` is first
    workers = peer.cluster.workers
    order = list(range(len(workers)))
    order = order[root:] + order[:root]
    rotated = workers.select(order)
    blob = peer.channel.broadcast_bytes(
        data, rotated, name=f"{name}.v{peer.cluster_version}"
    )
    arr = jnp.asarray(np.frombuffer(blob, dtype=np.float32).copy())
    return defuse(arr, spec)


def device_broadcast(params, axis, root: int = 0):
    """In-jit broadcast of a param pytree from peer ``root`` over ``axis``."""
    return ops.broadcast(params, axis, root=root)


def resync_parameters(params, peer=None, comm=None, root: int = 0):
    """Post-resize state re-sync, riding the DEVICE plane when a mesh
    exists (reference ``hooks/elastic.py:54`` re-broadcast, made
    TPU-native): an in-world resize leaves survivors and joiners sharing
    the NEW mesh epoch, so rank ``root``'s weights move over ICI instead
    of the host TCP channel.  Returns ``params`` replicated on the mesh,
    ready for the next compiled step.

    * single-controller mesh (simulated peers / one process): pure
      runtime replication — each leaf is ``device_put`` to every mesh
      device and assembled with ``make_array_from_single_device_arrays``;
      NO XLA program compiles, so the resize transition doesn't pay a
      per-epoch broadcast compile;
    * multi-controller mesh: one compiled device broadcast per mesh
      epoch (fuse → ``Communicator.broadcast`` → defuse), then a
      replicated placement;
    * no mesh (detached / standby / single-process): host-plane
      :func:`broadcast_parameters` fallback.
    """
    if comm is None and peer is not None:
        try:
            comm = peer.communicator()
        except RuntimeError:
            comm = None
    if comm is None or comm.size <= 1:
        if comm is not None:
            # 1-peer mesh: nothing to sync, just place on it
            sh = comm.replicated_sharding()
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(jnp.asarray(a), sh), params
            )
        return broadcast_parameters(params, peer, root=root)

    if not comm._multiproc:
        # every simulated peer lives in this process: "root's weights"
        # are the ones passed in — replicate them by runtime transfer
        sh = comm.replicated_sharding()
        devs = list(comm.mesh.devices.ravel())

        def leaf(a):
            a = jnp.asarray(a)
            bufs = [jax.device_put(a, d) for d in devs]
            return jax.make_array_from_single_device_arrays(a.shape, sh, bufs)

        return jax.tree_util.tree_map(leaf, params)

    # multi-controller: the joiners' stale values must be overwritten by
    # root's over the mesh — a compiled broadcast, amortized per epoch.
    # broadcast_value sends ONE fused row per process (each local device
    # gets it by runtime device_put), so a resize costs 1x model host RAM,
    # not the (n_local+1)x of stacking the eager collective convention.
    # Peer rank -> device slot: the mesh is carved in worker-rank order,
    # so the root worker's jax process (its provisioned world slot when a
    # world exists, its spawn rank otherwise) owns a contiguous run of
    # flat slots starting at first_slot_of_process.
    root_proc = root
    if peer is not None:
        world = getattr(peer.config, "world_peers", None)
        if world is not None:
            wr = world.rank(peer.cluster.workers[root])
            if wr is None:
                raise ValueError(
                    f"resync root {root} is outside the provisioned world")
            root_proc = wr
    buf, spec = fuse(params, dtype=jnp.float32)
    out = comm.broadcast_value(
        np.asarray(buf), comm.first_slot_of_process(root_proc))
    sh = comm.replicated_sharding()
    synced = defuse(jnp.asarray(out), spec)
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(np.asarray(a), sh), synced
    )
