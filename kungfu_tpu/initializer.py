"""Variable broadcast initialization.

Parity with reference ``kungfu/tensorflow/initializer`` (
``BroadcastGlobalVariablesOp/Hook/Callback``) and ``torch
broadcast_parameters``: make every worker start from (or re-sync to) rank
0's weights — at job start, and again after every elastic resize
(reference ``hooks/elastic.py:54``).

Two paths:

* :func:`broadcast_parameters` — host-side, process-to-process over the
  host channel (works while no mesh exists, e.g. right after a resize).
* :func:`device_broadcast` — in-jit ``ops.broadcast`` over the mesh axis
  (for stacked/simulated peers or per-device states).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kungfu_tpu import ops
from kungfu_tpu.ops.fuse import defuse, fuse


def broadcast_parameters(params, peer=None, root: int = 0, name: str = "bcast-params"):
    """Replace every worker's params with rank ``root``'s (host channel)."""
    if peer is None:
        from kungfu_tpu.python import init as _init

        peer = _init()
    if peer.size() <= 1 or peer.channel is None:
        return params
    buf, spec = fuse(params, dtype=jnp.float32)
    data = np.asarray(buf).tobytes() if peer.rank() == root else None
    # star broadcast rooted at `root`: reuse rank-0 rooted primitive by
    # rotating the peer list so `root` is first
    workers = peer.cluster.workers
    order = list(range(len(workers)))
    order = order[root:] + order[:root]
    rotated = workers.select(order)
    blob = peer.channel.broadcast_bytes(
        data, rotated, name=f"{name}.v{peer.cluster_version}"
    )
    arr = jnp.asarray(np.frombuffer(blob, dtype=np.float32).copy())
    return defuse(arr, spec)


def device_broadcast(params, axis, root: int = 0):
    """In-jit broadcast of a param pytree from peer ``root`` over ``axis``."""
    return ops.broadcast(params, axis, root=root)
