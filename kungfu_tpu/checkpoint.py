"""Checkpoint / resume.

The reference has no central checkpoint engine — recovery leans on (1)
live rank-0 re-broadcast, (2) the in-memory versioned store, (3)
example-level ``tf.keras``/np.savez checkpoints (SURVEY §5.4).  The TPU
build makes recovery real with a small checkpoint API used by the
auto-recovery path: param/opt-state pytrees + step/epoch counters saved
per epoch, newest-wins restore, atomic writes.

Two backends behind one API (save/restore/latest_step/prune):

* ``orbax`` — sharding-aware PyTree checkpointing via
  :mod:`orbax.checkpoint` (the standard JAX checkpoint library); used
  when available.  Directories ``ckpt_<step>.orbax``.
* ``npz`` — atomic numpy ``.npz`` of the flattened pytree; dependency-
  free fallback, identical on CPU test clusters and TPU hosts.

Select with ``KF_TPU_CKPT_BACKEND`` (``auto`` | ``orbax`` | ``npz``).
Restore reads whichever format the newest checkpoint has, so a job can
switch backends mid-history.

``save_checkpoint_async`` overlaps the file IO with training: the host
snapshot is taken synchronously (copy — safe against donated-buffer
reuse), the write runs on one ordered background thread; call
``wait_pending_checkpoints()`` before a shutdown/restart that relies on
the newest checkpoint being durable.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Optional, Tuple

import jax
import numpy as np

from kungfu_tpu.utils.log import get_logger

_log = get_logger("checkpoint")


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except ImportError:  # pragma: no cover - baked into the TPU image
        return None


def _backend() -> str:
    mode = os.environ.get("KF_TPU_CKPT_BACKEND", "auto").lower()
    if mode == "orbax" and _orbax() is None:
        raise RuntimeError(
            "KF_TPU_CKPT_BACKEND=orbax but orbax.checkpoint is not importable"
        )
    if mode in ("orbax", "npz"):
        return mode
    return "orbax" if _orbax() is not None else "npz"


def _flatten(tree) -> Tuple[list, Any]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def _step_entries(ckpt_dir: str):
    """[(step, filename)] of every checkpoint in either format."""
    out = []
    for name in os.listdir(ckpt_dir):
        if not name.startswith("ckpt_"):
            continue
        stem = name[5:]
        for suffix in (".npz", ".orbax"):
            if stem.endswith(suffix):
                try:
                    out.append((int(stem[: -len(suffix)]), name))
                except ValueError:
                    pass
    return out


def save_checkpoint(ckpt_dir: str, step: int, tree, meta: Optional[dict] = None) -> str:
    """Atomically write ``tree`` (+ meta) as checkpoint ``step``; returns
    the checkpoint path."""
    os.makedirs(ckpt_dir, exist_ok=True)
    if _backend() == "orbax":
        return _save_orbax(ckpt_dir, step, tree, meta)
    return _save_npz(ckpt_dir, step, tree, meta)


def _to_npz_safe(arr: np.ndarray) -> np.ndarray:
    """bfloat16 (ml_dtypes) round-trips through .npz as raw void bytes
    numpy can't cast back — store it widened to f32 (lossless); restore
    casts to the like-tree dtype anyway."""
    if arr.dtype.name == "bfloat16" or arr.dtype.kind == "V":
        return arr.astype(np.float32)
    return arr


def _save_npz(ckpt_dir: str, step: int, tree, meta: Optional[dict]) -> str:
    leaves, _ = _flatten(tree)
    arrays = {f"leaf_{i}": _to_npz_safe(np.asarray(l)) for i, l in enumerate(leaves)}
    path = os.path.join(ckpt_dir, f"ckpt_{step:08d}.npz")
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta or {}), **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    _log.info("saved checkpoint %s", path)
    return path


def _save_orbax(ckpt_dir: str, step: int, tree, meta: Optional[dict]) -> str:
    ocp = _orbax()
    path = os.path.join(os.path.abspath(ckpt_dir), f"ckpt_{step:08d}.orbax")
    # orbax writes into a temp dir and renames — atomic like the npz path;
    # an aborted earlier attempt must be cleared first
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, tree)
    # meta as a sidecar (orbax pytrees are arrays; job metadata is JSON)
    with open(path + ".meta.json", "w") as f:
        json.dump(meta or {}, f)
    _log.info("saved checkpoint %s", path)
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [s for s, _ in _step_entries(ckpt_dir)]
    return max(steps) if steps else None


def restore_checkpoint(ckpt_dir: str, like_tree, step: Optional[int] = None):
    """Restore the newest (or given-step) checkpoint into the structure of
    ``like_tree``.  Returns ``(tree, step, meta)`` or ``None``."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            return None
    orbax_path = os.path.join(os.path.abspath(ckpt_dir), f"ckpt_{step:08d}.orbax")
    if os.path.isdir(orbax_path):
        return _restore_orbax(orbax_path, like_tree, step)
    path = os.path.join(ckpt_dir, f"ckpt_{step:08d}.npz")
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
        leaves, treedef = _flatten(like_tree)
        restored = []
        for i, like in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            restored.append(np.asarray(arr, dtype=np.asarray(like).dtype))
    tree = jax.tree_util.tree_unflatten(treedef, restored)
    _log.info("restored checkpoint %s (meta=%s)", path, meta)
    return tree, step, meta


def _restore_orbax(path: str, like_tree, step: int):
    ocp = _orbax()
    if ocp is None:
        raise RuntimeError(
            f"checkpoint {path} was written by the orbax backend but "
            "orbax.checkpoint is not importable in this environment"
        )
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path)
    meta = {}
    if os.path.exists(path + ".meta.json"):
        with open(path + ".meta.json") as f:
            meta = json.load(f)
    # conform dtypes/structure to like_tree (orbax restores as numpy).
    # Structure drift (model config changed since the checkpoint) must
    # fail loudly like the npz path does on a missing key — zip() would
    # silently truncate or mispair parameters.  Compare KEY PATHS, not
    # just leaf counts: a renamed/reordered layer keeps the count equal
    # while changing which array lands where.  (Paths are compared as
    # strings so a custom pytree restored as a plain dict still matches
    # when its keys agree.)
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(like_tree)
    got_path_leaves, _ = jax.tree_util.tree_flatten_with_path(restored)
    want_paths = [jax.tree_util.keystr(p) for p, _ in path_leaves]
    got_paths = [jax.tree_util.keystr(p) for p, _ in got_path_leaves]
    if got_paths != want_paths:
        missing = sorted(set(want_paths) - set(got_paths))
        extra = sorted(set(got_paths) - set(want_paths))
        raise ValueError(
            f"checkpoint {path} structure does not match the restore "
            f"target (missing: {missing[:5]}, unexpected: {extra[:5]}) — "
            "model structure changed since this checkpoint was written"
        )
    leaves = [l for _, l in path_leaves]
    got_leaves = [l for _, l in got_path_leaves]
    conformed = [
        np.asarray(g, dtype=np.asarray(like).dtype)
        for g, like in zip(got_leaves, leaves)
    ]
    tree = jax.tree_util.tree_unflatten(treedef, conformed)
    _log.info("restored checkpoint %s (meta=%s)", path, meta)
    return tree, step, dict(meta)


# -- async save -----------------------------------------------------------
# one background writer: successive checkpoints must land in order, and a
# second writer would only contend on the same disk
_writer_lock = threading.Lock()
_writer: Optional[ThreadPoolExecutor] = None
_pending: list = []


def _get_writer() -> ThreadPoolExecutor:
    global _writer
    with _writer_lock:
        if _writer is None:
            _writer = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kf-ckpt"
            )
        return _writer


def save_checkpoint_async(ckpt_dir: str, step: int, tree,
                          meta: Optional[dict] = None) -> "Future[str]":
    """Overlap the checkpoint's file IO with training.

    The device→host materialization happens HERE, synchronously — the
    snapshot must be taken before the train loop's next donated step
    invalidates the buffers — then serialization + the atomic write run
    on a single background writer thread (ordered across calls).

    Returns a ``Future[str]`` resolving to the checkpoint path;
    ``.result()`` re-raises any write failure.  Call
    :func:`wait_pending_checkpoints` before relying on the newest
    checkpoint existing (e.g. at shutdown or before a restart-recovery
    exit).

    **Durability-before-report**: anything that advertises progress to a
    recovery mechanism (the ``epoch`` heartbeat signal, a progress file)
    must wait for THIS save's future first — observed failure mode: an
    epoch signal sent while its checkpoint was still in flight made the
    post-crash restart resume from an epoch whose file never landed.
    Overlap is for saves whose completion nothing reports yet (mid-epoch
    step checkpoints, periodic safety snapshots).
    """
    def snapshot(leaf):
        # np.array (copy), not np.asarray: a leaf that is ALREADY numpy
        # would alias the caller's buffer and a later in-place mutation
        # (donated step reuse) would corrupt the in-flight write
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            raise ValueError(
                "save_checkpoint_async snapshots to host numpy and "
                "requires fully-addressable arrays; for multi-host "
                "sharded state use orbax's async checkpointing directly"
            )
        return np.array(leaf)

    host_tree = jax.tree_util.tree_map(snapshot, tree)
    fut = _get_writer().submit(save_checkpoint, ckpt_dir, step, host_tree, meta)
    with _writer_lock:
        # prune only SUCCESSFUL finished writes — a failed one must stay
        # queued so wait_pending_checkpoints still surfaces its error
        _pending[:] = [f for f in _pending
                       if not f.done() or f.exception() is not None]
        _pending.append(fut)
    return fut


def wait_pending_checkpoints(timeout: Optional[float] = None) -> None:
    """Block until every async checkpoint issued so far is durable.

    Waits for ALL pending writes before raising the FIRST write failure
    (an early failure must not leave later in-flight saves untracked);
    ``timeout`` is one overall deadline, and futures still running when
    it expires are re-queued before ``TimeoutError`` propagates."""
    with _writer_lock:
        pending = list(_pending)
        _pending.clear()
    deadline = None if timeout is None else time.monotonic() + timeout
    first_err: Optional[BaseException] = None
    for i, f in enumerate(pending):
        left = (None if deadline is None
                else max(0.0, deadline - time.monotonic()))
        try:
            f.result(left)
        except _FutureTimeout:
            with _writer_lock:
                _pending.extend(pending[i:])  # still in flight: re-track
            raise
        except BaseException as e:  # noqa: BLE001 — surfaced after all wait
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


# -- in-memory last-committed-step snapshot --------------------------------
class StepSnapshot:
    """The replay point for in-flight shrink recovery.

    Disk checkpoints are epoch-grained and exist for *process-death*
    recovery; shrink-to-survivors keeps the process alive, so it only
    needs the last **committed step boundary** — params/opt-state as of
    the last step every peer finished — held in host memory.  The train
    loop calls :meth:`commit` after each applied step (a host copy of
    the leaves, no device sync beyond the transfer, no file IO); after a
    peer failure the survivors restore from :meth:`last` and re-run the
    interrupted step over the shrunk cluster instead of restoring a disk
    checkpoint from possibly many epochs ago.

    Leaves are snapshotted with ``np.array`` (a copy) on commit **and**
    on restore, so neither a later donated-buffer reuse nor the caller
    mutating a restored tree can corrupt the held boundary.

    Survivors of a shrink may hold *different* committed steps (the dead
    peer can have fed some survivors before dying, letting them finish
    the step the others lost) — :meth:`serialize`/:meth:`adopt` let the
    recovery protocol broadcast the leader's boundary so every survivor
    replays from ONE agreed (step, state), instead of livelocking on
    mismatched rendezvous names (see ``elastic/shrink.py``).

    A module-level default instance (:data:`step_snapshot`) serves the
    common one-trainer-per-process case.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._step: Optional[int] = None
        self._tree = None
        self._meta: Optional[dict] = None

    def commit(self, step: int, tree, meta: Optional[dict] = None) -> None:
        """Record ``tree`` as the committed state *after* step ``step``.

        Leaves must be locally addressable — this snapshot is a FULL host
        copy, the thing the shrink leader can broadcast whole.  State that
        is deliberately 1/n-sharded across processes (ZeRO optimizer
        shards, stage-3 parameter shards) must ride its own
        :class:`kungfu_tpu.elastic.reshard.ZeroBoundary` instead, whose
        re-carve is leaderless by design; commit the replicated leaves
        (params, step counters) here and the shard there."""
        def host_copy(l):
            if (isinstance(l, jax.Array) and not l.is_fully_addressable
                    and not l.is_fully_replicated):
                raise ValueError(
                    "StepSnapshot.commit needs fully-addressable leaves "
                    "(it is a full host copy, broadcast whole on replay); "
                    "ZeRO-sharded state belongs in "
                    "kungfu_tpu.elastic.reshard.ZeroBoundary — see "
                    "docs/zero.md"
                )
            return np.array(l)

        host_tree = jax.tree_util.tree_map(host_copy, tree)
        with self._lock:
            self._step = step
            self._tree = host_tree
            self._meta = dict(meta) if meta else {}

    def last(self) -> Optional[Tuple[int, Any, dict]]:
        """``(step, tree, meta)`` of the newest committed boundary, or
        ``None`` when nothing was committed yet (caller falls back to the
        disk-checkpoint restart path)."""
        with self._lock:
            if self._step is None:
                return None
            tree = jax.tree_util.tree_map(lambda l: np.array(l), self._tree)
            return self._step, tree, dict(self._meta)

    def step(self) -> Optional[int]:
        with self._lock:
            return self._step

    def clear(self) -> None:
        with self._lock:
            self._step = None
            self._tree = None
            self._meta = None

    # -- wire form (shrink-recovery replay-point agreement) ---------------
    def serialize(self) -> bytes:
        """Self-describing wire form of the committed boundary (``b""``
        when empty): a JSON header (step, meta, per-leaf dtype-name +
        shape) followed by the raw leaf bytes — raw, not ``.npz``, so an
        ml_dtypes leaf (bfloat16) round-trips bit-exactly."""
        snap = self.last()
        if snap is None:
            return b""
        step, tree, meta = snap
        leaves, _ = jax.tree_util.tree_flatten(tree)
        arrs = [np.ascontiguousarray(l) for l in leaves]
        head = json.dumps({
            "step": step,
            "meta": meta,
            "leaves": [{"dtype": a.dtype.name, "shape": list(a.shape)}
                       for a in arrs],
        }).encode()
        import struct

        return b"".join(
            [struct.pack("<I", len(head)), head] + [a.tobytes() for a in arrs]
        )

    def adopt(self, blob: bytes) -> Optional[Tuple[int, Any, dict]]:
        """Replace this snapshot's boundary with a serialized one (the
        shrink leader's) and return it as ``(step, tree, meta)`` — the
        tree is rebuilt in THIS snapshot's committed structure, so the
        caller must have committed at least once (the train loops that
        reach shrink recovery have; a never-committed snapshot raises
        ``ValueError`` and the caller falls back to no-replay)."""
        if not blob:
            return None
        import struct

        (hlen,) = struct.unpack_from("<I", blob)
        off = 4
        head = json.loads(blob[off:off + hlen].decode())
        off += hlen
        leaves = []
        for spec in head["leaves"]:
            dt = _np_dtype(spec["dtype"])
            n = int(np.prod(spec["shape"], dtype=np.int64)) * dt.itemsize
            leaves.append(
                np.frombuffer(blob[off:off + n], dtype=dt)
                .reshape(spec["shape"]).copy()
            )
            off += n
        with self._lock:
            if self._tree is None:
                raise ValueError(
                    "cannot adopt a replay point without a local committed "
                    "structure to rebuild it in"
                )
            _, treedef = jax.tree_util.tree_flatten(self._tree)
        if treedef.num_leaves != len(leaves):
            raise ValueError(
                f"replay point has {len(leaves)} leaves, local structure "
                f"has {treedef.num_leaves} — peers run different models?"
            )
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
        self.commit(int(head["step"]), tree, head.get("meta") or {})
        return self.last()


def _np_dtype(name: str) -> np.dtype:
    """dtype from its ``.name`` — including ml_dtypes extension types
    (``bfloat16``) that ``np.dtype(str)`` alone cannot resolve."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


#: default snapshot for the one-trainer-per-process case
step_snapshot = StepSnapshot()


def prune_checkpoints(ckpt_dir: str, keep: int = 3) -> None:
    if not os.path.isdir(ckpt_dir):
        return
    entries = sorted(_step_entries(ckpt_dir))
    for _, name in entries[:-keep]:
        full = os.path.join(ckpt_dir, name)
        if os.path.isdir(full):
            shutil.rmtree(full)
            if os.path.exists(full + ".meta.json"):
                os.unlink(full + ".meta.json")
        else:
            os.unlink(full)
