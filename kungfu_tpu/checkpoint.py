"""Checkpoint / resume.

The reference has no central checkpoint engine — recovery leans on (1)
live rank-0 re-broadcast, (2) the in-memory versioned store, (3)
example-level ``tf.keras``/np.savez checkpoints (SURVEY §5.4).  The TPU
build makes recovery real with a small checkpoint API used by the
auto-recovery path: param/opt-state pytrees + step/epoch counters saved
per epoch, newest-wins restore, atomic writes.

Two backends behind one API (save/restore/latest_step/prune):

* ``orbax`` — sharding-aware PyTree checkpointing via
  :mod:`orbax.checkpoint` (the standard JAX checkpoint library); used
  when available.  Directories ``ckpt_<step>.orbax``.
* ``npz`` — atomic numpy ``.npz`` of the flattened pytree; dependency-
  free fallback, identical on CPU test clusters and TPU hosts.

Select with ``KF_TPU_CKPT_BACKEND`` (``auto`` | ``orbax`` | ``npz``).
Restore reads whichever format the newest checkpoint has, so a job can
switch backends mid-history.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Optional, Tuple

import jax
import numpy as np

from kungfu_tpu.utils.log import get_logger

_log = get_logger("checkpoint")


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except ImportError:  # pragma: no cover - baked into the TPU image
        return None


def _backend() -> str:
    mode = os.environ.get("KF_TPU_CKPT_BACKEND", "auto").lower()
    if mode == "orbax" and _orbax() is None:
        raise RuntimeError(
            "KF_TPU_CKPT_BACKEND=orbax but orbax.checkpoint is not importable"
        )
    if mode in ("orbax", "npz"):
        return mode
    return "orbax" if _orbax() is not None else "npz"


def _flatten(tree) -> Tuple[list, Any]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def _step_entries(ckpt_dir: str):
    """[(step, filename)] of every checkpoint in either format."""
    out = []
    for name in os.listdir(ckpt_dir):
        if not name.startswith("ckpt_"):
            continue
        stem = name[5:]
        for suffix in (".npz", ".orbax"):
            if stem.endswith(suffix):
                try:
                    out.append((int(stem[: -len(suffix)]), name))
                except ValueError:
                    pass
    return out


def save_checkpoint(ckpt_dir: str, step: int, tree, meta: Optional[dict] = None) -> str:
    """Atomically write ``tree`` (+ meta) as checkpoint ``step``; returns
    the checkpoint path."""
    os.makedirs(ckpt_dir, exist_ok=True)
    if _backend() == "orbax":
        return _save_orbax(ckpt_dir, step, tree, meta)
    return _save_npz(ckpt_dir, step, tree, meta)


def _to_npz_safe(arr: np.ndarray) -> np.ndarray:
    """bfloat16 (ml_dtypes) round-trips through .npz as raw void bytes
    numpy can't cast back — store it widened to f32 (lossless); restore
    casts to the like-tree dtype anyway."""
    if arr.dtype.name == "bfloat16" or arr.dtype.kind == "V":
        return arr.astype(np.float32)
    return arr


def _save_npz(ckpt_dir: str, step: int, tree, meta: Optional[dict]) -> str:
    leaves, _ = _flatten(tree)
    arrays = {f"leaf_{i}": _to_npz_safe(np.asarray(l)) for i, l in enumerate(leaves)}
    path = os.path.join(ckpt_dir, f"ckpt_{step:08d}.npz")
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta or {}), **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    _log.info("saved checkpoint %s", path)
    return path


def _save_orbax(ckpt_dir: str, step: int, tree, meta: Optional[dict]) -> str:
    ocp = _orbax()
    path = os.path.join(os.path.abspath(ckpt_dir), f"ckpt_{step:08d}.orbax")
    # orbax writes into a temp dir and renames — atomic like the npz path;
    # an aborted earlier attempt must be cleared first
    if os.path.exists(path):
        shutil.rmtree(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, tree)
    # meta as a sidecar (orbax pytrees are arrays; job metadata is JSON)
    with open(path + ".meta.json", "w") as f:
        json.dump(meta or {}, f)
    _log.info("saved checkpoint %s", path)
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [s for s, _ in _step_entries(ckpt_dir)]
    return max(steps) if steps else None


def restore_checkpoint(ckpt_dir: str, like_tree, step: Optional[int] = None):
    """Restore the newest (or given-step) checkpoint into the structure of
    ``like_tree``.  Returns ``(tree, step, meta)`` or ``None``."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            return None
    orbax_path = os.path.join(os.path.abspath(ckpt_dir), f"ckpt_{step:08d}.orbax")
    if os.path.isdir(orbax_path):
        return _restore_orbax(orbax_path, like_tree, step)
    path = os.path.join(ckpt_dir, f"ckpt_{step:08d}.npz")
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
        leaves, treedef = _flatten(like_tree)
        restored = []
        for i, like in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            restored.append(np.asarray(arr, dtype=np.asarray(like).dtype))
    tree = jax.tree_util.tree_unflatten(treedef, restored)
    _log.info("restored checkpoint %s (meta=%s)", path, meta)
    return tree, step, meta


def _restore_orbax(path: str, like_tree, step: int):
    ocp = _orbax()
    if ocp is None:
        raise RuntimeError(
            f"checkpoint {path} was written by the orbax backend but "
            "orbax.checkpoint is not importable in this environment"
        )
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path)
    meta = {}
    if os.path.exists(path + ".meta.json"):
        with open(path + ".meta.json") as f:
            meta = json.load(f)
    # conform dtypes/structure to like_tree (orbax restores as numpy).
    # Structure drift (model config changed since the checkpoint) must
    # fail loudly like the npz path does on a missing key — zip() would
    # silently truncate or mispair parameters.  Compare KEY PATHS, not
    # just leaf counts: a renamed/reordered layer keeps the count equal
    # while changing which array lands where.  (Paths are compared as
    # strings so a custom pytree restored as a plain dict still matches
    # when its keys agree.)
    path_leaves, treedef = jax.tree_util.tree_flatten_with_path(like_tree)
    got_path_leaves, _ = jax.tree_util.tree_flatten_with_path(restored)
    want_paths = [jax.tree_util.keystr(p) for p, _ in path_leaves]
    got_paths = [jax.tree_util.keystr(p) for p, _ in got_path_leaves]
    if got_paths != want_paths:
        missing = sorted(set(want_paths) - set(got_paths))
        extra = sorted(set(got_paths) - set(want_paths))
        raise ValueError(
            f"checkpoint {path} structure does not match the restore "
            f"target (missing: {missing[:5]}, unexpected: {extra[:5]}) — "
            "model structure changed since this checkpoint was written"
        )
    leaves = [l for _, l in path_leaves]
    got_leaves = [l for _, l in got_path_leaves]
    conformed = [
        np.asarray(g, dtype=np.asarray(like).dtype)
        for g, like in zip(got_leaves, leaves)
    ]
    tree = jax.tree_util.tree_unflatten(treedef, conformed)
    _log.info("restored checkpoint %s (meta=%s)", path, meta)
    return tree, step, dict(meta)


def prune_checkpoints(ckpt_dir: str, keep: int = 3) -> None:
    if not os.path.isdir(ckpt_dir):
        return
    entries = sorted(_step_entries(ckpt_dir))
    for _, name in entries[:-keep]:
        full = os.path.join(ckpt_dir, name)
        if os.path.isdir(full):
            shutil.rmtree(full)
            if os.path.exists(full + ".meta.json"):
                os.unlink(full + ".meta.json")
        else:
            os.unlink(full)
