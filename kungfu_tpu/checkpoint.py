"""Checkpoint / resume.

The reference has no central checkpoint engine — recovery leans on (1)
live rank-0 re-broadcast, (2) the in-memory versioned store, (3)
example-level ``tf.keras``/np.savez checkpoints (SURVEY §5.4).  The TPU
build makes recovery real with a small checkpoint API used by the
auto-recovery path: param/opt-state pytrees + step/epoch counters saved
per epoch, newest-wins restore, atomic writes.

Format: atomic numpy ``.npz`` of the flattened pytree — dependency-free
and identical on CPU test clusters and TPU hosts.  (An orbax backend —
async + sharding-aware — is the planned upgrade path; the API here is
deliberately orbax-shaped: save/restore/latest_step/prune.)
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Optional, Tuple

import jax
import numpy as np

from kungfu_tpu.utils.log import get_logger

_log = get_logger("checkpoint")


def _flatten(tree) -> Tuple[list, Any]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def save_checkpoint(ckpt_dir: str, step: int, tree, meta: Optional[dict] = None) -> str:
    """Atomically write ``tree`` (+ meta) as checkpoint ``step``; returns
    the checkpoint path."""
    os.makedirs(ckpt_dir, exist_ok=True)
    leaves, _ = _flatten(tree)
    arrays = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    path = os.path.join(ckpt_dir, f"ckpt_{step:08d}.npz")
    fd, tmp = tempfile.mkstemp(dir=ckpt_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta or {}), **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    _log.info("saved checkpoint %s", path)
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = []
    for name in os.listdir(ckpt_dir):
        if name.startswith("ckpt_") and name.endswith(".npz"):
            try:
                steps.append(int(name[5:-4]))
            except ValueError:
                pass
    return max(steps) if steps else None


def restore_checkpoint(ckpt_dir: str, like_tree, step: Optional[int] = None):
    """Restore the newest (or given-step) checkpoint into the structure of
    ``like_tree``.  Returns ``(tree, step, meta)`` or ``None``."""
    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            return None
    path = os.path.join(ckpt_dir, f"ckpt_{step:08d}.npz")
    with np.load(path, allow_pickle=False) as data:
        meta = json.loads(str(data["__meta__"]))
        leaves, treedef = _flatten(like_tree)
        restored = []
        for i, like in enumerate(leaves):
            arr = data[f"leaf_{i}"]
            restored.append(np.asarray(arr, dtype=np.asarray(like).dtype))
    tree = jax.tree_util.tree_unflatten(treedef, restored)
    _log.info("restored checkpoint %s (meta=%s)", path, meta)
    return tree, step, meta


def prune_checkpoints(ckpt_dir: str, keep: int = 3) -> None:
    if not os.path.isdir(ckpt_dir):
        return
    steps = sorted(
        int(n[5:-4]) for n in os.listdir(ckpt_dir)
        if n.startswith("ckpt_") and n.endswith(".npz")
    )
    for s in steps[:-keep]:
        os.unlink(os.path.join(ckpt_dir, f"ckpt_{s:08d}.npz"))
