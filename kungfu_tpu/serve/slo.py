"""Serving SLO surfaces: latency histograms, load gauges, targets.

One thin layer over the unified registry
(:mod:`kungfu_tpu.monitor.registry`) so every serving latency lands in
the SAME pipeline the training plane already built: local ``/metrics``
rendering, percentile summaries, and — because
:class:`~kungfu_tpu.monitor.aggregator.RankReporter` forwards registry
counters/gauges and histogram *deltas* in every snapshot — the
aggregator ``/cluster`` view and the kftop serving section, with no new
wire schema.

The three serving latencies (docs/serving.md):

* **TTFT** (``kf_serve_ttft_seconds``) — admission to first decoded
  token, measured at the worker (includes engine queue wait);
* **per-token** (``kf_serve_token_seconds``) — decode-step wall time
  per active request, measured at the worker;
* **e2e** (``kf_serve_e2e_seconds``) — submit to completion, measured
  at the router (includes routing, wire, queue, replay after a worker
  death — the number a user feels).

Request accounting rides the flight recorder's counted-kind machinery:
``timeline.event("request", "accept"|"reject"|"complete"|"replay"|
"lost")`` ticks ``kf_serve_requests_total{what=...}`` even with tracing
off, exactly like the chaos/shrink counters.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.utils import envs

TTFT_HIST = "kf_serve_ttft_seconds"
TOKEN_HIST = "kf_serve_token_seconds"
E2E_HIST = "kf_serve_e2e_seconds"
QUEUE_GAUGE = "kf_serve_queue_depth"
ACTIVE_GAUGE = "kf_serve_active_requests"
REQUESTS_COUNTER = "kf_serve_requests_total"
PREFILL_COUNTER = "kf_serve_prefill_tokens_total"

DEFAULT_TTFT_MS = 500.0
DEFAULT_E2E_MS = 5000.0


def observe_ttft(seconds: float) -> None:
    REGISTRY.histogram(TTFT_HIST).observe(seconds)


def observe_token(seconds: float) -> None:
    REGISTRY.histogram(TOKEN_HIST).observe(seconds)


def observe_e2e(seconds: float) -> None:
    REGISTRY.histogram(E2E_HIST).observe(seconds)


def note_queue_depth(n: int) -> None:
    REGISTRY.gauge(QUEUE_GAUGE).set(n)


def note_active(n: int) -> None:
    REGISTRY.gauge(ACTIVE_GAUGE).set(n)


def count_prefill(computed: int = 0, reused: int = 0) -> None:
    """Prefill work accounting: ``computed`` tokens ran the forward,
    ``reused`` came out of the paged cache's prefix chain — the measured
    basis of the prefix-reuse claim (bench.py --serve)."""
    if computed:
        REGISTRY.counter(PREFILL_COUNTER, what="computed").inc(computed)
    if reused:
        REGISTRY.counter(PREFILL_COUNTER, what="reused").inc(reused)


@dataclass(frozen=True)
class SLOTargets:
    """Latency objectives; the policy layer's controllers steer against
    these (docs/serving.md SLO methodology)."""

    ttft_s: float = DEFAULT_TTFT_MS / 1e3
    e2e_s: float = DEFAULT_E2E_MS / 1e3

    @classmethod
    def from_env(cls) -> "SLOTargets":
        return cls(
            ttft_s=envs.parse_float_env(envs.SERVE_SLO_TTFT_MS,
                                        DEFAULT_TTFT_MS) / 1e3,
            e2e_s=envs.parse_float_env(envs.SERVE_SLO_E2E_MS,
                                       DEFAULT_E2E_MS) / 1e3,
        )


@dataclass(frozen=True)
class SLORules:
    """Declarative burn-rate rules the kf-sentinel evaluates online.

    Budgets are in MILLISECONDS because the sentinel judges the
    aggregator rollup series (``ttft_ms``/``e2e_ms``, already ms), not
    the local histograms.  The two-window test
    (:func:`kungfu_tpu.monitor.detect.slo_burn`) alerts only when BOTH
    the short window (fast burn, happening now) and the long window
    (sustained burn, not one blip) exceed their violation fractions —
    docs/sentinel.md has the rule table.

    monitor/sentinel.py reads the same env tokens from ``os.environ``
    directly (mirror constants — kfhist's stubbed context never imports
    this jax-adjacent package); tests pin both sides to these exact
    defaults so the contract cannot drift.
    """

    ttft_budget_ms: float = DEFAULT_TTFT_MS
    e2e_budget_ms: float = DEFAULT_E2E_MS
    short_window: int = 6
    long_window: int = 24
    short_frac: float = 0.5
    long_frac: float = 0.25

    @classmethod
    def from_env(cls) -> "SLORules":
        return cls(
            ttft_budget_ms=envs.parse_float_env(envs.SERVE_SLO_TTFT_MS,
                                                DEFAULT_TTFT_MS),
            e2e_budget_ms=envs.parse_float_env(envs.SERVE_SLO_E2E_MS,
                                               DEFAULT_E2E_MS),
            short_window=envs.parse_int_env(envs.SENTINEL_SLO_SHORT, 6),
            long_window=envs.parse_int_env(envs.SENTINEL_SLO_LONG, 24),
        )

    def budgets(self) -> Dict[str, float]:
        """Rollup-series name -> ms budget, the shape the sentinel's
        rule loop iterates."""
        return {"ttft_ms": self.ttft_budget_ms, "e2e_ms": self.e2e_budget_ms}


def slo_snapshot() -> Dict[str, Dict[str, float]]:
    """Current percentile summaries of the three serving histograms
    (local process view; the cross-rank view is kftop's)."""
    return {
        "ttft": REGISTRY.histogram(TTFT_HIST).summary(),
        "token": REGISTRY.histogram(TOKEN_HIST).summary(),
        "e2e": REGISTRY.histogram(E2E_HIST).summary(),
    }


def slo_verdict(targets: Optional[SLOTargets] = None,
                snapshot: Optional[Dict[str, Dict[str, float]]] = None
                ) -> Dict[str, bool]:
    """p99-vs-target booleans (empty histograms pass: no traffic is not
    a violation)."""
    targets = targets or SLOTargets.from_env()
    snap = snapshot if snapshot is not None else slo_snapshot()

    def ok(name: str, budget: float) -> bool:
        s = snap.get(name) or {}
        return s.get("count", 0) == 0 or s.get("p99", 0.0) <= budget

    return {"ttft_ok": ok("ttft", targets.ttft_s),
            "e2e_ok": ok("e2e", targets.e2e_s)}
