"""Paged KV-cache block manager: fixed-size pages, free-list allocation,
prefix-hash reuse, LRU eviction.

The serving plane's memory system, deliberately **pure** (numpy +
stdlib, no jax, no sockets) so its invariants are unit-testable the way
:mod:`kungfu_tpu.elastic.slices` is: the decode engine holds the device
slab; this pool owns the *host-side* pages — capacity accounting,
prefix-reuse bookkeeping, and the replay source of truth.

Model: a page holds ``page_tokens`` consecutive tokens' K and V for
every layer (``[n_layers, n_heads, page_tokens, head_dim]`` each).  A
request reserves ``ceil(total_tokens / page_tokens)`` pages at
admission — admission control is capacity-real, not optimistic — and
releases them at completion.  Completed *full* pages are committed
under a **prefix chain hash** (hash of all tokens up to and including
the page), so a later request sharing the prefix re-acquires the same
pages instead of recomputing their prefill: the classic shared-system-
prompt win.  Committed pages with no live reference park in an LRU;
allocation evicts from it when the free list runs dry.

Footprint contract: every allocation/release updates the
``kf_kv_cache_bytes`` gauge (allocated pages x page bytes) — the
serving analog of ``kf_opt_state_bytes``, flowing through aggregator
snapshots to the kftop serving view (docs/serving.md).

Invariants (tests/test_kvcache.py):

* a released, recycled page is never referenced by a live request;
* refcounts balance: acquire/release round-trips return the pool to
  its starting footprint;
* eviction only ever takes zero-reference committed pages;
* the gauge equals ``(capacity - free) * page_bytes`` at all times.

Durability (kf-persist): committed pages are *portable*.
:meth:`KVCachePool.snapshot_committed` images them as a flat numpy dict
(prefix tokens + K/V + content digest) that rides a
:class:`~kungfu_tpu.elastic.persist.PersistPlane` manifest's
``replicated`` payload; :meth:`KVCachePool.restore_committed` verifies
and re-commits them into a fresh pool after a preemption, so a restarted
serve worker's first request over a known prefix reuses prefill instead
of recomputing it (docs/persistence.md).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.utils import envs

#: default tokens per page (KF_SERVE_PAGE_TOKENS overrides)
DEFAULT_PAGE_TOKENS = 16
#: default pool capacity in pages (KF_SERVE_KV_PAGES overrides)
DEFAULT_CAPACITY_PAGES = 512

GAUGE = "kf_kv_cache_bytes"


class CacheExhausted(RuntimeError):
    """Allocation failed: free list empty and nothing evictable.  The
    typed admission-control signal — the scheduler keeps the request
    queued instead of thrashing live requests' pages."""


@dataclass(frozen=True)
class PageSpec:
    """Geometry of one page: K+V for every layer of a model."""

    n_layers: int
    n_heads: int
    head_dim: int
    page_tokens: int
    dtype: str = "float32"

    @property
    def page_bytes(self) -> int:
        # K and V, all layers, page_tokens rows of [n_heads, head_dim]
        return (2 * self.n_layers * self.n_heads * self.page_tokens
                * self.head_dim * np.dtype(self.dtype).itemsize)

    @classmethod
    def for_model(cls, cfg, page_tokens: Optional[int] = None,
                  dtype: Optional[str] = None) -> "PageSpec":
        """Spec from a :class:`~kungfu_tpu.models.transformer.
        TransformerConfig`; ``page_tokens`` defaults from the
        ``KF_SERVE_PAGE_TOKENS`` env."""
        if page_tokens is None:
            page_tokens = envs.parse_int_env(envs.SERVE_PAGE_TOKENS,
                                             DEFAULT_PAGE_TOKENS)
        return cls(n_layers=cfg.n_layers, n_heads=cfg.n_heads,
                   head_dim=cfg.head_dim, page_tokens=int(page_tokens),
                   dtype=dtype or cfg.dtype)


def chain_hashes(tokens: Sequence[int], page_tokens: int) -> List[bytes]:
    """One digest per FULL page of ``tokens``: digest *i* covers tokens
    ``[0, (i+1)*page_tokens)`` — a chain, so two sequences share page
    *i* exactly when their whole prefixes up to it agree (page-local
    hashing would alias different contexts onto one K/V block, which is
    silent cross-request corruption, not reuse)."""
    out: List[bytes] = []
    h = hashlib.blake2b(b"kf-kv-chain", digest_size=16)
    for i in range(len(tokens) // page_tokens):
        page = tokens[i * page_tokens:(i + 1) * page_tokens]
        h = h.copy()
        h.update(np.asarray(page, np.int64).tobytes())
        out.append(h.digest())
    return out


def _content_digest(k: np.ndarray, v: np.ndarray) -> bytes:
    """Digest over a page's K/V bytes — the torn-write detector for
    snapshotted pages (the chain hash covers only the *tokens*; a page
    whose data rotted in transit would otherwise restore cleanly under
    a valid key and serve garbage attention)."""
    h = hashlib.blake2b(b"kf-kv-page", digest_size=16)
    h.update(np.ascontiguousarray(k).tobytes())
    h.update(np.ascontiguousarray(v).tobytes())
    return h.digest()


class _Page:
    __slots__ = ("k", "v", "key", "refs", "prefix")

    def __init__(self):
        self.k: Optional[np.ndarray] = None   # [L, H, T, D]
        self.v: Optional[np.ndarray] = None
        self.key: Optional[bytes] = None      # chain hash when committed
        self.refs = 0
        #: the covering token prefix (all tokens the chain hash digests)
        #: — kept so a committed page is *portable*: a snapshot carries
        #: (prefix, K, V) and a restoring pool re-derives the chain hash
        #: from the tokens instead of trusting a stored key (kf-persist)
        self.prefix: Optional[np.ndarray] = None


class KVCachePool:
    """Thread-safe page pool (the worker's engine loop and the channel
    handler both touch it)."""

    def __init__(self, spec: PageSpec,
                 capacity_pages: Optional[int] = None):
        if capacity_pages is None:
            capacity_pages = envs.parse_int_env(envs.SERVE_KV_PAGES,
                                                DEFAULT_CAPACITY_PAGES)
        self.spec = spec
        self.capacity = int(capacity_pages)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._pages: Dict[int, _Page] = {}
        #: chain hash -> page id, for committed pages (live or parked)
        self._by_key: Dict[bytes, int] = {}
        #: zero-ref committed pages, LRU order (oldest first)
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._evictions = 0
        self._update_gauge()

    # -- accounting ------------------------------------------------------
    def _update_gauge(self) -> None:
        REGISTRY.gauge(GAUGE).set(
            (self.capacity - len(self._free)) * self.spec.page_bytes)

    @property
    def footprint_bytes(self) -> int:
        with self._lock:
            return (self.capacity - len(self._free)) * self.spec.page_bytes

    @property
    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def cached_pages(self) -> int:
        """Committed pages currently parked with zero references."""
        with self._lock:
            return len(self._lru)

    @property
    def evictions(self) -> int:
        with self._lock:
            return self._evictions

    # -- allocation ------------------------------------------------------
    def _take_one_locked(self) -> int:
        if self._free:
            pid = self._free.pop()
        elif self._lru:
            # evict the coldest zero-ref committed page — committed
            # data is a recomputable cache, live requests' pages are not
            pid, _ = self._lru.popitem(last=False)
            page = self._pages.pop(pid)
            assert page.refs == 0, "evicting a referenced page"
            if page.key is not None:
                self._by_key.pop(page.key, None)
            self._evictions += 1
        else:
            raise CacheExhausted(
                f"kv cache exhausted: {self.capacity} pages all referenced "
                f"by live requests (page={self.spec.page_tokens} tokens)")
        self._pages[pid] = _Page()
        self._pages[pid].refs = 1
        return pid

    def alloc(self, n: int) -> List[int]:
        """Reserve ``n`` fresh pages (refcount 1 to the caller), evicting
        cold committed pages as needed.  All-or-nothing: on
        :class:`CacheExhausted` no page moved."""
        with self._lock:
            if n > len(self._free) + len(self._lru):
                raise CacheExhausted(
                    f"need {n} pages, {len(self._free)} free + "
                    f"{len(self._lru)} evictable of {self.capacity}")
            out = [self._take_one_locked() for _ in range(n)]
            self._update_gauge()
            return out

    def release(self, page_ids: Sequence[int]) -> None:
        """Drop one reference per page.  Zero-ref committed pages park
        in the LRU (reusable); zero-ref uncommitted pages return to the
        free list — their data is dead and must never be served."""
        with self._lock:
            for pid in page_ids:
                page = self._pages.get(pid)
                if page is None or page.refs <= 0:
                    raise ValueError(f"release of non-live page {pid}")
                page.refs -= 1
                if page.refs == 0:
                    if page.key is not None:
                        self._lru[pid] = None
                        self._lru.move_to_end(pid)
                    else:
                        del self._pages[pid]
                        self._free.append(pid)
            self._update_gauge()

    # -- page data -------------------------------------------------------
    def put_page_data(self, pid: int, k: np.ndarray, v: np.ndarray) -> None:
        """Fill a reserved page's host copy (``[L, H, T, D]`` each)."""
        want = (self.spec.n_layers, self.spec.n_heads,
                self.spec.page_tokens, self.spec.head_dim)
        if tuple(k.shape) != want or tuple(v.shape) != want:
            raise ValueError(f"page data shape {k.shape} != {want}")
        with self._lock:
            page = self._pages.get(pid)
            if page is None or page.refs <= 0:
                raise ValueError(f"put_page_data on non-live page {pid}")
            page.k = np.ascontiguousarray(k)
            page.v = np.ascontiguousarray(v)

    def page_data(self, pid: int) -> Tuple[np.ndarray, np.ndarray]:
        with self._lock:
            page = self._pages.get(pid)
            if page is None or page.refs <= 0:
                raise ValueError(f"page_data on non-live page {pid}")
            if page.k is None or page.v is None:
                raise ValueError(f"page {pid} holds no data")
            return page.k, page.v

    # -- prefix reuse ----------------------------------------------------
    def commit_chain(self, tokens: Sequence[int],
                     page_ids: Sequence[int]) -> int:
        """Register the caller's filled pages under the prefix chain of
        ``tokens`` (only FULL pages commit).  A chain link already
        committed keeps the incumbent page (first writer wins — both
        hold identical K/V by construction).  Returns committed count.
        The caller still holds its references; release() parks the
        committed ones in the LRU."""
        digests = chain_hashes(tokens, self.spec.page_tokens)
        committed = 0
        with self._lock:
            for i, (digest, pid) in enumerate(zip(digests, page_ids)):
                page = self._pages.get(pid)
                if page is None or page.refs <= 0:
                    raise ValueError(f"commit of non-live page {pid}")
                if page.k is None:
                    break  # pages are filled in order; stop at the gap
                if digest in self._by_key:
                    continue
                page.key = digest
                page.prefix = np.asarray(
                    tokens[:(i + 1) * self.spec.page_tokens], np.int64)
                self._by_key[digest] = pid
                committed += 1
        return committed

    def lookup(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest committed prefix of ``tokens``: ``(page_ids,
        n_cached_tokens)``.  Returned pages are RETAINED for the caller
        (refcount +1, pulled out of the LRU) — they cannot be evicted
        under the request that is about to attend to them."""
        digests = chain_hashes(tokens, self.spec.page_tokens)
        out: List[int] = []
        with self._lock:
            for digest in digests:
                pid = self._by_key.get(digest)
                if pid is None:
                    break
                page = self._pages[pid]
                page.refs += 1
                if page.refs == 1:
                    self._lru.pop(pid, None)
                out.append(pid)
            return out, len(out) * self.spec.page_tokens

    # -- durable snapshot (kf-persist) -----------------------------------
    def snapshot_committed(self) -> Dict[str, np.ndarray]:
        """Portable image of every committed page that still holds data:
        flat ``{name: array}`` suitable as a :class:`~kungfu_tpu.elastic.
        persist.PersistPlane` ``replicated`` dict.  Per page *j*:
        ``kv{j}_p`` covering token prefix (int64), ``kv{j}_k``/``kv{j}_v``
        the K/V blocks, ``kv{j}_c`` a content digest over the K/V bytes.
        The chain hash itself is deliberately NOT stored — the restoring
        pool recomputes it from the prefix tokens, so a page can only
        ever re-enter a cache under the key its own tokens derive."""
        out: Dict[str, np.ndarray] = {}
        with self._lock:
            j = 0
            for pid in self._by_key.values():
                page = self._pages.get(pid)
                if (page is None or page.k is None or page.v is None
                        or page.prefix is None):
                    continue
                out[f"kv{j}_p"] = np.array(page.prefix, np.int64)
                out[f"kv{j}_k"] = np.array(page.k)
                out[f"kv{j}_v"] = np.array(page.v)
                out[f"kv{j}_c"] = np.frombuffer(
                    _content_digest(page.k, page.v), np.uint8).copy()
                j += 1
        return out

    def restore_committed(self, snap: Dict[str, np.ndarray]
                          ) -> Tuple[int, int]:
        """Re-commit a :meth:`snapshot_committed` image into THIS pool:
        ``(restored, rejected)``.  Every page is verified before
        adoption — prefix length must tile whole pages, K/V shapes must
        match this pool's spec, and the content digest must reproduce
        (a torn/corrupted page is *rejected*, never served).  The chain
        hash is recomputed from the prefix tokens via
        :func:`chain_hashes`; a digest already committed here keeps the
        incumbent (idempotent restore).  A pool too full to adopt a
        verified page counts it rejected — restore never evicts live
        requests' pages."""
        restored = rejected = 0
        pt = self.spec.page_tokens
        shape = (self.spec.n_layers, self.spec.n_heads, pt,
                 self.spec.head_dim)
        idx = sorted(int(name[2:-2]) for name in snap
                     if name.startswith("kv") and name.endswith("_p")
                     and name[2:-2].isdigit())
        for j in idx:
            prefix = snap.get(f"kv{j}_p")
            k = snap.get(f"kv{j}_k")
            v = snap.get(f"kv{j}_v")
            want = snap.get(f"kv{j}_c")
            if (prefix is None or k is None or v is None or want is None
                    or len(prefix) == 0 or len(prefix) % pt
                    or tuple(np.shape(k)) != shape
                    or tuple(np.shape(v)) != shape):
                rejected += 1
                continue
            k = np.ascontiguousarray(k, np.dtype(self.spec.dtype))
            v = np.ascontiguousarray(v, np.dtype(self.spec.dtype))
            if _content_digest(k, v) != bytes(np.asarray(want, np.uint8)):
                rejected += 1
                continue
            digest = chain_hashes(
                np.asarray(prefix, np.int64).tolist(), pt)[-1]
            if self._adopt_committed(digest, prefix, k, v):
                restored += 1
            else:
                rejected += 1
        return restored, rejected

    def _adopt_committed(self, digest: bytes, prefix: np.ndarray,
                         k: np.ndarray, v: np.ndarray) -> bool:
        """Install a verified page as committed + parked (zero refs, in
        the LRU).  ``True`` also when the digest is already committed —
        the restore's goal state holds either way."""
        with self._lock:
            if digest in self._by_key:
                return True
            if not self._free and not self._lru:
                return False  # only live pages left; never steal those
            pid = self._take_one_locked()
            page = self._pages[pid]
            page.k = np.ascontiguousarray(k)
            page.v = np.ascontiguousarray(v)
            page.prefix = np.asarray(prefix, np.int64)
            page.key = digest
            self._by_key[digest] = pid
            page.refs = 0
            self._lru[pid] = None
            self._lru.move_to_end(pid)
            self._update_gauge()
            return True

    # -- introspection ---------------------------------------------------
    def live_refs(self) -> Dict[int, int]:
        """``{page id: refcount}`` for referenced pages (tests)."""
        with self._lock:
            return {pid: p.refs for pid, p in self._pages.items()
                    if p.refs > 0}

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "free": len(self._free),
                "cached": len(self._lru),
                "live": sum(1 for p in self._pages.values() if p.refs > 0),
                "evictions": self._evictions,
                "bytes": (self.capacity - len(self._free))
                * self.spec.page_bytes,
            }
