"""Request router + serving workers over the existing host channel.

The serving deployment is a :class:`~kungfu_tpu.peer.Peer` world wearing
a different workload: each serving rank runs a :class:`ServeWorker`
(one :class:`~kungfu_tpu.serve.engine.InferenceEngine` + a channel
handler + a load-scaled response-send pool), and one rank runs the
:class:`ServeRouter` — admission, dispatch, and the serving rung of the
fault-tolerance ladder.

Wire protocol (PEER_TO_PEER frames on the existing host channel; every
name sits under the ``req.srv`` prefix the blob store's p2p handler
explicitly skips, so the two planes share the transport without racing
replies onto each other's ids):

* ``req.srv.<rid>``  router → worker: ``{rid, prompt, committed,
  max_new}`` — ``committed`` is non-empty only on replay;
* ``req.srvp.<rid>`` worker → router: progress — the tokens generated
  so far, sent every ``KF_SERVE_COMMIT_EVERY`` decode positions.  A
  progress frame COMMITS those tokens: after the worker dies, replay
  restarts from them, not from scratch;
* ``req.srvc.<rid>`` worker → router: completion (tokens + timings).

Admission is FCFS with a bounded accepted-set
(``KF_SERVE_QUEUE_DEPTH``); past it, :class:`~kungfu_tpu.comm.faults.
ServeOverloadError` rejects immediately (typed overload beats unbounded
tail latency).  Dispatch is least-outstanding among live workers.

Failure ladder (docs/serving.md, docs/fault_tolerance.md):

1. a send failure toward a worker, or ``strike_limit`` consecutive
   progress-deadline expiries, declares it dead;
2. with a :class:`~kungfu_tpu.elastic.slices.SliceTopology`, the dead
   set expands to slice grain exactly like the training ladder — a
   degraded slice is excluded whole (its surviving members are not
   schedulable capacity);
3. every in-flight request assigned to excluded ranks re-admits on a
   survivor, replaying from its last committed decode position (greedy
   decode re-derives the same continuation deterministically);
4. zero live workers left = the typed :class:`~kungfu_tpu.comm.faults.
   RequestLostError` carrying the committed tokens — never a hang.
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

from kungfu_tpu.chaos import inject as chaos_inject
from kungfu_tpu.comm.faults import (RequestLostError, ServeOverloadError)
from kungfu_tpu.comm.host import (SERVE_NAME_PREFIX, ConnType,
                                  host_pool_size)
from kungfu_tpu.elastic.slices import SliceTopology, slice_verdict
from kungfu_tpu.monitor import timeline
from kungfu_tpu.serve import slo
from kungfu_tpu.utils import envs
from kungfu_tpu.utils.log import get_logger

_log = get_logger("serve-router")

#: reserved name space on the host channel — ONE constant, defined in
#: comm/host.py so the blob store's skip and this module's frame names
#: can never drift apart
RESERVED_PREFIX = SERVE_NAME_PREFIX
REQ_PREFIX = RESERVED_PREFIX + "."
PROG_PREFIX = RESERVED_PREFIX + "p."
DONE_PREFIX = RESERVED_PREFIX + "c."

DEFAULT_QUEUE_DEPTH = 64
DEFAULT_COMMIT_EVERY = 8
DEFAULT_DEADLINE_S = 60.0
#: bounded retries on serve-plane sends: a dead worker must fail the
#: send in seconds (and enter the dead-worker ladder), not ride the
#: full 500 x 200 ms connect ladder of the gradient path
SEND_RETRIES = 3

_rid_counter = itertools.count()


def remaining_budget(max_new: int, committed: Sequence[int],
                     eos_id: Optional[int]) -> int:
    """New-token budget left for a (re)dispatched request.  A committed
    tail already ending in EOS is a FINISHED generation — the engine
    stops at EOS, so it can only ever be the last committed token — and
    replaying it with leftover budget would decode past EOS and diverge
    from the failure-free run's output."""
    if eos_id is not None and committed and committed[-1] == eos_id:
        return 0
    return int(max_new) - len(committed)


# -- worker side ------------------------------------------------------------
class ServeWorker:
    """One serving rank: engine loop + request handler + send pool."""

    def __init__(self, peer, engine, *, commit_every: Optional[int] = None,
                 idle_wait_s: float = 0.02, step_period_s: float = 0.0):
        self.peer = peer
        self.engine = engine
        self.commit_every = int(
            commit_every if commit_every is not None
            else envs.parse_int_env(envs.SERVE_COMMIT_EVERY,
                                    DEFAULT_COMMIT_EVERY))
        self._idle_wait_s = idle_wait_s
        #: floor on the decode-iteration cadence.  0 = run flat out;
        #: the CPU-mesh SLO bench pins it so per-token latency models a
        #: heavier model instead of the toy's sub-ms steps — latency
        #: STRUCTURE (queueing, replay, recovery), not raw speed, is
        #: what that row measures
        self.step_period_s = float(step_period_s)
        self._lock = threading.Lock()
        self._src: Dict[str, str] = {}        # rid -> requester peer id
        self._toks: Dict[str, List[int]] = {}  # rid -> generated tokens
        #: rid -> dispatch attempt currently owning it.  The engine runs
        #: under "rid#att" ids, so a superseded attempt's surviving run
        #: (cancel can miss one mid-admission) emits events that simply
        #: fail the attempt check instead of interleaving tokens
        self._att: Dict[str, int] = {}
        self._stop = threading.Event()
        self.dead = False                      # set by an injected death
        self._sendq: "queue.Queue" = queue.Queue()
        n = host_pool_size(peer.size(), pool="serve")
        self._senders = [
            threading.Thread(target=self._send_loop,
                             name=f"kf-serve-send-{i}", daemon=True)
            for i in range(n)
        ]
        self._thread = threading.Thread(
            target=self._loop, name=f"kf-serve-w{peer.chaos_rank()}",
            daemon=True)

    def start(self) -> "ServeWorker":
        if self.peer.channel is None:
            raise RuntimeError("serving needs a started multi-peer world")
        self.peer.channel.on_p2p_request(self._on_frame)
        for t in self._senders:
            t.start()
        self._thread.start()
        return self

    # -- channel receive path (must stay fast: hand off and return) ------
    def _on_frame(self, name: str, payload: bytes, src: str) -> None:
        # note: progress/completion names ("req.srvp."/"req.srvc.") do
        # not match the request prefix "req.srv." — the dot disambiguates
        if not name.startswith(REQ_PREFIX) or self._stop.is_set():
            return
        try:
            req = json.loads(payload.decode())
            rid = req["rid"]
            prompt = [int(t) for t in req["prompt"]]
            committed = [int(t) for t in req.get("committed") or []]
            max_new = int(req["max_new"])
            att = int(req.get("att", 0))
        except (ValueError, KeyError) as e:
            _log.warning("bad serve request from %s: %s", src, e)
            return
        # kf-xray: the frame's meta carries the router's trace context;
        # this worker's handling mark and the engine's prefill span join
        # that trace (malformed/absent tc = unlinked, never an error)
        trace, parent = timeline.parse_trace_context(req.get("tc"))
        if timeline.enabled():
            timeline.event("serve", "request-recv",
                           rank=self.peer.chaos_rank(), rid=rid, att=att,
                           **timeline.context_attrs(trace, parent))
        ctl = chaos_inject.controller_for(self.peer.chaos_rank())
        if ctl is not None and ctl.on_serve_request(rid):
            return  # injected frame loss: the router's deadline re-admits
        with self._lock:
            prev = self._att.get(rid)
            self._att[rid] = att
            self._src[rid] = src
            # this worker's OWN progress only — the router prepends the
            # committed prefix itself (sending it back would double-count
            # on the next replay)
            self._toks[rid] = []
        # receipt ack (an empty progress frame): the router's deadline
        # measures LIVENESS, not token rate — a request parked in this
        # worker's admission queue behind a backlog must not read as a
        # dead worker (that false strike is how one real failure
        # cascades into killing the healthy rest of the fleet)
        self._queue_progress(rid, [], None)
        if prev is not None and prev != att:
            # a re-dispatch of a request we already hold (the router's
            # deadline fired on a slow, not dead, first attempt): drop
            # the stale run.  Best-effort — a run mid-admission escapes
            # the cancel, but its events carry the OLD attempt id and
            # are discarded by the attempt check in _loop
            self.engine.cancel(f"{rid}#{prev}")
        remaining = remaining_budget(max_new, committed, self.engine.eos_id)
        if remaining <= 0:
            # replay raced completion (budget spent, or the committed
            # tail already ends in EOS): nothing left to generate
            self._queue_done(rid, [], ok=True, ttft_s=0.0,
                             queue_s=0.0, reused_tokens=0, computed_tokens=0)
            return
        try:
            self.engine.submit(f"{rid}#{att}", prompt + committed, remaining,
                               trace=timeline.format_trace_context(trace,
                                                                   parent))
        except ValueError as e:
            self._queue_done(rid, [], ok=False, error=str(e))

    # -- response sends (load-scaled pool, never the engine loop) --------
    def _send_loop(self) -> None:
        # sentinel/stop-flag-terminated worker loop, not a retry loop:
        # each queue item is sent once (channel.send owns its bounded
        # retries) and delivery failures are dropped with a warning
        while True:  # kflint: allow(retry-discipline)
            try:
                item = self._sendq.get(timeout=0.5)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if item is None:
                return
            dst, name, body = item
            try:
                from kungfu_tpu.plan.peer import parse_peer_id

                self.peer.channel.send(parse_peer_id(dst), name, body,
                                       ConnType.PEER_TO_PEER,
                                       retries=SEND_RETRIES)
            except (OSError, ConnectionError) as e:
                _log.warning("cannot answer %s: %s", dst, e)

    def _queue_progress(self, rid: str, tokens: List[int],
                        ttft_s: Optional[float]) -> None:
        with self._lock:
            src = self._src.get(rid)
            att = self._att.get(rid, 0)
        if src is None:
            return
        body = json.dumps({"rid": rid, "att": att, "tokens": tokens,
                           "ttft_s": ttft_s}).encode()
        self._sendq.put((src, f"{PROG_PREFIX}{rid}", body))

    def _queue_done(self, rid: str, tokens: List[int], ok: bool,
                    error: str = "", **stats) -> None:
        with self._lock:
            src = self._src.pop(rid, None)
            att = self._att.pop(rid, 0)
            self._toks.pop(rid, None)
        if src is None:
            return
        body = json.dumps({"rid": rid, "att": att, "tokens": tokens,
                           "ok": ok, "error": error, **stats}).encode()
        self._sendq.put((src, f"{DONE_PREFIX}{rid}", body))

    #: wall period between keepalive progress frames for every tracked
    #: request (queued or decoding): liveness proof for the router's
    #: deadline ladder, decoupled from token rate
    KEEPALIVE_S = 0.5

    def _keepalive(self) -> None:
        with self._lock:
            snap = {rid: list(toks) for rid, toks in self._toks.items()}
        for rid, toks in snap.items():
            self._queue_progress(rid, toks, None)

    # -- the engine loop --------------------------------------------------
    def _loop(self) -> None:
        it = 0
        last_beat = time.perf_counter()
        while not self._stop.is_set():
            now = time.perf_counter()
            if now - last_beat >= self.KEEPALIVE_S:
                last_beat = now
                self._keepalive()
            if not self.engine.wait_for_work(self._idle_wait_s):
                continue
            it += 1
            t_step = time.perf_counter()
            try:
                # the serving analog of the training-step boundary: the
                # chaos `die`/`die_slice` step triggers fire here, so a
                # worker kill lands at a deterministic decode iteration
                from kungfu_tpu import chaos

                chaos.note_step(self.peer.chaos_rank(), it)
                events = self.engine.step()
            except Exception as e:  # noqa: BLE001 — no silent wedge
                # injected deaths die on purpose; anything else must
                # look like a death too, not a zombie: a silently-dead
                # loop thread would leave the channel answering (no fast
                # send-failure detection) while every request waits out
                # the full router deadline.  Either way: mark dead, stop,
                # close the peer so dispatch sends fail fast.
                if isinstance(e, chaos_inject.InjectedDeath):
                    timeline.event("serve", "worker-die",
                                   rank=self.peer.chaos_rank(), why=str(e))
                else:
                    _log.exception("serve worker loop failed: %s", e)
                    timeline.event("serve", "worker-error",
                                   rank=self.peer.chaos_rank(), why=str(e))
                self.dead = True
                self._stop.set()
                try:
                    self.peer.close()
                except Exception:  # noqa: BLE001 — dying is the point
                    pass
                return
            for ev in events:
                rid, _, att_s = (ev.get("rid") or "").rpartition("#")
                with self._lock:
                    current = (self._att.get(rid) is not None
                               and str(self._att[rid]) == att_s)
                if not current:
                    continue  # a superseded attempt's surviving run
                if ev["kind"] == "token":
                    with self._lock:
                        toks = self._toks.get(rid)
                        if toks is not None:
                            toks.append(ev["tok"])
                            n = len(toks)
                            snap = list(toks)
                    if toks is not None and n % self.commit_every == 0:
                        self._queue_progress(rid, snap, None)
                elif ev["kind"] == "done":
                    self._queue_done(
                        rid, ev["tokens"], ok=True,
                        ttft_s=ev["ttft_s"], queue_s=ev["queue_s"],
                        reused_tokens=ev["reused_tokens"],
                        computed_tokens=ev["computed_tokens"])
            if self.step_period_s > 0:
                left = self.step_period_s - (time.perf_counter() - t_step)
                if left > 0:
                    time.sleep(left)

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(join_timeout)
        for _ in self._senders:
            self._sendq.put(None)
        for t in self._senders:
            t.join(join_timeout)


# -- router side ------------------------------------------------------------
class RequestHandle:
    """Client-side future for one accepted request."""

    def __init__(self, rid: str, prompt: Sequence[int], max_new: int):
        self.rid = rid
        self.prompt = list(int(t) for t in prompt)
        self.max_new = int(max_new)
        #: kf-xray causal trace of this request: one trace id spans the
        #: router's admission events, the worker's frame handling, and
        #: the engine's prefill span (docs/xray.md).  The router span id
        #: is the parent every downstream span hangs off.
        self.trace = f"srv.{rid}"
        self.router_span = timeline.new_span_id()
        self.submitted_s = time.perf_counter()
        #: tokens committed across ALL workers (replay restarts here)
        self.committed: List[int] = []
        #: current worker's progress beyond ``committed``
        self.worker_tokens: List[int] = []
        self.worker: Optional[int] = None
        self.deadline = 0.0
        self.replays = 0
        self.ttft_s: Optional[float] = None
        self.stats: dict = {}
        self.tokens: Optional[List[int]] = None
        self.error: Optional[BaseException] = None
        self.done_s: Optional[float] = None  # perf_counter at settle
        self._done = threading.Event()

    @property
    def committed_total(self) -> List[int]:
        return self.committed + self.worker_tokens

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.rid} not done")
        if self.error is not None:
            raise self.error
        return list(self.tokens or [])


class ServeRouter:
    """Admission + dispatch + the serving fault ladder, riding one
    peer's channel."""

    def __init__(self, peer, worker_ranks: Sequence[int], *,
                 queue_depth: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 topology: Optional[SliceTopology] = None,
                 strike_limit: int = 2,
                 watch_period_s: Optional[float] = None):
        if peer.channel is None:
            raise RuntimeError("routing needs a started multi-peer world")
        self.peer = peer
        workers = peer.config.cluster.workers
        self._addr: Dict[int, object] = {r: workers[r] for r in worker_ranks}
        self._live = set(int(r) for r in worker_ranks)
        self._dead: set = set()
        self.topology = topology
        self.queue_depth = int(
            queue_depth if queue_depth is not None
            else envs.parse_int_env(envs.SERVE_QUEUE_DEPTH,
                                    DEFAULT_QUEUE_DEPTH))
        self.deadline_s = float(
            deadline_s if deadline_s is not None
            else envs.parse_float_env(envs.SERVE_REQUEST_DEADLINE,
                                      DEFAULT_DEADLINE_S))
        self.strike_limit = int(strike_limit)
        self._lock = threading.Lock()
        self._reqs: Dict[str, RequestHandle] = {}
        self._outstanding: Dict[int, int] = {r: 0 for r in self._live}
        self._strikes: Dict[int, int] = {}
        self._completed = 0
        self._replayed = 0
        self._stop = threading.Event()
        self._watch_period = (watch_period_s if watch_period_s is not None
                              else max(0.05, min(0.25, self.deadline_s / 4)))
        self._watchdog = threading.Thread(
            target=self._watch_loop, name="kf-serve-router", daemon=True)
        peer.channel.on_p2p_request(self._on_frame)
        self._watchdog.start()

    # -- views -----------------------------------------------------------
    @property
    def live_workers(self) -> List[int]:
        with self._lock:
            return sorted(self._live)

    @property
    def dead_workers(self) -> List[int]:
        with self._lock:
            return sorted(self._dead)

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._reqs)

    def outstanding(self, rank: int) -> int:
        """Requests currently dispatched to ``rank`` — the autoscaler's
        drain check before retiring a worker (serve/scale.py)."""
        with self._lock:
            return int(self._outstanding.get(int(rank), 0))

    @property
    def completed(self) -> int:
        with self._lock:
            return self._completed

    @property
    def replayed(self) -> int:
        with self._lock:
            return self._replayed

    # -- admission --------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new: Optional[int] = None,
               rid: Optional[str] = None) -> RequestHandle:
        """FCFS admission with a bounded accepted set; rejected
        admissions raise the typed overload error immediately.
        ``max_new`` defaults from ``KF_SERVE_MAX_TOKENS``."""
        if max_new is None:
            max_new = envs.parse_int_env(envs.SERVE_MAX_TOKENS, 256)
        rid = rid or f"{self.peer.config.self_id.port}-{next(_rid_counter)}"
        h = RequestHandle(rid, prompt, max_new)
        # check + insert under ONE acquisition: two concurrent
        # submitters passing a split check would both insert and exceed
        # the documented bound
        with self._lock:
            depth = len(self._reqs)
            if depth >= self.queue_depth:
                timeline.event("request", "reject",
                               rank=self.peer.chaos_rank(), depth=depth,
                               trace=h.trace, parent=h.router_span)
                raise ServeOverloadError(depth, self.queue_depth)
            self._reqs[rid] = h
            slo.note_queue_depth(len(self._reqs))
        timeline.event("request", "accept", rank=self.peer.chaos_rank(),
                       rid=rid, trace=h.trace, span=h.router_span)
        self._dispatch(h)
        return h

    def _pick_worker_locked(self) -> Optional[int]:
        live = sorted(self._live)
        if not live:
            return None
        return min(live, key=lambda r: (self._outstanding.get(r, 0), r))

    def _dispatch(self, h: RequestHandle) -> None:
        """Send (or re-send) a request to the least-outstanding live
        worker; a send failure walks the dead-worker ladder and tries
        the next survivor.  Bounded: every failing pass removes a
        worker from the live set, so the loop ends in at most
        ``len(workers) + 1`` passes (the last one fails the handle).
        No backoff on purpose — each pass targets a DIFFERENT endpoint
        (failover, not re-hammering), and channel.send already owns the
        bounded per-endpoint retry."""
        for _ in range(len(self._addr) + 1):  # kflint: allow(retry-discipline)
            with self._lock:
                target = self._pick_worker_locked()
                if target is not None:
                    self._outstanding[target] = (
                        self._outstanding.get(target, 0) + 1)
                    h.worker = target
                    h.worker_tokens = []
                    h.deadline = time.monotonic() + self.deadline_s
                    addr = self._addr[target]
            if target is None:
                self._fail(h, RequestLostError(h.rid, h.committed))
                return
            body = json.dumps({
                "rid": h.rid, "prompt": h.prompt,
                "committed": h.committed, "max_new": h.max_new,
                # attempt id, echoed in every progress/done frame: a
                # replayed-away worker's late frames fail this check, so
                # tokens already folded into h.committed can never be
                # double-counted even when the replay landed on the SAME
                # worker (where the src guard alone is blind)
                "att": h.replays,
                # kf-xray trace context in the existing JSON meta (the
                # HeaderCodec wire header is untouched): the worker
                # re-enters it so its handling + the engine prefill span
                # join this request's trace (docs/xray.md)
                "tc": timeline.format_trace_context(h.trace,
                                                    h.router_span),
            }).encode()
            try:
                self.peer.channel.send(addr, f"{REQ_PREFIX}{h.rid}", body,
                                       ConnType.PEER_TO_PEER,
                                       retries=SEND_RETRIES)
                return
            except (OSError, ConnectionError) as e:
                _log.warning("dispatch of %s to rank %d failed: %s",
                             h.rid, target, e)
                with self._lock:
                    self._outstanding[target] = max(
                        0, self._outstanding.get(target, 1) - 1)
                # the dead-mark replays every OTHER victim; h itself
                # re-dispatches in this loop (it is not yet assigned —
                # mark_worker_dead skips handles whose worker it just
                # unset here)
                with self._lock:
                    h.worker = None
                self.mark_worker_dead(target)
        self._fail(h, RequestLostError(h.rid, h.committed,
                                       "dispatch retries exhausted"))

    # -- channel receive path ---------------------------------------------
    def _on_frame(self, name: str, payload: bytes, src: str) -> None:
        if name.startswith(PROG_PREFIX):
            kind = "progress"
            rid = name[len(PROG_PREFIX):]
        elif name.startswith(DONE_PREFIX):
            kind = "done"
            rid = name[len(DONE_PREFIX):]
        else:
            return
        try:
            msg = json.loads(payload.decode())
        except ValueError as e:
            _log.warning("bad serve frame %s from %s: %s", name, src, e)
            return
        with self._lock:
            h = self._reqs.get(rid)
            if h is None:
                return  # late frame from a worker we already replayed away
            worker = h.worker
            if worker is None or str(self._addr.get(worker)) != src \
                    or int(msg.get("att", -1)) != h.replays:
                # a frame from a PREVIOUS assignment/attempt (the
                # request was replayed away — possibly onto the same
                # worker): its tokens overlap the committed prefix —
                # accepting it would double-count the replay
                return
            self._strikes.pop(worker, None)  # liveness proof
            if kind == "progress":
                h.worker_tokens = [int(t) for t in msg.get("tokens") or []]
                h.deadline = time.monotonic() + self.deadline_s
                if h.ttft_s is None and msg.get("ttft_s") is not None:
                    h.ttft_s = float(msg["ttft_s"])
                return
            # done
            self._reqs.pop(rid, None)
            if worker is not None:
                self._outstanding[worker] = max(
                    0, self._outstanding.get(worker, 1) - 1)
            self._completed += 1
            slo.note_queue_depth(len(self._reqs))
        if not msg.get("ok", False):
            self._fail(h, ValueError(msg.get("error") or "worker rejection"),
                       count="reject")
            return
        h.tokens = h.committed + [int(t) for t in msg.get("tokens") or []]
        h.stats = {k: msg.get(k) for k in ("ttft_s", "queue_s",
                                           "reused_tokens",
                                           "computed_tokens")}
        if h.ttft_s is None and msg.get("ttft_s") is not None:
            h.ttft_s = float(msg["ttft_s"])
        h.done_s = time.perf_counter()
        e2e = h.done_s - h.submitted_s
        slo.observe_e2e(e2e)
        timeline.event("request", "complete", rank=self.peer.chaos_rank(),
                       rid=rid, e2e_ms=e2e * 1e3, replays=h.replays,
                       trace=h.trace, parent=h.router_span)
        h._done.set()

    def _fail(self, h: RequestHandle, err: BaseException,
              count: str = "lost") -> None:
        with self._lock:
            self._reqs.pop(h.rid, None)
            slo.note_queue_depth(len(self._reqs))
        h.error = err
        h.done_s = time.perf_counter()
        timeline.event("request", count, rank=self.peer.chaos_rank(),
                       rid=h.rid, trace=h.trace, parent=h.router_span)
        h._done.set()

    # -- the fault ladder --------------------------------------------------
    def _watch_loop(self) -> None:
        while not self._stop.wait(self._watch_period):
            now = time.monotonic()
            expired: List[tuple] = []  # (handle, worker at expiry)
            with self._lock:
                for h in self._reqs.values():
                    if h.worker is not None and now > h.deadline:
                        expired.append((h, h.worker))
            suspects: Dict[int, int] = {}
            for _, w in expired:
                suspects[w] = suspects.get(w, 0) + 1
            newly_dead: List[int] = []
            for w, n in suspects.items():
                with self._lock:
                    if w not in self._live:
                        continue
                    strikes = self._strikes.get(w, 0) + n
                    self._strikes[w] = strikes
                    is_dead = strikes >= self.strike_limit
                if is_dead:
                    newly_dead.append(w)
            for w in newly_dead:
                self.mark_worker_dead(w)
            # a single expired request on a worker that stays under the
            # strike limit (e.g. a chaos-dropped frame) replays alone —
            # keyed on the worker AT EXPIRY: the dead-mark above already
            # re-dispatched its victims, whose h.worker now names the
            # replacement
            for h, w in expired:
                if w not in newly_dead and not h.done():
                    self._replay(h)

    def _replay(self, h: RequestHandle) -> None:
        with self._lock:
            if h.rid not in self._reqs:
                return  # completed while we deliberated
            if h.worker is not None:
                self._outstanding[h.worker] = max(
                    0, self._outstanding.get(h.worker, 1) - 1)
            h.committed = h.committed + h.worker_tokens
            h.worker_tokens = []
            h.replays += 1
            self._replayed += 1
        timeline.event("request", "replay", rank=self.peer.chaos_rank(),
                       rid=h.rid, committed=len(h.committed),
                       trace=h.trace, parent=h.router_span)
        self._dispatch(h)

    def admit_worker(self, rank: int) -> bool:
        """Admit a (newly spawned or recovered) worker into the
        schedulable set — the autoscale execution path
        (:class:`kungfu_tpu.serve.scale.ServeFleet` spawns the engine +
        :class:`ServeWorker`, then admits its rank here).  The rank
        must exist in the peer's cluster membership; a rank previously
        excluded by the fault ladder is re-admitted fresh (zero
        strikes, zero outstanding).  Returns False when already live."""
        workers = self.peer.config.cluster.workers
        if not 0 <= rank < len(workers):
            raise ValueError(
                f"rank {rank} outside the {len(workers)}-worker cluster")
        with self._lock:
            if rank in self._live:
                return False
            self._live.add(int(rank))
            self._dead.discard(int(rank))
            self._addr[int(rank)] = workers[rank]
            self._outstanding[int(rank)] = 0
            self._strikes.pop(int(rank), None)
        timeline.event("serve", "readmit", rank=self.peer.chaos_rank(),
                       ranks=[int(rank)])
        _log.info("serving worker %d admitted", rank)
        return True

    def mark_worker_dead(self, rank: int, readmit: bool = True) -> List[int]:
        """Remove a worker (and, at slice grain, its whole slice) from
        the schedulable set; re-admit its in-flight requests.  Returns
        the ranks excluded by this call."""
        with self._lock:
            if rank not in self._live:
                return []
            excluded = {rank}
            if self.topology is not None:
                dead_slices, degraded = slice_verdict(
                    self._dead | {rank}, self.topology)
                for s in dead_slices | degraded:
                    excluded |= set(self.topology.ranks_in(s))
                excluded &= self._live
            self._live -= excluded
            self._dead |= excluded
            for r in excluded:
                self._strikes.pop(r, None)
            victims = [h for h in self._reqs.values()
                       if h.worker in excluded]
        if self.topology is not None and len(excluded) > 1:
            timeline.event("serve", "slice-dead", rank=self.peer.chaos_rank(),
                           ranks=sorted(excluded))
        else:
            timeline.event("serve", "worker-dead",
                           rank=self.peer.chaos_rank(), ranks=sorted(excluded))
        _log.warning("serving workers %s excluded (%d in-flight to replay)",
                     sorted(excluded), len(victims))
        if readmit:
            for h in victims:
                self._replay(h)
        return sorted(excluded)

    def close(self) -> None:
        self._stop.set()
        self._watchdog.join(2.0)
