"""Autoscale execution: serving resize intents become real workers.

:class:`~kungfu_tpu.policy.serve.ServeAutoscalePolicy` raises worker-
count intents on the standard :class:`~kungfu_tpu.policy.base.
PolicyContext`; until kf-pipeline those intents stopped there (ROADMAP
item-1 leftover).  :class:`ServeFleet` closes the loop:

1. the intent's target is **slice-aligned** through the existing
   :func:`kungfu_tpu.elastic.resize.slice_aligned_size` path (a
   fractional slice has no within-slice mesh to serve from — the same
   rule training resizes obey);
2. when the deployment is elastic (a config server is wired), the
   aligned target is **published** through the existing
   ``Peer.propose_new_size`` path, so watch runners and standby peers
   observe the serving fleet's size exactly like a training job's;
3. the workers themselves are **spawned**: ``spawn_fn(rank)`` builds
   the engine + :class:`~kungfu_tpu.serve.router.ServeWorker` for a
   provisioned rank (in-process in tests, a process under the runner
   in production) and the router admits it
   (:meth:`~kungfu_tpu.serve.router.ServeRouter.admit_worker`);
   scale-down stops the highest spare worker and retires it from the
   schedulable set via the fault ladder's exclusion (no readmit — the
   requests drain first).

Worker setup consumes the unified
:class:`~kungfu_tpu.parallel.train.ParallelPlan`: serving replicas are
dp lanes (``plan.dp`` is the target replica count floor), ``pp`` must
be 1 (a serving worker runs the whole model; cross-DCN pipelined
serving is future work), and ``tp`` is the per-worker local mesh degree
handed to the engine factory.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from kungfu_tpu.monitor import ledger, timeline
from kungfu_tpu.policy.base import PolicyContext
from kungfu_tpu.policy.serve import ServeAutoscalePolicy
from kungfu_tpu.utils.log import get_logger

_log = get_logger("serve-scale")


class ServeFleet:
    """Owns the serving worker set of one router and executes autoscale
    intents as spawns/retires.

    ``spawn_fn(rank) -> ServeWorker`` must return a STARTED worker for
    a provisioned cluster rank; ``stop_fn(rank, worker)`` (optional)
    tears one down on scale-in.  ``plan`` validates the parallel shape
    of the deployment (pp == 1; ``plan.dp`` floors the replica count).
    """

    def __init__(self, router, policy: Optional[ServeAutoscalePolicy],
                 spawn_fn: Callable[[int], object], *,
                 stop_fn: Optional[Callable[[int, object], None]] = None,
                 plan=None):
        if plan is not None:
            if plan.pp != 1:
                raise ValueError(
                    "serving workers run the whole model (plan.pp must "
                    "be 1; pipelined serving is not wired yet)")
            if plan.zero_stage:
                raise ValueError(
                    "serving holds no optimizer state — plan.zero_stage "
                    "must be 0")
        self.router = router
        self.policy = policy or ServeAutoscalePolicy()
        self.plan = plan
        self._spawn = spawn_fn
        self._stop = stop_fn
        #: live worker objects by rank (the spawned ones; pre-existing
        #: workers admitted at router construction are not owned here)
        self.workers: dict = {}
        self._min = (plan.dp if plan is not None
                     else self.policy.min_workers)

    # -- capacity ----------------------------------------------------------
    def _provisioned(self) -> List[int]:
        """Cluster ranks that can host a worker: everything except the
        router's own rank."""
        workers = self.router.peer.config.cluster.workers
        me = workers.rank(self.router.peer.config.self_id)
        return [r for r in range(len(workers)) if r != me]

    def live(self) -> List[int]:
        return self.router.live_workers

    def _aligned(self, target_workers: int) -> Tuple[int, int]:
        """Slice-align through the existing resize path — in CLUSTER
        units, the same units ``propose_new_size`` speaks: the aligned
        total membership is ``target_workers`` plus the non-serving
        ranks (the router), rounded to whole slices by the peer's live
        topology.  Returns ``(aligned_workers, aligned_total)`` so the
        published size and the spawned count can never disagree by the
        router's offset (single-slice deployments pass through)."""
        from kungfu_tpu.elastic.resize import slice_aligned_size

        others = (len(self.router.peer.config.cluster.workers)
                  - len(self._provisioned()))
        total = slice_aligned_size(self.router.peer,
                                   int(target_workers) + others)
        return max(0, total - others), total

    # -- the control tick ---------------------------------------------------
    def tick(self, view: Optional[dict] = None, **metrics) -> List[int]:
        """One autoscale tick: feed the policy (an aggregator
        ``/cluster`` view, or direct ``serve_queued=/serve_e2e_ms=``
        metrics), then execute any intent.  Returns the ranks spawned
        (positive) — retires return an empty list but take effect via
        the router's live set."""
        if view is not None:
            self.policy.observe_view(view)
        ctx = PolicyContext(cluster_size=len(self.live()))
        ctx.metrics.update(metrics)
        self.policy.after_step(ctx)
        target = ctx.requested_size
        if target is None or target == len(self.live()):
            return []
        return self.scale_to(target)

    def scale_to(self, target: int) -> List[int]:
        """Execute a worker-count intent: slice-align, publish through
        the elastic propose path when one is wired, spawn/retire, and
        admit/exclude on the router."""
        live = self.live()
        aligned, total = self._aligned(int(target))
        aligned = max(self._min, aligned)
        spare = [r for r in self._provisioned() if r not in live]
        if aligned > len(self._provisioned()):
            _log.warning(
                "autoscale target %d exceeds the provisioned world "
                "(%d slots) — clamping", aligned, len(self._provisioned()))
            aligned = len(self._provisioned())
            total = aligned + (len(self.router.peer.config.cluster.workers)
                               - len(self._provisioned()))
        peer = self.router.peer
        if peer.config.config_server and peer.rank() == 0:
            # the existing elastic publish path: the config server (and
            # every watch runner) observes the serving fleet's agreed
            # size exactly like a training resize.  ``total`` is already
            # in cluster units AND slice-aligned, so propose_new_size's
            # internal alignment is a no-op — the published membership
            # always matches what the fleet actually runs
            try:
                peer.propose_new_size(total)
            except (OSError, RuntimeError) as e:
                _log.warning("could not publish fleet size: %s", e)
        if aligned > len(live):
            spawned = []
            for r in spare[: aligned - len(live)]:
                w = self._spawn(r)
                self.workers[r] = w
                self.router.admit_worker(r)
                spawned.append(r)
            timeline.event("serve", "scale-up", rank=peer.chaos_rank(),
                           ranks=spawned, target=aligned)
            ledger.record_decision(
                "serve-fleet", "workers", len(live),
                len(live) + len(spawned),
                evidence={"ranks": spawned, "target": aligned},
                effect_series="e2e_ms")
            _log.info("autoscale: spawned workers %s (target %d)",
                      spawned, aligned)
            return spawned
        # scale-in: retire whole FAILURE DOMAINS, highest first — a
        # slice-aware router's mark_worker_dead excludes at slice
        # grain, so retiring one rank of a slice would cascade-exclude
        # its (possibly busy) siblings and replay their requests: the
        # exact latency spike the autoscaler exists to avoid.  Every
        # member of a retire group must be fleet-owned (excluding a
        # pre-existing worker would leave its thread running as a
        # zombie) AND drained (nothing outstanding); a group that
        # fails either check is skipped whole — the next tick retries.
        topo = self.router.topology
        if topo is None:
            groups = [[r] for r in sorted(live, reverse=True)]
        else:
            by_slice: dict = {}
            for r in live:
                by_slice.setdefault(topo.slice_of(r), []).append(r)
            groups = [sorted(by_slice[s])
                      for s in sorted(by_slice, reverse=True)]
        floor = max(self._min, aligned)
        remaining = len(live)
        retire = []
        for g in groups:
            if remaining - len(g) < floor:
                continue
            busy = [r for r in g if self.router.outstanding(r) > 0]
            if busy or any(r not in self.workers for r in g):
                if busy:
                    _log.info("autoscale: workers %s still have work "
                              "outstanding — deferring their retire",
                              busy)
                continue
            retire.append(g)
            remaining -= len(g)
        victims = []
        for g in retire:
            excluded = self.router.mark_worker_dead(g[0], readmit=True)
            for r in sorted(set(excluded) | set(g)):
                victims.append(r)
                w = self.workers.pop(r, None)
                if w is not None:
                    (self._stop or (lambda _r, _w: _w.stop()))(r, w)
        if victims:
            timeline.event("serve", "scale-down", rank=peer.chaos_rank(),
                           ranks=victims, target=aligned)
            ledger.record_decision(
                "serve-fleet", "workers", len(live),
                len(live) - len(victims),
                evidence={"ranks": victims, "target": aligned},
                effect_series="e2e_ms")
            _log.info("autoscale: retired workers %s (target %d)",
                      victims, aligned)
        return []
