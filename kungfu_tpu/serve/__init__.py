"""kf-serve: the elastic inference plane.

Turns a :class:`~kungfu_tpu.peer.Peer` world into an inference
deployment over the substrate the training arc built — host transport
with registered receives and load-scaled responder pools, elastic
membership with slice-aware shrink, the aggregator/kftop observability
plane:

* :mod:`kungfu_tpu.serve.kvcache` — paged KV-cache block manager
  (fixed-size pages, free-list allocation, prefix-hash reuse, LRU
  eviction) whose per-rank footprint is the ``kf_kv_cache_bytes`` gauge
  next to ``kf_opt_state_bytes``;
* :mod:`kungfu_tpu.serve.engine` — continuous-batching decode loop over
  :mod:`kungfu_tpu.models.transformer` (jit-compiled prefill/decode
  steps, decode-priority admission);
* :mod:`kungfu_tpu.serve.router` — request router + admission policy
  (FCFS, bounded queue, typed overload rejection) speaking over the
  existing host channel / p2p handler machinery, with SLO-gated fault
  tolerance: a killed worker or killed slice is detected, excluded at
  the slice grain when a topology exists, and its in-flight requests
  replay from the last committed decode position on survivors;
* :mod:`kungfu_tpu.serve.slo` — TTFT / per-token / e2e latency
  histograms in the unified registry, flowing through aggregator
  snapshots to the kftop serving view.

Design + SLO methodology + failure semantics: docs/serving.md.
"""

from kungfu_tpu.serve.kvcache import (CacheExhausted, KVCachePool, PageSpec,
                                      chain_hashes)
from kungfu_tpu.serve.slo import SLOTargets, slo_snapshot

__all__ = [
    "CacheExhausted",
    "KVCachePool",
    "PageSpec",
    "chain_hashes",
    "SLOTargets",
    "slo_snapshot",
    "InferenceEngine",
    "ServeRouter",
    "ServeWorker",
    "RequestHandle",
]


def __getattr__(name):
    # engine/router import jax and the comm stack — lazy, so the pure
    # kvcache/slo units (and stdlib-only tooling) stay importable alone
    if name == "InferenceEngine":
        from kungfu_tpu.serve.engine import InferenceEngine

        return InferenceEngine
    if name in ("ServeRouter", "ServeWorker", "RequestHandle"):
        from kungfu_tpu.serve import router as _router

        return getattr(_router, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
