"""Continuous-batching inference engine over the flagship transformer.

One engine = one replica: it owns the params, a device-side KV slab of
``max_batch`` decode slots, and a :class:`~kungfu_tpu.serve.kvcache.
KVCachePool` for host-side page accounting.  The loop discipline is
**decode-priority continuous batching** (the Orca/vLLM scheduling
shape): every :meth:`step` first admits at most ``admit_per_step``
pending prefills into free slots, then runs ONE jit-compiled decode
step for ALL active slots — new requests join the running batch between
decode steps instead of waiting for a batch boundary, and long prompts
cannot starve in-flight decodes.

Phases are jit-compiled with static shapes (one trace per prefill
length bucket + one decode trace — the recompile-hazard discipline):

* **prefill** — forward over the un-cached prompt suffix, writing K/V
  into the slab at ``[cached, prompt_len)`` and emitting the first
  generated token.  The cached prefix comes straight out of the paged
  pool (prefix-chain hit), so a shared system prompt costs its pages'
  load, not its FLOPs — the measured delta in ``bench.py --serve``.
* **decode** — one token for every active slot: write K/V at each
  slot's position, attend over ``[0, pos]``, greedy argmax (greedy on
  purpose: a replayed request deterministically re-derives the same
  continuation from its committed prefix, docs/serving.md).

Fault surface: the engine is process-local and carries no collective
state — worker death is handled ABOVE it by the router's replay ladder
(serve/router.py); the engine only guarantees that completed requests
committed their full pages to the pool first.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from kungfu_tpu.models import nn
from kungfu_tpu.models.transformer import Transformer, _rope
from kungfu_tpu.monitor import timeline
from kungfu_tpu.ops import costmodel
from kungfu_tpu.serve import slo
from kungfu_tpu.serve.kvcache import CacheExhausted, KVCachePool, PageSpec
from kungfu_tpu.utils import envs

DEFAULT_MAX_BATCH = 8
DEFAULT_MAX_TOKENS = 256


class _Req:
    __slots__ = ("rid", "tokens", "max_new", "generated", "slot", "pages",
                 "reused", "computed", "submitted_s", "admitted_s",
                 "first_token_s", "canceled", "trace", "parent")

    def __init__(self, rid: str, tokens: Sequence[int], max_new: int,
                 trace=None):
        self.rid = rid
        self.tokens = tuple(int(t) for t in tokens)
        self.max_new = int(max_new)
        # kf-xray causal context (the router's trace, via the frame meta)
        self.trace, self.parent = timeline.parse_trace_context(trace)
        self.generated: List[int] = []
        self.slot = -1
        self.pages: List[int] = []
        self.reused = 0
        self.computed = 0
        self.submitted_s = time.perf_counter()
        self.admitted_s = 0.0
        self.first_token_s = 0.0
        self.canceled = False

    @property
    def total_len(self) -> int:
        return len(self.tokens) + len(self.generated)


class InferenceEngine:
    """Single-replica continuous-batching decode loop (one per serving
    worker; thread-safe submit, single-threaded :meth:`step`)."""

    def __init__(self, model: Transformer, params, *,
                 pool: Optional[KVCachePool] = None,
                 max_batch: Optional[int] = None,
                 max_seq: Optional[int] = None,
                 page_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 admit_per_step: int = 1,
                 rank: Optional[int] = None,
                 plan=None):
        cfg = model.cfg
        if plan is not None:
            # the unified ParallelPlan route (parallel/train.py): a
            # serving worker is one dp replica of the whole model —
            # pipelined/TP-sharded serving engines are future work, so
            # a plan asking for them must fail loudly here, not
            # silently serve an unsharded model
            if plan.pp != 1 or plan.tp != 1 or plan.sp != 1:
                raise NotImplementedError(
                    f"InferenceEngine serves one full-model replica per "
                    f"worker; plan carries pp={plan.pp} tp={plan.tp} "
                    f"sp={plan.sp} (TP-sharded serving is ROADMAP work)")
            if plan.zero_stage:
                raise ValueError("serving holds no optimizer state — "
                                 "plan.zero_stage must be 0")
        self.plan = plan
        self.model = model
        self.params = params
        self.rank = rank
        self.eos_id = eos_id
        self.admit_per_step = max(1, int(admit_per_step))
        self.max_batch = int(max_batch if max_batch is not None
                             else envs.parse_int_env(envs.SERVE_MAX_BATCH,
                                                     DEFAULT_MAX_BATCH))
        self.max_seq = int(max_seq or cfg.max_seq)
        self.pool = pool if pool is not None else KVCachePool(
            PageSpec.for_model(cfg, page_tokens=page_tokens))
        self._page_tokens = self.pool.spec.page_tokens
        self._width = self.max_batch  # admitted width (policy-adjustable)
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._pending: "deque[_Req]" = deque()
        self._active: Dict[int, _Req] = {}       # slot -> request
        self._free_slots = list(range(self.max_batch - 1, -1, -1))
        self._steps = 0
        # device KV slab: [L, B, H, S, D] in compute dtype
        L, B, H, S, D = (cfg.n_layers, self.max_batch, cfg.n_heads,
                        self.max_seq, cfg.head_dim)
        dt = cfg.compute_dtype
        self._k = jnp.zeros((L, B, H, S, D), dt)
        self._v = jnp.zeros((L, B, H, S, D), dt)
        # no donate_argnums: the CPU backend ignores donation (with a
        # warning per compile); on chip the slab update is small next to
        # the model math and the jit cache keys per prefill bucket shape
        self._decode_j = jax.jit(self._decode_fn)
        self._prefill_j = jax.jit(self._prefill_fn)
        # kf-xray serving MFU: analytic prefill/decode FLOPs accumulate
        # per step into the kf_model_flops_s gauge (+ kf_mfu when a chip
        # peak is known; None on the CPU mesh — docs/xray.md)
        self._mfu = costmodel.MFUMeter(rank=rank)

    # -- forward passes --------------------------------------------------
    def _layer_qkv(self, lp, x, positions):
        cfg = self.model.cfg
        dt = cfg.compute_dtype

        def heads(t):
            b, s, _ = t.shape
            return t.reshape(b, s, cfg.n_heads, cfg.head_dim
                             ).transpose(0, 2, 1, 3)

        q = heads(nn.dense_apply(lp["wq"], x, dtype=dt))
        k = heads(nn.dense_apply(lp["wk"], x, dtype=dt))
        v = heads(nn.dense_apply(lp["wv"], x, dtype=dt))
        if cfg.pos == "rope":
            q, k = _rope(q, k, positions)
        return q, k, v

    @staticmethod
    def _attend(q, keys, values, mask):
        """q [B,H,Q,D] over keys/values [B,H,S,D]; mask [B,1,Q,S] (or
        broadcastable) True = attend.  f32 logits/softmax like the
        training path."""
        d = q.shape[-1]
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, keys
                            ).astype(jnp.float32) / jnp.sqrt(d)
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, values)

    def _merge(self, x):
        b, h, s, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    def _prefill_fn(self, params, k_slab, v_slab, ids, n, start, slot):
        """ids [S_pad] (suffix, zero-padded past ``n``); writes K/V at
        positions ``[start, start + S_pad)`` of ``slot`` and returns the
        greedy next token after the last REAL row (``n - 1``)."""
        cfg = self.model.cfg
        dt = cfg.compute_dtype
        s_pad = ids.shape[0]
        s_max = k_slab.shape[3]
        positions = start + jnp.arange(s_pad)
        h = nn.embedding_apply(params["embed"], ids[None], dtype=dt)
        if cfg.pos == "learned":
            h = h + nn.embedding_apply(params["pos_embed"], positions[None],
                                       dtype=dt)
        q_pos = positions
        key_pos = jnp.arange(s_max)
        mask = (key_pos[None, :] <= q_pos[:, None])[None, None]  # [1,1,Q,S]
        for li in range(cfg.n_layers):
            lp = params[f"layer_{li}"]
            x = nn.layernorm_apply(lp["ln1"], h)
            q, k, v = self._layer_qkv(lp, x, positions[None])
            k_slab = jax.lax.dynamic_update_slice(
                k_slab, k[None], (li, slot, 0, start, 0))
            v_slab = jax.lax.dynamic_update_slice(
                v_slab, v[None], (li, slot, 0, start, 0))
            keys = jax.lax.dynamic_index_in_dim(k_slab[li], slot, 0,
                                                keepdims=True)
            values = jax.lax.dynamic_index_in_dim(v_slab[li], slot, 0,
                                                  keepdims=True)
            o = self._merge(self._attend(q, keys, values, mask))
            h = h + nn.dense_apply(lp["wo"], o, dtype=dt)
            x = nn.layernorm_apply(lp["ln2"], h)
            y = nn.gelu(nn.dense_apply(lp["ffn_in"], x, dtype=dt))
            h = h + nn.dense_apply(lp["ffn_out"], y, dtype=dt)
        h = nn.layernorm_apply(params["ln_f"], h)
        last = jax.lax.dynamic_index_in_dim(h, n - 1, axis=1, keepdims=False)
        logits = nn.dense_apply(params["head"], last).astype(jnp.float32)
        return k_slab, v_slab, jnp.argmax(logits[0], axis=-1).astype(jnp.int32)

    def _decode_fn(self, params, k_slab, v_slab, last_ids, pos):
        """One token for every slot: ``last_ids``/``pos`` are [B]; the
        new K/V lands at each slot's ``pos`` and attention covers
        ``[0, pos]``.  Inactive slots compute garbage nobody reads."""
        cfg = self.model.cfg
        dt = cfg.compute_dtype
        s_max = k_slab.shape[3]
        positions = pos[:, None]                     # [B, 1]
        h = nn.embedding_apply(params["embed"], last_ids[:, None], dtype=dt)
        if cfg.pos == "learned":
            h = h + nn.embedding_apply(params["pos_embed"], positions,
                                       dtype=dt)
        mask = (jnp.arange(s_max)[None, :] <= positions)[:, None, None, :]

        def upd(slab_b, new_b, p):  # [H,S,D], [H,1,D], scalar
            return jax.lax.dynamic_update_slice(slab_b, new_b, (0, p, 0))

        for li in range(cfg.n_layers):
            lp = params[f"layer_{li}"]
            x = nn.layernorm_apply(lp["ln1"], h)
            q, k, v = self._layer_qkv(lp, x, positions)
            k_l = jax.vmap(upd)(k_slab[li], k, pos)
            v_l = jax.vmap(upd)(v_slab[li], v, pos)
            k_slab = k_slab.at[li].set(k_l)
            v_slab = v_slab.at[li].set(v_l)
            o = self._merge(self._attend(q, k_l, v_l, mask))
            h = h + nn.dense_apply(lp["wo"], o, dtype=dt)
            x = nn.layernorm_apply(lp["ln2"], h)
            y = nn.gelu(nn.dense_apply(lp["ffn_in"], x, dtype=dt))
            h = h + nn.dense_apply(lp["ffn_out"], y, dtype=dt)
        h = nn.layernorm_apply(params["ln_f"], h)
        logits = nn.dense_apply(params["head"], h[:, 0]).astype(jnp.float32)
        return k_slab, v_slab, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _prefill_bucket(self, n: int) -> int:
        """Static prefill length: the smallest power-of-two multiple of
        the page size holding ``n`` (one compile per bucket, ever)."""
        b = max(self._page_tokens, 1)
        while b < n:
            b *= 2
        return min(b, self.max_seq)

    def warmup(self, prompt_lens: Sequence[int] = (8,)) -> None:
        """Compile the decode step and EVERY prefill bucket up to the
        one covering ``max(prompt_lens)`` before serving starts.
        Cold-start compiles otherwise land on a live request's clock —
        long enough to stall the worker loop (decode AND its liveness
        keepalives) and read as a dead worker.  The smaller rungs are
        not optional: a prefix-cache hit prefills only its SUFFIX, so
        the first reuse of a warmed long prompt would otherwise compile
        the smallest bucket mid-service — exactly the stall this method
        exists to pay up front."""
        top = self._prefill_bucket(max(max(prompt_lens), 1))
        buckets, b = [], max(self._page_tokens, 1)
        while b < top:
            buckets.append(b)
            b *= 2
        buckets.append(top)
        for s_pad in buckets:
            ids = jnp.zeros(s_pad, jnp.int32)
            # results discarded: jit populates its trace cache, the live
            # slabs are untouched (functional updates on copies)
            self._prefill_j(self.params, self._k, self._v, ids,
                            jnp.int32(1), jnp.int32(0), jnp.int32(0)
                            )[2].block_until_ready()
        self._decode_j(self.params, self._k, self._v,
                       jnp.zeros(self.max_batch, jnp.int32),
                       jnp.zeros(self.max_batch, jnp.int32)
                       )[2].block_until_ready()

    # -- scheduling ------------------------------------------------------
    @property
    def width(self) -> int:
        return self._width

    def set_width(self, w: int) -> int:
        """Admitted decode width (<= max_batch); the policy layer's
        batch-width controller moves this, never the slab shape."""
        with self._lock:
            self._width = max(1, min(int(w), self.max_batch))
            return self._width

    def submit(self, rid: str, tokens: Sequence[int], max_new: int,
               trace: Optional[str] = None) -> None:
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) + max_new > self.max_seq:
            raise ValueError(
                f"request {rid!r}: {len(tokens)} prompt + {max_new} new "
                f"tokens exceeds max_seq {self.max_seq}")
        req = _Req(rid, tokens, max_new, trace=trace)
        with self._wake:
            self._pending.append(req)
            self._wake.notify_all()

    def cancel(self, rid: str) -> bool:
        """Drop a request.  Pending requests leave immediately; an
        ACTIVE (or mid-admission) request is only FLAGGED — the step
        thread retires it at the next boundary.  Retirement must stay
        single-threaded: a cross-thread release here would race
        ``_complete``'s page commit (put_page_data on a freed page)."""
        with self._lock:
            for i, r in enumerate(self._pending):
                if r.rid == rid:
                    del self._pending[i]
                    return True
            for r in self._active.values():
                if r.rid == rid:
                    r.canceled = True
                    return True
        return False

    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def wait_for_work(self, timeout: float) -> bool:
        """Park the loop thread until work arrives (bounded)."""
        with self._wake:
            if self._pending or self._active:
                return True
            return self._wake.wait(timeout)

    # -- admission (prefill phase) ---------------------------------------
    def _try_admit(self, req: _Req) -> bool:
        T = self._page_tokens
        budget = len(req.tokens) + req.max_new
        n_pages = -(-budget // T)
        cached_pages, n_cached = self.pool.lookup(req.tokens)
        # at least one prompt token must run the forward — the last row's
        # hidden state is where the first generated token comes from
        max_reuse = ((len(req.tokens) - 1) // T) * T
        while n_cached > max_reuse:
            self.pool.release([cached_pages.pop()])
            n_cached -= T
        # the padded prefill must FIT the slab past the cached offset:
        # start + bucket(suffix) > max_seq would make dynamic_update_slice
        # silently clamp the write over the restored prefix (corrupt K/V
        # that _complete would then commit into the prefix chain).  Give
        # reuse back until the rounded suffix fits — n_cached = 0 always
        # does, since submit() bounds the prompt by max_seq
        while n_cached > 0 and (
                n_cached + self._prefill_bucket(len(req.tokens) - n_cached)
                > self.max_seq):
            self.pool.release([cached_pages.pop()])
            n_cached -= T
        try:
            fresh = self.pool.alloc(n_pages - len(cached_pages))
        except CacheExhausted:
            self.pool.release(cached_pages)
            return False
        req.pages = cached_pages + fresh
        req.reused = n_cached
        with self._lock:
            slot = self._free_slots.pop()
        req.slot = slot
        req.admitted_s = time.perf_counter()
        if n_cached:
            ks = np.stack([self.pool.page_data(p)[0] for p in cached_pages],
                          axis=2)  # [L, H, n_pages, T, D] stacked on axis 2
            vs = np.stack([self.pool.page_data(p)[1] for p in cached_pages],
                          axis=2)
            L, H = ks.shape[0], ks.shape[1]
            ks = ks.reshape(L, H, n_cached, -1)
            vs = vs.reshape(L, H, n_cached, -1)
            dt = self.model.cfg.compute_dtype
            self._k = self._k.at[:, slot, :, :n_cached, :].set(
                jnp.asarray(ks, dt))
            self._v = self._v.at[:, slot, :, :n_cached, :].set(
                jnp.asarray(vs, dt))
        suffix = req.tokens[n_cached:]
        s_pad = self._prefill_bucket(len(suffix))
        ids = np.zeros(s_pad, np.int32)
        ids[:len(suffix)] = suffix
        tc_attrs = timeline.context_attrs(req.trace, req.parent)
        with timeline.span("serve", "prefill", rank=self.rank,
                           tokens=len(suffix), reused=n_cached,
                           rid=req.rid, **tc_attrs):
            self._k, self._v, tok = self._prefill_j(
                self.params, self._k, self._v, jnp.asarray(ids),
                jnp.int32(len(suffix)), jnp.int32(n_cached), jnp.int32(slot))
        req.computed = len(suffix)
        self._mfu.add_flops(costmodel.serve_prefill_flops(
            self.model.cfg, len(suffix), n_cached))
        req.first_token_s = time.perf_counter()
        req.generated.append(int(tok))
        slo.count_prefill(computed=len(suffix), reused=n_cached)
        with self._lock:
            self._active[slot] = req
        return True

    # -- completion ------------------------------------------------------
    def _retire_locked(self, slot: int, req: _Req) -> None:
        # idempotent: a cancel() racing the decode loop must not free a
        # slot twice or double-release pages
        if self._active.pop(slot, None) is None:
            return
        self._free_slots.append(slot)
        if req.pages:
            self.pool.release(req.pages)
            req.pages = []

    def _complete(self, slot: int, req: _Req) -> dict:
        T = self._page_tokens
        # commit the full pages this request produced (beyond the reused
        # prefix) so the next shared-prefix request skips their prefill
        seq = list(req.tokens) + req.generated
        # K/V exists for positions [0, total_len - 1): the final token
        # was emitted but never ran through the stack
        full = (req.total_len - 1) // T
        first_new = req.reused // T
        if full > first_new and req.pages:
            kb = np.asarray(jax.device_get(
                self._k[:, req.slot, :, first_new * T:full * T, :]))
            vb = np.asarray(jax.device_get(
                self._v[:, req.slot, :, first_new * T:full * T, :]))
            for p in range(first_new, full):
                lo = (p - first_new) * T
                self.pool.put_page_data(req.pages[p],
                                        kb[:, :, lo:lo + T, :],
                                        vb[:, :, lo:lo + T, :])
            self.pool.commit_chain(seq[:full * T], req.pages[:full])
        done_s = time.perf_counter()
        stats = {
            "rid": req.rid,
            "tokens": list(req.generated),
            "ttft_s": req.first_token_s - req.submitted_s,
            "queue_s": req.admitted_s - req.submitted_s,
            "engine_s": done_s - req.submitted_s,
            "reused_tokens": req.reused,
            "computed_tokens": req.computed,
        }
        slo.observe_ttft(stats["ttft_s"])
        with self._lock:
            self._retire_locked(slot, req)
        return stats

    def _is_done(self, req: _Req) -> bool:
        if len(req.generated) >= req.max_new:
            return True
        return self.eos_id is not None and req.generated[-1] == self.eos_id

    # -- the step --------------------------------------------------------
    def step(self) -> List[dict]:
        """One continuous-batching iteration: admit (bounded), decode
        every active slot, retire finished requests.  Returns events:
        ``{"kind": "admit"|"token"|"done", ...}`` in occurrence order."""
        events: List[dict] = []
        self._steps += 1
        t_step0 = time.perf_counter()
        admitted = 0
        while admitted < self.admit_per_step:
            with self._lock:
                can = (self._pending and self._free_slots
                       and len(self._active) < self._width)
                req = self._pending.popleft() if can else None
            if req is None:
                break
            if not self._try_admit(req):
                with self._lock:
                    self._pending.appendleft(req)  # FCFS: keep its turn
                break
            admitted += 1
            events.append({"kind": "admit", "rid": req.rid,
                           "reused": req.reused, "computed": req.computed})
            events.append({"kind": "token", "rid": req.rid,
                           "tok": req.generated[-1], "n": 1})
            if self._is_done(req):
                events.append({"kind": "done", **self._complete(req.slot, req)})
        # consume cancel flags on the step thread (the only retirer)
        with self._lock:
            doomed = [(s, r) for s, r in self._active.items() if r.canceled]
            for s, r in doomed:
                self._retire_locked(s, r)
        with self._lock:
            active = dict(self._active)
        if active:
            B = self.max_batch
            last = np.zeros(B, np.int32)
            pos = np.zeros(B, np.int32)
            for slot, r in active.items():
                last[slot] = r.generated[-1]
                pos[slot] = r.total_len - 1
            t0 = time.perf_counter()
            with timeline.span("serve", "decode", rank=self.rank,
                               batch=len(active)):
                self._k, self._v, nxt = self._decode_j(
                    self.params, self._k, self._v,
                    jnp.asarray(last), jnp.asarray(pos))
            nxt = np.asarray(jax.device_get(nxt))
            slo.observe_token(time.perf_counter() - t0)
            cfg = self.model.cfg
            self._mfu.add_flops(sum(
                costmodel.serve_decode_flops(cfg, int(pos[slot]) + 1)
                for slot in active))
            for slot, r in active.items():
                r.generated.append(int(nxt[slot]))
                events.append({"kind": "token", "rid": r.rid,
                               "tok": int(nxt[slot]), "n": len(r.generated)})
                if self._is_done(r):
                    events.append({"kind": "done", **self._complete(slot, r)})
        self._mfu.step(wall_s=time.perf_counter() - t_step0)
        slo.note_active(self.active_count)
        return events

    def drain(self, max_steps: int = 10_000) -> List[dict]:
        """Run steps until idle (tests / local mode); bounded so a
        non-terminating request cannot wedge the caller."""
        out: List[dict] = []
        for _ in range(max_steps):
            if not (self.pending_count or self.active_count):
                break
            out.extend(self.step())
        return out
