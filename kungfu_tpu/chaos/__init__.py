"""Deterministic, seedable fault injection for the kungfu-tpu runtime.

The chaos layer turns "we think shrink-to-survivors works" into a
tier-1 assertion: faults that normally need a flaky multi-host repro —
a worker dying mid-allreduce, a connection reset halfway through a
chunk, a straggler, a lost detector fan-out, a config-server brownout —
are injected at exact, reproducible points (Nth collective, Nth send,
Nth fetch) controlled entirely by two env vars:

``KF_CHAOS_SPEC``
    The fault clauses (grammar in :mod:`kungfu_tpu.chaos.spec`).
    Unset ⇒ every hook is a ``None``-check no-op and the wire behavior
    is byte-identical to an injection-free build.
``KF_CHAOS_SEED``
    Seeds the (only) randomized perturbation, delay jitter.

Hook sites: the collective engine's send/recv
(:mod:`kungfu_tpu.comm.engine`), the Python host channel's frame writer
(:meth:`~kungfu_tpu.comm.host.PyHostChannel.chaos_partial_send`), the
failure detector's fan-out (:mod:`kungfu_tpu.monitor.detector`), the
elastic config fetch (:mod:`kungfu_tpu.elastic.resize`), and the train
loop's step announcement (:func:`note_step`, called by
:func:`kungfu_tpu.elastic.hooks.elastic_step`).

See :doc:`docs/fault_tolerance` for the failure model and the fault
matrix.
"""

from kungfu_tpu.chaos.inject import (
    DIE_EXIT_CODE,
    ChaosController,
    InjectedDeath,
    InjectedReset,
    SEED_ENV,
    SPEC_ENV,
    controller_for,
    note_step,
    reset,
)
from kungfu_tpu.chaos.spec import Clause, parse_spec

__all__ = [
    "DIE_EXIT_CODE",
    "ChaosController",
    "Clause",
    "InjectedDeath",
    "InjectedReset",
    "SEED_ENV",
    "SPEC_ENV",
    "controller_for",
    "note_step",
    "parse_spec",
    "reset",
]
