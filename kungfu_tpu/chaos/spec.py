"""``KF_CHAOS_SPEC`` grammar: deterministic fault clauses.

A spec is ``;``-separated clauses; each clause is a fault kind followed
by ``key=value`` params::

    kind[:key=value[,key=value...]]

Example — rank 2 dies on its 3rd allreduce, rank 0's 2nd collective send
is reset mid-chunk, rank 1 straggles 200 ms on every send, the detector
drops its fan-out to one host, and the config server is dark for fetch
calls 3..5::

    KF_CHAOS_SPEC="die:coll=3,rank=2;reset:send=2,rank=0;\
delay:ms=200,rank=1;drop_fanout:host=10.0.0.7;config_down:after=2,count=3"

Fault kinds and their params (``rank=R`` scopes a clause to the
controller built for rank R — except ``drop_fanout``, which runs in the
detector's rank-less controller and is scoped by ``host=`` instead;
without scoping a clause applies everywhere):

``die``
    Kill this worker.  Trigger: ``step=N`` (the training loop announced
    step N via :func:`kungfu_tpu.chaos.note_step`) or ``coll=N`` (this
    rank's Nth engine collective, 1-based).  ``mode=exit`` (default —
    ``os._exit(43)``, a real process death) or ``mode=raise`` (raise
    :class:`~kungfu_tpu.chaos.inject.InjectedDeath` in the collective;
    for in-process test clusters where ``_exit`` would take the whole
    interpreter down).
``die_slice``
    Kill every rank of TPU slice ``slice=S`` — the multislice failure
    grain (a slice loses DCN/power as a unit; docs/multislice.md).  Each
    rank's controller evaluates its OWN slice id against ``S``:
    ``MEGASCALE_SLICE_ID`` when the launcher set it (kfrun emulation /
    real pod env), else ``rank // rps`` when ``rps=K`` (ranks per slice)
    is given — in-process multi-rank test clusters have one env, so they
    pass ``rps``.  Triggers and ``mode`` as for ``die``; all matching
    ranks fire at the same step/collective boundary, so the whole slice
    goes down "at once", deterministically under ``KF_CHAOS_SEED``
    (death needs no randomness — the seed only ever feeds delay jitter).
``reset``
    Connection reset mid-chunk: on this rank's Nth engine send
    (``send=N``), transmit a frame header promising the full chunk,
    deliver only half the payload, kill the socket, and raise
    ``InjectedReset`` at the sender — the receiver observes a
    peer-closed-mid-message stream, the sender's bounded retry path
    re-sends.  ``peer=R`` restricts to sends toward rank R.
``delay``
    Straggler: sleep ``ms=X`` (+ uniform ``jitter=Y`` ms, seeded by
    ``KF_CHAOS_SEED``) before a send.  ``peer=R`` restricts the target;
    ``every=K`` delays only every Kth matching send (default 1 = all);
    ``on=recv`` delays the receive side instead, ``on=ping`` the
    latency-probe pings (``get_peer_latencies``) — a throttled link
    must look slow to the MST re-carve, not just to the data path —
    and ``on=serve`` the serving request path (the worker straggles
    ``ms`` before admitting each matching request, kf-serve).
    ``after_step=N`` keeps the clause INERT until the training loop
    announces step N via :func:`kungfu_tpu.chaos.note_step` — a
    mid-run onset, so a regression experiment gets a clean baseline
    phase and a planted degradation from one deterministic step
    boundary (the kf-sentinel changepoint gate).  Matching-event
    counts (``every``) start at the onset, not at process start.
``preempt``
    Whole-job preemption: EVERY rank dies at the same boundary — the
    spot/maintenance eviction that takes the entire capacity at once
    (no survivors, so only the durable manifest plane of
    ``elastic/persist.py`` can recover; docs/persistence.md).  The
    mandatory bare ``all`` token makes the blast radius explicit:
    ``preempt:all[,step=N][,mode=...]``.  ``step=N`` fires when the
    training loop announces step N (without it, the first announced
    step); ``mode`` as for ``die``.  Deliberately NOT rank-scopable —
    a partial kill is ``die``/``die_slice``; preemption means all.
``drop_request``
    The serving plane loses an incoming request frame: this rank's
    serve handler silently discards every matching request
    (``every=K`` strides over matching requests, ``count=N`` bounds
    the total dropped; both default to all) — the router's per-request
    deadline then re-admits it elsewhere, exactly the lost-frame /
    half-open-connection failure the strike ladder exists for
    (docs/serving.md).
``drop_fanout``
    The failure detector's cross-host fan-out silently loses its POST to
    ``host=H`` (absent = every host); ``count=N`` drops only the first N
    (default: all).
``config_down``
    Config-server unavailability window, in units of fetch attempts:
    fetches ``after+1 .. after+count`` fail (``after`` default 0,
    ``count`` default 1) — deterministic regardless of wall clock.

Parsing is strict: an unknown kind or a malformed param raises
``ValueError`` at controller construction — a typo'd chaos spec must
fail the experiment loudly, not silently run fault-free.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

KINDS = ("die", "die_slice", "preempt", "reset", "delay", "drop_fanout",
         "drop_request", "config_down")

_INT_PARAMS = {
    "rank", "step", "coll", "send", "peer", "every", "count", "after",
    "ms", "jitter", "slice", "rps", "after_step",
}
_STR_PARAMS = {"mode", "host", "on"}

_ALLOWED = {
    "die": {"rank", "step", "coll", "mode"},
    "die_slice": {"slice", "step", "coll", "mode", "rps"},
    "preempt": {"all", "step", "mode"},
    "reset": {"rank", "send", "peer"},
    "delay": {"rank", "ms", "jitter", "peer", "every", "on", "after_step"},
    "drop_fanout": {"host", "count"},
    "drop_request": {"rank", "count", "every"},
    "config_down": {"rank", "after", "count"},
}


@dataclass(frozen=True)
class Clause:
    kind: str
    params: Tuple[Tuple[str, object], ...] = field(default=())

    def get(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default

    @property
    def rank(self) -> Optional[int]:
        return self.get("rank")

    def matches_rank(self, rank: Optional[int]) -> bool:
        want = self.rank
        return want is None or want == rank


def _parse_clause(text: str) -> Clause:
    kind, _, rest = text.partition(":")
    kind = kind.strip()
    if kind not in KINDS:
        raise ValueError(f"unknown chaos fault kind {kind!r} (one of {KINDS})")
    params: Dict[str, object] = {}
    if rest.strip():
        for item in rest.split(","):
            key, eq, val = item.partition("=")
            key, val = key.strip(), val.strip()
            if kind == "preempt" and key == "all" and not eq:
                # the explicit blast-radius token, not a key=value pair
                params["all"] = True
                continue
            if not eq or not key or not val:
                raise ValueError(f"malformed chaos param {item!r} in {text!r}")
            if key not in _ALLOWED[kind]:
                raise ValueError(
                    f"param {key!r} not valid for {kind!r} "
                    f"(allowed: {sorted(_ALLOWED[kind])})"
                )
            if key in _INT_PARAMS:
                try:
                    params[key] = int(val)
                except ValueError:
                    raise ValueError(
                        f"chaos param {key}={val!r} must be an integer"
                    ) from None
            else:
                params[key] = val
    mode = params.get("mode")
    if kind in ("die", "die_slice", "preempt") \
            and mode not in (None, "exit", "raise"):
        raise ValueError(f"{kind} mode must be exit|raise, got {mode!r}")
    if kind == "die_slice" and params.get("slice") is None:
        raise ValueError("die_slice needs slice=S (the slice to kill)")
    if kind == "preempt" and params.get("all") is not True:
        raise ValueError(
            "preempt needs the explicit 'all' scope (preempt:all[,step=N])"
            " — a partial kill is die/die_slice")
    if kind == "delay" and params.get("on") not in (None, "send", "recv",
                                                    "ping", "serve"):
        raise ValueError(
            f"delay on= must be send|recv|ping|serve, got "
            f"{params.get('on')!r}")
    return Clause(kind, tuple(sorted(params.items())))


def parse_spec(text: str) -> List[Clause]:
    """Parse a ``KF_CHAOS_SPEC`` value; raises ``ValueError`` on junk."""
    clauses = []
    for part in text.split(";"):
        part = part.strip()
        if part:
            clauses.append(_parse_clause(part))
    if not clauses:
        raise ValueError("KF_CHAOS_SPEC is set but contains no clauses")
    return clauses
