"""Deterministic fault injection: the controller behind the data-path hooks.

One :class:`ChaosController` exists per (spec, seed, rank) — the engine
holds the instance for its own rank, the detector and other rank-less
subsystems use the ``rank=None`` instance — so trigger counters (Nth
collective, Nth send, Nth config fetch) are deterministic given a
deterministic call sequence, and an in-process multi-rank test cluster
can target one victim rank while its siblings run fault-free.

The contract that makes this shippable in the hot path: with
``KF_CHAOS_SPEC`` unset, :func:`controller_for` returns ``None`` and
every call site guards with ``if chaos is not None`` — the disabled cost
is one attribute load + branch, and the wire behavior is byte-identical
to a build without the hooks (tier-1 asserts this).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import List, Optional

from kungfu_tpu.chaos.spec import Clause, parse_spec
from kungfu_tpu.monitor import timeline
from kungfu_tpu.utils import envs
from kungfu_tpu.utils.log import get_logger

_log = get_logger("chaos")

# the registry (utils/envs.py) is the single authority for KF_* names;
# chaos was the one subsystem naming its envs locally — drift bait
SPEC_ENV = envs.CHAOS_SPEC
SEED_ENV = envs.CHAOS_SEED

#: worker exit status for ``die`` faults in ``exit`` mode — distinct from
#: real crash codes so the runner's logs attribute the death to chaos
DIE_EXIT_CODE = 43


class InjectedDeath(Exception):
    """A ``die`` fault in ``mode=raise`` — the in-process stand-in for a
    worker process vanishing (the thread playing the victim should close
    its channel and stop participating)."""


class InjectedReset(ConnectionResetError):
    """A ``reset`` fault at the sender: the wire saw a truncated frame;
    a ``ConnectionResetError`` subtype so the engine's bounded-retry send
    path handles it exactly like a real mid-chunk reset."""


class ChaosController:
    """Evaluates the parsed clauses against this rank's event stream."""

    def __init__(self, clauses: List[Clause], rank: Optional[int], seed: int):
        self.rank = rank
        self._clauses = [c for c in clauses if c.matches_rank(rank)]
        self._rng = random.Random(
            seed * 1000003 + (rank if rank is not None else -1)
        )
        self._lock = threading.Lock()
        self._colls = 0
        self._sends = 0
        self._recvs = 0
        self._fetches = 0
        #: the last step the training loop announced (note_step) — the
        #: arming clock for ``delay:after_step=N`` mid-run onsets; None
        #: until the first announcement, so un-announced processes never
        #: arm a gated clause by accident
        self._step: Optional[int] = None
        self._fanout_dropped: dict = {}
        #: clause-index -> count of events MATCHING that clause's filters
        #: (``delay:every=K`` strides over matching events; striding the
        #: global counter would make the outcome depend on unrelated
        #: traffic interleaving — not reproducible across topologies)
        self._matched: dict = {}

    # -- death ------------------------------------------------------------
    def _die(self, clause: Clause, why: str) -> None:
        mode = clause.get("mode", "exit")
        _log.warning("chaos: injecting death (%s, mode=%s)", why, mode)
        timeline.event("chaos", "die", rank=self.rank, why=why, mode=mode)
        if mode == "exit":
            # os._exit skips atexit — flush the flight recorder first so
            # the injected death is correlatable in the merged timeline
            timeline.maybe_dump()
        if mode == "raise":
            raise InjectedDeath(why)
        os._exit(DIE_EXIT_CODE)

    def _slice_matches(self, clause: Clause) -> bool:
        """Does THIS controller's process/rank live in the clause's
        target slice?  Slice identity comes from ``MEGASCALE_SLICE_ID``
        (one process per worker: the launcher's emulation contract, or
        the real pod host env) and falls back to ``rank // rps`` for
        in-process multi-rank clusters that share one environment."""
        want = clause.get("slice")
        if want is None:
            return False
        sid = (os.environ.get(envs.MEGASCALE_SLICE_ID, "") or "").strip()
        if sid:
            return int(sid) == want
        rps = clause.get("rps")
        if rps and self.rank is not None:
            return self.rank // rps == want
        return False

    def on_step(self, step: int) -> None:
        """Training loop announced step ``step`` (``die[_slice]:step=N``,
        ``preempt:all[,step=N]``, and the ``delay:after_step=N`` arming
        clock)."""
        self._step = step
        for c in self._clauses:
            if c.kind == "die" and c.get("step") == step:
                self._die(c, f"step={step}")
            elif (c.kind == "die_slice" and c.get("step") == step
                    and self._slice_matches(c)):
                self._die(c, f"slice={c.get('slice')} step={step}")
            elif c.kind == "preempt" and c.get("step") in (None, step):
                # whole-job preemption: every rank's controller matches
                # (no rank scope by grammar), so all processes die at the
                # same announced boundary — no survivors by construction
                self._die(c, f"preempt step={step}")

    def on_collective(self, tag: str) -> None:
        """Engine is starting a collective (``die[_slice]:coll=N``,
        1-based)."""
        with self._lock:
            self._colls += 1
            n = self._colls
        for c in self._clauses:
            if c.kind == "die" and c.get("coll") == n:
                self._die(c, f"coll={n} ({tag!r})")
            elif (c.kind == "die_slice" and c.get("coll") == n
                    and self._slice_matches(c)):
                self._die(c, f"slice={c.get('slice')} coll={n} ({tag!r})")

    # -- data-path perturbation -------------------------------------------
    def on_send(self, to_rank: int, name: str, payload, channel=None,
                peer=None) -> None:
        """Engine send hook: may straggle (``delay``) or tear the wire
        (``reset``).  ``channel``/``peer`` let the reset clause transmit a
        real truncated frame when the backend supports it."""
        with self._lock:
            self._sends += 1
            n = self._sends
        for ci, c in enumerate(self._clauses):
            if c.kind == "delay" and c.get("on", "send") == "send":
                self._maybe_delay(ci, c, to_rank)
            elif c.kind == "reset" and c.get("send") == n:
                if c.get("peer") is not None and c.get("peer") != to_rank:
                    continue
                self._reset(name, payload, channel, peer)

    def on_ping(self, to_rank: int) -> None:
        """Latency-probe hook (``delay:on=ping``): the adaptation layer's
        ping RTT measurement (``monitor/adapt.get_peer_latencies``) must
        see an injected slow link, or the MST re-carve it drives would
        route straight back onto the degraded edge the data path is
        paying for."""
        for ci, c in enumerate(self._clauses):
            if c.kind == "delay" and c.get("on") == "ping":
                self._maybe_delay(ci, c, to_rank)

    def on_serve_request(self, rid: str) -> bool:
        """Serving request-path hook (kf-serve worker handler).  Applies
        ``delay:on=serve`` stragglers, then ``drop_request``: True = the
        frame is lost (the worker must ignore it; the router's deadline
        ladder re-admits the request, docs/serving.md).  Deterministic:
        counted in MATCHING requests, like every other clause."""
        dropped = False
        for ci, c in enumerate(self._clauses):
            if c.kind == "delay" and c.get("on") == "serve":
                self._maybe_delay(ci, c, -1)
            elif c.kind == "drop_request":
                with self._lock:
                    n = self._matched[ci] = self._matched.get(ci, 0) + 1
                if n % max(1, c.get("every", 1)) != 0:
                    continue
                budget = c.get("count")
                if budget is not None:
                    with self._lock:
                        used = self._fanout_dropped.get(("req", ci), 0)
                        if used >= budget:
                            continue
                        self._fanout_dropped[("req", ci)] = used + 1
                _log.warning("chaos: dropping serve request %s", rid)
                timeline.event("chaos", "drop_request", rank=self.rank,
                               rid=rid)
                dropped = True
        return dropped

    def on_recv(self, from_rank: int, name: str) -> None:
        """Engine receive hook (``delay:on=recv`` stragglers)."""
        with self._lock:
            self._recvs += 1
        for ci, c in enumerate(self._clauses):
            if c.kind == "delay" and c.get("on") == "recv":
                self._maybe_delay(ci, c, from_rank)

    def _maybe_delay(self, ci: int, c: Clause, other_rank: int) -> None:
        if c.get("peer") is not None and c.get("peer") != other_rank:
            return
        after = c.get("after_step")
        if after is not None and (self._step is None or self._step < after):
            # gated BEFORE the match count: an every=K stride over an
            # after_step clause strides armed-phase events only
            return
        with self._lock:
            n = self._matched[ci] = self._matched.get(ci, 0) + 1
        if n % max(1, c.get("every", 1)) != 0:
            return
        ms = c.get("ms", 0) + (
            self._rng.uniform(0, c.get("jitter", 0)) if c.get("jitter") else 0
        )
        if ms > 0:
            timeline.event("chaos", "delay", rank=self.rank, ms=ms,
                           peer=other_rank)
            time.sleep(ms / 1000.0)

    def _reset(self, name: str, payload, channel, peer) -> None:
        nbytes = (
            len(payload) if isinstance(payload, bytes)
            else memoryview(payload).nbytes
        )
        sent = nbytes // 2
        partial = getattr(channel, "chaos_partial_send", None)
        if partial is not None and peer is not None:
            # real wire damage: header promises nbytes, half arrive, the
            # socket dies — the receiver's stream loop sees peer-closed-
            # mid-message, exactly what a worker dying mid-chunk produces
            try:
                partial(peer, name, payload, sent)
            except OSError:
                pass  # the tear itself failing is still a tear
        _log.warning(
            "chaos: reset mid-chunk on %r (%d/%d bytes sent)", name, sent, nbytes
        )
        timeline.event("chaos", "reset", rank=self.rank, coll=name,
                       sent=sent, nbytes=nbytes)
        raise InjectedReset(f"injected reset mid-chunk on {name!r}")

    # -- control-plane faults ---------------------------------------------
    def drop_fanout(self, host: str) -> bool:
        """True = the detector's fan-out POST to ``host`` is lost."""
        for i, c in enumerate(self._clauses):
            if c.kind != "drop_fanout":
                continue
            if c.get("host") is not None and c.get("host") != host:
                continue
            budget = c.get("count")
            if budget is not None:
                with self._lock:
                    used = self._fanout_dropped.get(i, 0)
                    if used >= budget:
                        continue
                    self._fanout_dropped[i] = used + 1
            _log.warning("chaos: dropping detector fan-out to %s", host)
            timeline.event("chaos", "drop_fanout", rank=self.rank, host=host)
            return True
        return False

    def config_unavailable(self) -> bool:
        """True = this config-server fetch falls inside a dark window
        (deterministic: counted in fetch attempts, not wall time)."""
        with self._lock:
            self._fetches += 1
            n = self._fetches
        for c in self._clauses:
            if c.kind == "config_down":
                after = c.get("after", 0)
                if after < n <= after + c.get("count", 1):
                    timeline.event("chaos", "config_down", rank=self.rank,
                                   fetch=n)
                    return True
        return False


# -- controller registry ----------------------------------------------------
_cache_lock = threading.Lock()
_cache: dict = {}


def controller_for(rank: Optional[int]) -> Optional[ChaosController]:
    """The process's controller for ``rank`` — ``None`` (the fast no-op
    path) unless ``KF_CHAOS_SPEC`` is set.  Cached per (spec, seed, rank)
    so every subsystem of one rank shares one set of trigger counters."""
    spec = os.environ.get(SPEC_ENV)
    if not spec:
        return None
    seed = int(os.environ.get(SEED_ENV, "0") or 0)
    key = (spec, seed, rank)
    with _cache_lock:
        ctl = _cache.get(key)
        if ctl is None:
            ctl = _cache[key] = ChaosController(parse_spec(spec), rank, seed)
        return ctl


def note_step(rank: Optional[int], step: int) -> None:
    """Training-loop step announcement (drives ``die:step=N``); free when
    chaos is disabled.  Also stamps the flight recorder's step counter —
    every instrumented training loop already calls this at each step
    boundary, so timeline events get step attribution without a second
    per-step hook."""
    timeline.set_step(step)
    ctl = controller_for(rank)
    if ctl is not None:
        ctl.on_step(step)


def reset() -> None:
    """Drop all cached controllers (their trigger counters die with
    them).  For tests that reuse one spec across scenarios, and for a
    long-lived process that re-arms an experiment."""
    with _cache_lock:
        _cache.clear()
