"""Core runtime singleton — rank/size/resize surface.

Parity with reference ``srcs/python/kungfu/python/__init__.py``: a default
peer created from the env bootstrap contract, exposing
``current_rank/cluster_size/local_rank/local_size``, ``uid``, ``detached``,
``run_barrier``, ``propose_new_size`` and ``resize``.  Unlike the reference
(which ctypes-inits at import), initialisation here is lazy or explicit via
:func:`init` — import side effects and JAX runtime startup don't mix.
"""

from __future__ import annotations

import threading
from typing import Optional

_default_peer = None
_lock = threading.RLock()


def init(config=None):
    """Create (or return) the process-wide default Peer."""
    global _default_peer
    with _lock:
        if _default_peer is None:
            from kungfu_tpu.peer import Peer

            _default_peer = Peer(config=config)
            _default_peer.start()
        return _default_peer


def finalize():
    global _default_peer
    with _lock:
        if _default_peer is not None:
            _default_peer.close()
            _default_peer = None


def _peer():
    return init()


def uid() -> int:
    """(cluster_version << 32) | rank — like reference ``python/__init__.py`` uid."""
    p = _peer()
    return (p.cluster_version << 32) | p.rank()


def current_rank() -> int:
    return _peer().rank()


def cluster_size() -> int:
    return _peer().size()


def current_local_rank() -> int:
    return _peer().local_rank()


def current_local_size() -> int:
    return _peer().local_size()


def detached() -> bool:
    return _peer().detached


def run_barrier() -> None:
    _peer().barrier()


def propose_new_size(new_size: int) -> None:
    _peer().propose_new_size(new_size)


def resize(n: Optional[int] = None) -> bool:
    """Resize the cluster; returns True if membership changed.
    With ``n=None``, pull the target size from the config server
    (reference ``python/__init__.py`` resize/resize_from_url)."""
    p = _peer()
    if n is None:
        return p.resize_cluster_from_url()
    return p.resize_cluster(n)


def current_communicator():
    """The active :class:`~kungfu_tpu.comm.Communicator` (mesh epoch)."""
    return _peer().communicator()
