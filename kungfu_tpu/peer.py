"""Worker-side peer runtime: membership + mesh epochs + elasticity.

Parity with reference ``srcs/go/kungfu/peer/peer.go``: a ``Peer`` is created
from the env bootstrap contract, owns the host-side message endpoint and the
current :class:`~kungfu_tpu.comm.device.Communicator` (the analog of the
reference's per-membership ``Session``), and implements the membership
change protocol (consensus on the proposed cluster → notify runners →
bump version → rebuild communicator, or mark self detached).

Process model on TPU: one peer process per host, driving all local chips
(the launcher sets ``KF_COORDINATOR``/``KF_NUM_PROCESSES``/``KF_PROCESS_ID``
and we bring up ``jax.distributed``); or one process per simulated device in
CPU-backend test clusters; or a single process in single-controller mode.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Optional

from kungfu_tpu.comm.device import Communicator
from kungfu_tpu.comm.host import ConnType, HostChannel
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.utils import envs
from kungfu_tpu.utils.log import get_logger, log_event
from kungfu_tpu.utils.stall import stall_detector
from kungfu_tpu.utils.trace import trace_scope

_log = get_logger("peer")


class Peer:
    def __init__(self, config: Optional[envs.Config] = None):
        self.config = config or envs.parse_config_from_env()
        self.cluster: Cluster = self.config.cluster
        self.cluster_version: int = self.config.init_version
        self.detached: bool = False
        self._channel: Optional[HostChannel] = None
        self._comm: Optional[Communicator] = None
        self._comm_version = -1
        self._engine = None
        self._engine_version = -1
        self._lock = threading.RLock()
        self._started = False
        self._jax_initialized = False
        from kungfu_tpu.store.store import VersionedStore

        #: this peer's versioned model store (served to gossip peers)
        self.store = VersionedStore()
        self.net_monitor = None
        self._metrics_server = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            platform = os.environ.get("KF_JAX_PLATFORM")
            if platform:
                import jax

                try:
                    jax.config.update("jax_platforms", platform)
                except Exception as e:  # backend may already be initialized
                    _log.warning("cannot set jax platform %s: %s", platform, e)
            monitor = None
            if envs.parse_bool_env(envs.ENABLE_MONITORING):
                from kungfu_tpu.monitor.metrics import (
                    METRICS_PORT_OFFSET,
                    MetricsServer,
                    NetMonitor,
                    monitoring_period_from_env,
                )

                monitor = NetMonitor(monitoring_period_from_env()).start()
                self.net_monitor = monitor
                try:
                    self._metrics_server = MetricsServer(
                        monitor, self.config.self_id.port + METRICS_PORT_OFFSET
                    ).start()
                except OSError as e:
                    _log.warning("metrics server not started: %s", e)
            if not self.config.single_process:
                self._channel = HostChannel(
                    self.config.self_id, token=self.cluster_version, monitor=monitor
                )
                from kungfu_tpu.store import install_p2p_handler

                install_p2p_handler(self._channel, self.store)
            if self.config.coordinator and self.config.num_processes > 1:
                self._init_jax_distributed()
            from kungfu_tpu.utils.affinity import bind_local_rank

            bind_local_rank(self.local_rank(), self.local_size())
            log_event("peer-started")

    def _init_jax_distributed(self) -> None:
        """Bring up the jax.distributed world ONCE per process.

        Contract on membership change (the reference's ``ResetNcclHelper``
        analog, defined here because jax.distributed cannot re-initialize
        in-process with a different world): the multi-host device world is
        fixed for a process's lifetime.  Elastic resize changes the
        *worker-process* membership — the watch runner kills/spawns
        processes, and each NEW process boots with fresh
        ``KF_COORDINATOR``/``KF_NUM_PROCESSES`` envs.  A surviving process
        keeps its original jax.distributed world and only rebuilds its
        Communicator (mesh epoch); if it left the worker list it detaches
        and exits.  ``_propose`` warns when a resize would need a different
        device world than this process was booted with."""
        import jax

        with stall_detector("jax.distributed.initialize"):
            jax.distributed.initialize(
                coordinator_address=self.config.coordinator,
                num_processes=self.config.num_processes,
                process_id=self.config.process_id,
            )
        self._jax_initialized = True
        # the device world is sized by PROCESS count (one jax process per
        # worker), not host count — a same-host-count resize still strands
        # surviving processes on a stale world
        self._jax_world_procs = self.config.num_processes

    def close(self) -> None:
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
            if self._metrics_server is not None:
                self._metrics_server.stop()
                self._metrics_server = None
            if self.net_monitor is not None:
                self.net_monitor.stop()
                self.net_monitor = None
            if self._engine is not None:
                self._engine.close()
            self._engine = None
            self._engine_version = -1
            self._comm = None
            self._comm_version = -1
            self._started = False

    # -- identity --------------------------------------------------------
    def rank(self) -> int:
        if self.detached:
            return -1
        r = self.cluster.workers.rank(self.config.self_id)
        if r is None:
            raise RuntimeError(
                f"{self.config.self_id} not in worker list {self.cluster.workers}"
            )
        return r

    def size(self) -> int:
        return self.cluster.size()

    def local_rank(self) -> int:
        r = self.cluster.workers.local_rank(self.config.self_id)
        return 0 if r is None else r

    def local_size(self) -> int:
        return self.cluster.workers.local_size(self.config.self_id)

    @property
    def channel(self) -> Optional[HostChannel]:
        return self._channel

    # -- communicator (mesh epoch) ---------------------------------------
    def communicator(self) -> Communicator:
        """The communicator for the current cluster version; rebuilt lazily
        after membership changes (analog of ``Peer.CurrentSession`` +
        ``updateTo``, peer.go:138-166)."""
        with self._lock:
            if self._comm is None or self._comm_version != self.cluster_version:
                self._comm = Communicator(
                    cluster=self.cluster, version=self.cluster_version
                )
                self._comm_version = self.cluster_version
                _log.info("new %r", self._comm)
            return self._comm

    def engine(self):
        """Graph-collective engine over the host channel for the current
        membership — the multi-process data path when no shared XLA mesh
        exists (CPU test clusters, between-mesh-epoch phases).  None in
        single-process mode."""
        with self._lock:
            if self._channel is None:
                return None
            if self._engine is None or self._engine_version != self.cluster_version:
                from kungfu_tpu.comm.engine import CollectiveEngine

                if self._engine is not None:
                    self._engine.close()
                self._engine = CollectiveEngine(
                    self._channel, self.cluster.workers, self.config.strategy
                )
                self._engine_version = self.cluster_version
            return self._engine

    # -- sync ------------------------------------------------------------
    def barrier(self) -> None:
        """Host-level barrier across worker processes."""
        if self.size() <= 1 or self._channel is None:
            return
        with trace_scope("peer.barrier"), stall_detector("barrier"):
            self._channel.barrier(
                self.cluster.workers, name=f"barrier.v{self.cluster_version}"
            )

    def consensus_bytes(self, data: bytes, name: str = "consensus") -> bool:
        if self.size() <= 1 or self._channel is None:
            return True
        return self._channel.consensus_bytes(
            data, self.cluster.workers, name=f"{name}.v{self.cluster_version}"
        )

    # -- elasticity (full protocol in kungfu_tpu.elastic) -----------------
    def propose_new_size(self, new_size: int) -> None:
        """Rank 0 PUTs the resized cluster to the config server
        (reference ``peer/legacy.go:18-39``)."""
        if not self.config.config_server:
            raise RuntimeError("propose_new_size requires KF_CONFIG_SERVER")
        if self.rank() != 0:
            return
        new_cluster = self.cluster.resize(new_size)
        req = urllib.request.Request(
            self.config.config_server,
            data=new_cluster.to_json().encode(),
            method="PUT",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()

    def resize_cluster_from_url(self) -> bool:
        """Fetch the target cluster from the config server, reach consensus,
        and apply (reference ``peer.go:236-263``).  Returns True if
        membership changed."""
        if not self.config.config_server:
            raise RuntimeError("resize requires KF_CONFIG_SERVER")
        from kungfu_tpu.elastic.resize import fetch_cluster_with_consensus

        new_cluster, version = fetch_cluster_with_consensus(self)
        return self._propose(new_cluster, version)

    def resize_cluster(self, n: int) -> bool:
        """Direct resize (config-server-backed when available)."""
        if self.config.config_server:
            self.propose_new_size(n)
            return self.resize_cluster_from_url()
        new_cluster = self.cluster.resize(n)
        return self._propose(new_cluster, self.cluster_version + 1)

    def _propose(self, new_cluster: Cluster, version: int) -> bool:
        """Apply an agreed membership change (reference ``peer.go:177-225``):
        notify runners, bump version, detach if not in the new worker list."""
        with self._lock:
            if new_cluster.workers == self.cluster.workers:
                return False
            with trace_scope("peer.propose"), stall_detector("propose"):
                self._notify_runners(new_cluster, version)
                self.cluster = new_cluster
                self.cluster_version = version
                if self._channel is not None:
                    self._channel.set_token(version)
                    # pooled sockets to removed peers must not leak
                    self._channel.reset_connections()
                self.detached = (
                    new_cluster.workers.rank(self.config.self_id) is None
                )
                self._comm = None  # next communicator() call builds the new epoch
                if self._jax_initialized and not self.detached:
                    new_procs = len(new_cluster.workers)
                    if new_procs != getattr(self, "_jax_world_procs", new_procs):
                        # see _init_jax_distributed: the device world is
                        # per-process-lifetime; collectives in this process
                        # keep spanning the ORIGINAL world's devices
                        _log.warning(
                            "resize to %d worker processes but this "
                            "process's jax.distributed world has %d — "
                            "surviving processes keep their original device "
                            "world; the new world takes effect in "
                            "relaunched workers only",
                            new_procs, self._jax_world_procs,
                        )
            log_event(f"cluster-resized-v{version}-n{new_cluster.size()}")
            return True

    def _notify_runners(self, new_cluster: Cluster, version: int) -> None:
        """Send the new Stage to every runner so they can spawn/kill local
        workers (reference ``peer.go:195-209`` → ``runner/handler.go``)."""
        if self._channel is None or self.rank() != 0:
            return
        stage = json.dumps(
            {"version": version, "cluster": json.loads(new_cluster.to_json())}
        ).encode()
        for runner in new_cluster.runners:
            try:
                self._channel.wait(runner, timeout=10)
                self._channel.send(runner, "update", stage, ConnType.CONTROL)
            except (TimeoutError, ConnectionError) as e:
                _log.warning("cannot notify runner %s: %s", runner, e)

    # -- monitoring / adaptation (reference peer.hpp GetPeerLatencies /
    # CheckInterference / GetEgressRates / SetTree) ----------------------
    def get_peer_latencies(self, samples: int = 1):
        from kungfu_tpu.monitor.adapt import get_peer_latencies

        return get_peer_latencies(self, samples)

    def get_egress_rates(self):
        if self.net_monitor is None:
            return [0.0] * self.size()
        return self.net_monitor.egress_rates(
            [str(w) for w in self.cluster.workers]
        )

    def check_interference(self) -> bool:
        from kungfu_tpu.monitor.adapt import check_interference, majority_vote_interference

        engine = self.engine()
        suspected = bool(engine and check_interference(engine))
        return majority_vote_interference(self, suspected)

    def set_tree(self, forest) -> None:
        """Install an explicit broadcast tree after cluster-wide agreement
        (reference SetTree: consensus on the tree digest, barrier, swap)."""
        from kungfu_tpu.monitor.adapt import set_tree
        from kungfu_tpu.plan.graph import Graph

        digest = Graph.from_forest_array(forest).digest_bytes()
        if not self.consensus_bytes(digest, name="set-tree"):
            raise RuntimeError("peers disagree on the proposed tree")
        self.barrier()
        engine = self.engine()
        if engine is not None:
            set_tree(engine, forest)

    # -- p2p blob store (gossip) -----------------------------------------
    def save(self, name: str, blob: bytes, version: Optional[str] = None) -> None:
        self.store.save(name, blob, version)

    def request(self, target_rank: int, name: str, version: Optional[str] = None) -> Optional[bytes]:
        """Pull a named blob from a peer's versioned store
        (reference ``p2p.go:15-41``, ``handler/p2p.go:102-120``)."""
        from kungfu_tpu.store import remote_request

        target = self.cluster.workers[target_rank]
        return remote_request(self, target, name, version)
