"""Worker-side peer runtime: membership + mesh epochs + elasticity.

Parity with reference ``srcs/go/kungfu/peer/peer.go``: a ``Peer`` is created
from the env bootstrap contract, owns the host-side message endpoint and the
current :class:`~kungfu_tpu.comm.device.Communicator` (the analog of the
reference's per-membership ``Session``), and implements the membership
change protocol (consensus on the proposed cluster → notify runners →
bump version → rebuild communicator, or mark self detached).

Process model on TPU: one peer process per host, driving all local chips
(the launcher sets ``KF_COORDINATOR``/``KF_NUM_PROCESSES``/``KF_PROCESS_ID``
and we bring up ``jax.distributed``); or one process per simulated device in
CPU-backend test clusters; or a single process in single-controller mode.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Optional

from kungfu_tpu.comm.device import Communicator
from kungfu_tpu.comm.host import ConnType, HostChannel
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.utils import envs
from kungfu_tpu.utils.log import get_logger, log_event
from kungfu_tpu.utils.stall import stall_detector
from kungfu_tpu.utils.trace import trace_scope

_log = get_logger("peer")


class Peer:
    def __init__(self, config: Optional[envs.Config] = None):
        self.config = config or envs.parse_config_from_env()
        self.cluster: Cluster = self.config.cluster
        self.cluster_version: int = self.config.init_version
        self.detached: bool = False
        #: in the provisioned device world but not in the active worker
        #: list — alive, holding its jax.distributed slot, waiting to be
        #: re-included by a future resize (no reference analog: the
        #: reference kills/spawns processes, we re-carve the mesh)
        self.standby: bool = (
            self.config.world_peers is not None
            and self.cluster.workers.rank(self.config.self_id) is None
        )
        self._channel: Optional[HostChannel] = None
        self._comm: Optional[Communicator] = None
        self._comm_version = -1
        #: bootstrap slice topology (None = single slice, the byte-
        #: identical legacy path); the CURRENT topology is derived per
        #: membership via slice_topology() — whole-slice elasticity
        #: keeps ranks_per_slice invariant
        from kungfu_tpu.elastic.slices import bootstrap_topology

        try:
            self._slice_boot = bootstrap_topology(
                len(self.config.cluster.workers))
        except ValueError as e:
            # a pod host's inherited MEGASCALE_NUM_SLICES with a worker
            # world that does not tile it (e.g. -np 3 on a 2-slice pod's
            # env): before the multislice wiring this trained flat —
            # keep doing that, loudly, instead of crashing kf.init()
            _log.warning("incoherent multislice contract (%s) — "
                         "running single-slice (flat)", e)
            self._slice_boot = None
        #: carried across mesh epochs — the resize paths retire the old
        #: communicator object, not the user's strategy decision.
        #: Multislice default is two_stage: the hierarchical mesh's
        #: outer (DCN) stage then compiles as an explicit reduce-scatter
        #: + all-gather over slice representatives after the inner ICI
        #: psum (ops/schedules.all_reduce_scheduled), instead of one
        #: flat collective XLA must route across the slow axis blind.
        self._comm_strategy = self.config.device_strategy or (
            "two_stage" if self._slice_boot is not None else "psum")
        self._engine = None
        self._engine_version = -1
        self._lock = threading.RLock()
        self._started = False
        self._jax_initialized = False
        from kungfu_tpu.store.store import VersionedStore

        #: this peer's versioned model store (served to gossip peers)
        self.store = VersionedStore()
        #: control-plane blobs (reserved ``kf.`` names): own eviction
        #: window so gossip's per-step model versions cannot push out an
        #: epoch's strategy record before a joiner pulls it
        self._ctrl_store = VersionedStore(window=8)
        self.net_monitor = None
        self._metrics_server = None
        #: live-plane snapshot pusher (KF_CONFIG_ENABLE_CLUSTER_MONITOR)
        self._reporter = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            platform = os.environ.get("KF_JAX_PLATFORM")
            if platform:
                import jax

                try:
                    jax.config.update("jax_platforms", platform)
                except Exception as e:  # backend may already be initialized
                    _log.warning("cannot set jax platform %s: %s", platform, e)
            monitor = None
            if envs.parse_bool_env(envs.ENABLE_MONITORING):
                from kungfu_tpu.monitor.metrics import (
                    METRICS_PORT_OFFSET,
                    MetricsServer,
                    NetMonitor,
                    monitoring_period_from_env,
                )

                monitor = NetMonitor(monitoring_period_from_env()).start()
                self.net_monitor = monitor
                try:
                    self._metrics_server = MetricsServer(
                        monitor, self.config.self_id.port + METRICS_PORT_OFFSET
                    ).start()
                    _log.info("/metrics on port %d", self._metrics_server.port)
                except OSError as e:
                    _log.warning("metrics server not started: %s", e)
            if not self.config.single_process:
                from kungfu_tpu.comm.host import bind_own_host_channel

                self._channel = bind_own_host_channel(
                    self.config.self_id, token=self.cluster_version,
                    monitor=monitor
                )
                from kungfu_tpu.store import install_p2p_handler

                self._p2p_stop = install_p2p_handler(
                    self._channel, self.store, self._ctrl_store,
                    n_peers=self.size())
            if self.config.coordinator and self.config.num_processes > 1:
                self._init_jax_distributed()
            from kungfu_tpu.utils.affinity import bind_local_rank

            world = self.config.world_peers
            if world is not None:
                # world mode: pin by the STABLE world-slot position so the
                # binding survives resizes (and standby peers — which have
                # no active local rank — still get a valid share)
                lr = world.local_rank(self.config.self_id)
                bind_local_rank(
                    0 if lr is None else lr,
                    max(world.local_size(self.config.self_id), 1),
                )
            else:
                bind_local_rank(self.local_rank(), self.local_size())
            # every fresh process is about to cold-compile its step: tell
            # the failure detector (no-op without KF_MONITOR_ADDR).  This
            # also covers a joiner that reuses a rank id whose previous
            # incarnation left non-fresh detector state.
            from kungfu_tpu.monitor.signals import monitor_compile_grace

            monitor_compile_grace(self.rank())
            # flight-recorder identity: events (and the dump filename)
            # default to this worker's rank; in-process multi-peer test
            # clusters pass rank= explicitly at rank-owning call sites
            from kungfu_tpu.monitor import timeline

            timeline.set_rank(None if self.detached or self.standby
                              else self.rank())
            # live cluster plane: push snapshots to the aggregator
            # co-hosted with the config server (kfrun -monitor).  The
            # reporter's identity is the STABLE bootstrap rank, matching
            # the flight recorder's per-process tracks — a shrink must
            # not make a promoted survivor alias a dead rank's row.
            if (envs.parse_bool_env(envs.ENABLE_CLUSTER_MONITOR)
                    and self.config.config_server):
                rank = self.chaos_rank()
                if rank is None and not (self.detached or self.standby):
                    rank = self.rank()
                if rank is not None:
                    from kungfu_tpu.monitor.aggregator import RankReporter
                    from kungfu_tpu.monitor.metrics import \
                        publish_device_memory
                    from kungfu_tpu.utils.jaxcompat import \
                        install_compile_metrics

                    # XLA compiles become registry series the snapshot
                    # carries (kf_jit_compiles_total — the sentinel's
                    # recompile-steady feedstock); no-op on jax
                    # versions without the monitoring hook
                    install_compile_metrics()
                    # slice identity rides the same stable bootstrap
                    # frame as the rank: kftop's per-slice grouping
                    # must not re-home a row when a shrink renumbers
                    # the live topology
                    slice_id = (self._slice_boot.slice_of(rank)
                                if self._slice_boot is not None else None)
                    self._reporter = RankReporter(
                        rank, self.config.config_server,
                        strategy_fn=self._active_strategy,
                        net_totals_fn=(self._net_totals
                                       if monitor is not None else None),
                        slice_id=slice_id,
                        # HBM gauges refresh once per push (None-safe:
                        # CPU backends simply publish nothing)
                        pre_snapshot_fn=publish_device_memory,
                    ).start()
            log_event("peer-started")

    def _active_strategy(self) -> str:
        """The active strategy/arm set, stamped on live snapshots (the
        kftop ``strategy`` column): the host-engine strategy in force
        (set_strategy / adaptation swaps included; an installed explicit
        tree renders as ``tree``), plus the device communicator's
        per-bucket schedule table when the kf-adapt bandit has installed
        one — e.g. ``STAR dev[small=psum,large=ring]``."""
        engine = self._engine
        s = engine.strategy if engine is not None else self.config.strategy
        name = "tree" if (engine is not None and s is None) \
            else getattr(s, "name", str(s))
        comm = self._comm
        if comm is not None:
            buckets = comm.bucket_summary()
            if buckets:
                name = f"{name} dev[{buckets}]"
        return name

    def _net_totals(self) -> dict:
        mon = self.net_monitor
        if mon is None:
            return {}
        totals = mon.totals()
        return {
            "egress_bytes": sum(totals["egress"].values()),
            "ingress_bytes": sum(totals["ingress"].values()),
        }

    def _init_jax_distributed(self) -> None:
        """Bring up the jax.distributed world ONCE per process.

        The device world is fixed for a process's lifetime (jax.distributed
        cannot re-initialize in-process).  Two operating modes:

        * **Provisioned world** (``KF_WORLD_PEERS`` set): the world spans
          ALL provisioned slots — every slot's process boots here at job
          start, whether or not it is in the initial worker list.  Elastic
          resize then re-carves the Communicator mesh over the *active*
          workers' devices (``_carve_active_devices``); inactive in-world
          peers go ``standby`` instead of detaching.  This is the live
          resize the reference promises (``peer/peer.go:236-276`` +
          ``gpu/scheduler.cpp:43-72``): survivors keep training on the
          device plane, no process relaunch.

        * **Fixed world** (no ``KF_WORLD_PEERS``): world == the initial
          worker set; a resize beyond it only takes effect in relaunched
          workers and ``_propose`` warns on the survivors."""
        import jax

        platform = os.environ.get("KF_JAX_PLATFORM") or ""
        if platform == "cpu":
            # CPU-backend multi-process clusters (the fake-cluster test
            # trick, SURVEY §4) need an explicit cross-process collectives
            # impl; TPU uses ICI/DCN natively
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception as e:  # older jaxlib without gloo
                _log.warning("cannot enable gloo cpu collectives: %s", e)
            ndev = os.environ.get(envs.NUM_DEVICES)
            if ndev:
                try:
                    jax.config.update("jax_num_cpu_devices", int(ndev))
                except Exception as e:
                    _log.warning("cannot set cpu device count: %s", e)
        with stall_detector("jax.distributed.initialize"):
            jax.distributed.initialize(
                coordinator_address=self.config.coordinator,
                num_processes=self.config.num_processes,
                process_id=self.config.process_id,
            )
            # force backend bring-up NOW: global device discovery exchanges
            # every process's local topology through the coordinator — a
            # standby peer that never touched jax would otherwise stall
            # every active peer's first jax.devices() call forever
            n = len(jax.devices())
        self._jax_initialized = True
        self._jax_world_procs = self.config.num_processes
        _log.info(
            "jax.distributed world up: %d processes, %d devices",
            self.config.num_processes, n,
        )

    def _carve_active_devices(self):
        """Devices of the ACTIVE workers, in worker-rank order — the mesh
        epoch is a sub-mesh of the provisioned world (grow/shrink =
        re-carving, not re-initializing).  Returns (devices, local_size),
        or (None, None) to fall back to the full-world mesh."""
        world = self.config.world_peers
        if world is None:
            return None, None
        import jax

        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, []).append(d)
        devs, per = [], None
        for w in self.cluster.workers:
            wr = world.rank(w)
            if wr is None or wr not in by_proc:
                _log.warning(
                    "worker %s is outside the provisioned device world "
                    "(%d slots) — cannot carve a device mesh for this "
                    "membership; falling back to the full-world mesh", w,
                    len(world),
                )
                return None, None
            ds = by_proc[wr]
            if per is None:
                per = len(ds)
            elif len(ds) != per:
                _log.warning(
                    "uneven device counts per world slot (%d vs %d) — "
                    "falling back to the full-world mesh", len(ds), per,
                )
                return None, None
            devs.extend(ds)
        # the mesh's local axis must span a HOST (the local_*/cross_*
        # hierarchy contract, see Communicator._infer_local_size), not a
        # process: a host may hold several world slots
        hosts = [w.host for w in self.cluster.workers]
        counts = {}
        seen = set()
        contiguous = True
        for i, h in enumerate(hosts):
            counts[h] = counts.get(h, 0) + 1
            if i > 0 and h != hosts[i - 1] and h in seen:
                contiguous = False  # host's workers split into >1 run
            seen.add(h)
        sizes = set(counts.values())
        if len(sizes) == 1 and contiguous:
            local_size = sizes.pop() * (per or 1)
        else:
            _log.warning(
                "active workers are unevenly or non-contiguously placed "
                "across hosts %s: mesh degrades to flat 1x%d — local_* "
                "collectives will span ALL devices and cross_* collectives "
                "become no-ops", counts, len(devs),
            )
            local_size = len(devs)
        return devs, local_size

    def close(self) -> None:
        # flush the flight recorder before tearing channels down (the
        # atexit hook also fires, but a long-lived driver that closes and
        # re-opens peers would otherwise only dump its last incarnation)
        from kungfu_tpu.monitor import timeline

        timeline.maybe_dump()
        if self._reporter is not None:
            # final push BEFORE channels tear down: a clean shutdown
            # leaves fresh numbers on the aggregator, not a stale flag
            self._reporter.stop(final_push=True)
            self._reporter = None
        with self._lock:
            if self._channel is not None:
                self._notify_done()
                if getattr(self, "_p2p_stop", None) is not None:
                    self._p2p_stop()
                    self._p2p_stop = None
                self._channel.close()
                self._channel = None
            if self._metrics_server is not None:
                self._metrics_server.stop()
                self._metrics_server = None
            if self.net_monitor is not None:
                self.net_monitor.stop()
                self.net_monitor = None
            if self._engine is not None:
                self._engine.close()
            self._engine = None
            self._engine_version = -1
            self._retire_comm()  # keep the strategy across close/start
            self._comm_version = -1
            self._started = False

    # -- identity --------------------------------------------------------
    def rank(self) -> int:
        if self.detached or self.standby:
            return -1
        r = self.cluster.workers.rank(self.config.self_id)
        if r is None:
            raise RuntimeError(
                f"{self.config.self_id} not in worker list {self.cluster.workers}"
            )
        return r

    def size(self) -> int:
        return self.cluster.size()

    def local_rank(self) -> int:
        r = self.cluster.workers.local_rank(self.config.self_id)
        return 0 if r is None else r

    def local_size(self) -> int:
        return self.cluster.workers.local_size(self.config.self_id)

    @property
    def channel(self) -> Optional[HostChannel]:
        return self._channel

    # -- slice identity (multislice pods) ---------------------------------
    def slice_topology(self):
        """The CURRENT membership's :class:`~kungfu_tpu.elastic.slices.
        SliceTopology`, or ``None`` on a single-slice job.  Ranks-per-
        slice is the bootstrap invariant; the slice count follows the
        membership (slice-granular elasticity keeps it whole).  A
        membership that no longer tiles is the rank-granular tail — a
        job shrunk to its last slice keeps surviving RANK deaths
        (elastic/shrink.py falls back to the classic ladder there), and
        from then on slice semantics are over: ``None``."""
        if self._slice_boot is None:
            return None
        try:
            return self._slice_boot.for_size(self.size())
        except ValueError:
            return None

    def slice_id(self) -> Optional[int]:
        """This worker's slice in the CURRENT membership (``None`` on a
        single-slice job; raises for detached/standby peers, like
        :meth:`rank`)."""
        topo = self.slice_topology()
        return None if topo is None else topo.slice_of(self.rank())

    def chaos_rank(self) -> Optional[int]:
        """Stable fault-injection identity: this process's rank in its
        BOOTSTRAP worker list.  Elastic reshuffles change :meth:`rank`
        (a shrink promotes survivors), and a rank-scoped chaos
        clause must keep pointing at the same process for the whole
        experiment — the end-to-end repro of the alternative is a
        ``die`` clause re-firing on the promoted survivor of the very
        failure it injected."""
        return self.config.cluster.workers.rank(self.config.self_id)

    # -- communicator (mesh epoch) ---------------------------------------
    def _retire_comm(self) -> None:
        """Drop the current communicator ahead of a new mesh epoch,
        preserving the installed allreduce strategy (set_strategy /
        autotune) for the next epoch's build.  Callers hold the lock."""
        if self._comm is not None:
            self._comm_strategy = self._comm.strategy
        self._comm = None

    def _record_strategy(self, name: str) -> None:
        """``on_strategy_change`` hook: a ``set_strategy`` call lands on
        the Peer durably even if the communicator object it was made on
        is being retired by a concurrent resize."""
        self._comm_strategy = name

    _STRATEGY_BLOB = "kf.device-strategy"

    def _sync_device_strategy(self, version: int) -> None:
        """Cluster-consistent device schedule for a mesh epoch: rank 0's
        strategy IS the epoch's strategy — it publishes to its blob store
        keyed by the cluster version, everyone else adopts via a p2p pull
        (retried: rank 0 publishes when it builds its own communicator).

        This is mandatory, not cosmetic, on multi-controller meshes: a
        survivor compiling ring collectives while a joiner compiles psum
        is two DIFFERENT programs on one mesh — a deadlock, not a wrong
        value.  (The reference sidesteps this by rebuilding sessions from
        the static configured strategy on every membership change,
        i.e. runtime swaps do not survive resizes at all; here they
        survive whenever rank 0 survives.)  A joiner that becomes rank 0
        resets the epoch to its own default — consistency wins over
        persistence."""
        if self._channel is None or self.size() <= 1:
            return
        ver = str(version)
        if self.rank() == 0:
            # fixed-width payload: Store.save refuses same-name size
            # changes, and a close/start cycle may legitimately
            # re-publish a different (longer) strategy for this version
            self._ctrl_store.save(
                self._STRATEGY_BLOB,
                self._comm_strategy.ljust(32).encode(), version=ver
            )
            return
        deadline = time.monotonic() + 30.0
        attempt = 0
        while time.monotonic() < deadline:
            try:
                blob = self.request(0, self._STRATEGY_BLOB, version=ver,
                                    timeout=5.0)
            except (OSError, ConnectionError, TimeoutError):
                blob = None
            if blob:
                self._comm_strategy = blob.decode().strip()
                return
            from kungfu_tpu.utils.retry import sleep_backoff

            # every non-zero rank polls rank 0 at once after a resize;
            # jittered backoff keeps the pulls from re-synchronizing
            sleep_backoff(attempt, base=0.2, cap=1.0)
            attempt += 1
        _log.warning(
            "no device-strategy from rank 0 for v%d after 30s; keeping %r "
            "(mesh-wide schedule mismatch possible)",
            version, self._comm_strategy,
        )

    def communicator(self) -> Communicator:
        """The communicator for the current cluster version; rebuilt lazily
        after membership changes (analog of ``Peer.CurrentSession`` +
        ``updateTo``, peer.go:138-166)."""
        with self._lock:
            if self.standby:
                raise RuntimeError(
                    "standby peer is not in the active worker list; call "
                    "await_rejoin() before communicator()"
                )
            if self._comm is None or self._comm_version != self.cluster_version:
                devices = local_size = None
                if self._jax_initialized:
                    devices, local_size = self._carve_active_devices()
                if self._slice_boot is not None and devices is not None:
                    # multislice: the mesh epoch is hierarchical — outer
                    # axis = slice (DCN), inner = within-slice (ICI).
                    # slice_mesh_layout re-groups the carved devices by
                    # slice (the emulation contract groups by process)
                    # and validates the federation against the CURRENT
                    # topology: after a slice-shrink the surviving
                    # devices regroup into fewer slices — the DCN mesh
                    # re-carve (docs/multislice.md).  Without a booted
                    # jax.distributed world (devices=None: the host-
                    # plane emulation) this lone process's local devices
                    # cannot show the federation — the legacy local
                    # Communicator stands
                    from kungfu_tpu.platforms.tpu_pod import \
                        slice_mesh_layout

                    topo = self.slice_topology()
                    devices, local_size = slice_mesh_layout(
                        topo.num_slices, devices)
                # an installed schedule (set_strategy / autotune)
                # survives the mesh epoch swap — the resize rebuilds the
                # mesh, not the user's strategy decision — and the epoch
                # agrees on ONE schedule cluster-wide (rank 0's)
                self._retire_comm()
                self._sync_device_strategy(self.cluster_version)
                self._comm = Communicator(
                    cluster=self.cluster,
                    version=self.cluster_version,
                    devices=devices,
                    local_size=local_size,
                    strategy=self._comm_strategy,
                    on_strategy_change=self._record_strategy,
                )
                self._comm_version = self.cluster_version
                _log.info("new %r", self._comm)
            return self._comm

    def engine(self):
        """Graph-collective engine over the host channel for the current
        membership — the multi-process data path when no shared XLA mesh
        exists (CPU test clusters, between-mesh-epoch phases).  None in
        single-process mode."""
        with self._lock:
            if self._channel is None:
                return None
            if self._engine is None or self._engine_version != self.cluster_version:
                from kungfu_tpu.comm.engine import CollectiveEngine

                if self._engine is not None:
                    self._engine.close()
                self._engine = CollectiveEngine(
                    self._channel, self.cluster.workers, self.config.strategy,
                    chaos_rank=self.chaos_rank(),
                )
                self._engine_version = self.cluster_version
            return self._engine

    # -- sync ------------------------------------------------------------
    def barrier(self) -> None:
        """Host-level barrier across worker processes."""
        if self.size() <= 1 or self._channel is None:
            return
        with trace_scope("peer.barrier"), stall_detector("barrier"):
            self._channel.barrier(
                self.cluster.workers, name=f"barrier.v{self.cluster_version}"
            )

    def consensus_bytes(self, data: bytes, name: str = "consensus") -> bool:
        if self.size() <= 1 or self._channel is None:
            return True
        return self._channel.consensus_bytes(
            data, self.cluster.workers, name=f"{name}.v{self.cluster_version}"
        )

    # -- elasticity (full protocol in kungfu_tpu.elastic) -----------------
    def propose_new_size(self, new_size: int) -> None:
        """Rank 0 PUTs the resized cluster to the config server
        (reference ``peer/legacy.go:18-39``)."""
        if not self.config.config_server:
            raise RuntimeError("propose_new_size requires KF_CONFIG_SERVER")
        if self.rank() != 0:
            return
        from kungfu_tpu.elastic.resize import slice_aligned_size

        # multislice: planned elasticity moves whole slices (a fractional
        # slice has no within-slice mesh to join) — no-op on single-slice
        new_size = slice_aligned_size(self, new_size)
        world = self.config.world_peers
        if world is not None and new_size > len(world):
            # a phantom worker (valid PeerID, no process) would wedge every
            # later host-plane collective waiting for it to come up; clamp
            # rather than raise — schedules drive this from per-step hooks
            # and an over-ask must not kill the training run
            _log.warning(
                "proposed size %d exceeds the provisioned device world "
                "(%d slots) — clamping to the world capacity",
                new_size, len(world),
            )
            new_size = len(world)
        new_cluster = self.cluster.resize(new_size)
        req = urllib.request.Request(
            self.config.config_server,
            data=new_cluster.to_json().encode(),
            method="PUT",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()

    def resize_cluster_from_url(self) -> bool:
        """Fetch the target cluster from the config server, reach consensus,
        and apply (reference ``peer.go:236-263``).  Returns True if
        membership changed."""
        if not self.config.config_server:
            raise RuntimeError("resize requires KF_CONFIG_SERVER")
        from kungfu_tpu.elastic.resize import fetch_cluster_with_consensus

        new_cluster, version = fetch_cluster_with_consensus(self)
        return self._propose(new_cluster, version)

    def resize_cluster(self, n: int) -> bool:
        """Direct resize (config-server-backed when available)."""
        if self.config.config_server:
            self.propose_new_size(n)
            return self.resize_cluster_from_url()
        new_cluster = self.cluster.resize(n)
        return self._propose(new_cluster, self.cluster_version + 1)

    def _propose(self, new_cluster: Cluster, version: int) -> bool:
        """Apply an agreed membership change (reference ``peer.go:177-225``):
        notify runners, bump version, detach if not in the new worker list."""
        # kf-overlap fence: an async collective handle may never cross a
        # membership change (its tags and peer set belong to the old
        # epoch; the post-resize engine rebuild would strand its recvs).
        # Settling is deadline-bounded, so this cannot hang on a dead
        # peer — a doomed handle completes with its typed failure, which
        # still re-raises at that handle's own wait().  Outside the lock:
        # the draining collectives' completion path must not need it.
        eng = self._engine
        if eng is not None:
            eng.drain_async()
        with self._lock:
            if new_cluster.workers == self.cluster.workers:
                return False
            with trace_scope("peer.propose"), stall_detector("propose"):
                self._notify_runners(new_cluster, version)
                self.cluster = new_cluster
                self.cluster_version = version
                if self._channel is not None:
                    self._channel.set_token(version)
                    # pooled sockets to removed peers must not leak
                    self._channel.reset_connections()
                world = self.config.world_peers
                active = new_cluster.workers.rank(self.config.self_id) is not None
                in_world = (
                    world is not None
                    and world.rank(self.config.self_id) is not None
                )
                # in-world peers never detach: they go standby and can be
                # re-carved into a later mesh epoch without a relaunch
                self.detached = not active and not in_world
                self.standby = not active and in_world
                self._retire_comm()  # next communicator() builds the new epoch
                if self._jax_initialized and active and world is None:
                    new_procs = len(new_cluster.workers)
                    if new_procs != getattr(self, "_jax_world_procs", new_procs):
                        # fixed-world mode only (no KF_WORLD_PEERS): the
                        # device world is per-process-lifetime; collectives
                        # in this process keep spanning the ORIGINAL world.
                        # With a provisioned world this path is unreachable —
                        # communicator() re-carves the sub-mesh instead.
                        _log.warning(
                            "resize to %d worker processes but this "
                            "process's jax.distributed world has %d — "
                            "surviving processes keep their original device "
                            "world; the new world takes effect in "
                            "relaunched workers only (set KF_WORLD_PEERS "
                            "to provision a max world for live resize)",
                            new_procs, self._jax_world_procs,
                        )
            log_event(f"cluster-resized-v{version}-n{new_cluster.size()}")
        # control event for the live plane (best-effort, outside the
        # lock): rank 0 of the NEW membership announces the resize so
        # kftop's cluster-health line flips with the epoch
        if new_cluster.workers.rank(self.config.self_id) == 0:
            from kungfu_tpu.monitor.aggregator import post_control_if_enabled

            post_control_if_enabled(self, "resize", version=version,
                                    size=new_cluster.size())
        return True

    def _notify_done(self) -> None:
        """Tell every runner the job completed cleanly (rank 0, on close).
        Hosts the schedule shrank to zero workers have a runner idling for
        a possible re-grow — without this signal they could never exit
        (``watch_run``'s job_done condition)."""
        if self.config.parent is None or self.detached or self.standby:
            return
        if self.cluster.workers.rank(self.config.self_id) != 0:
            return  # rank() is None for non-members — also not rank 0
        for runner in self.cluster.runners:
            try:
                # best-effort: a runner whose host finished earlier is
                # already gone — don't ride the 500-retry connect loop
                self._channel.send(
                    runner, "done", b"", ConnType.CONTROL, retries=2
                )
            except (TimeoutError, ConnectionError, OSError) as e:
                _log.debug("cannot send done to runner %s: %s", runner, e)

    def _notify_runners(self, new_cluster: Cluster, version: int) -> None:
        """Send the new Stage to every runner so they can spawn/kill local
        workers (reference ``peer.go:195-209`` → ``runner/handler.go``).
        Skipped when no runner spawned us (mp-spawn / direct-driven test
        clusters have no runner daemon to notify).

        Rank 0 fans the stage out to EVERY runner; every OTHER worker
        also sends it to its own parent.  The parent copy closes a
        shutdown race: a worker the stage removes exits right after this
        call, and if rank 0's fan-out were the only copy, the runner
        could reap that exit first, read it as the job's natural end, and
        quit — orphaning the host for later re-grows.  The local send
        happens-before the local exit; duplicate versions are tolerated
        (``watch_run`` cross-checks and drops them)."""
        if self._channel is None or self.config.parent is None:
            return
        # rank in the OLD membership; standby/detached peers don't notify
        if self.cluster.workers.rank(self.config.self_id) is None:
            return
        stage = json.dumps(
            {"version": version, "cluster": json.loads(new_cluster.to_json())}
        ).encode()
        targets = (new_cluster.runners
                   if self.cluster.workers.rank(self.config.self_id) == 0
                   else [self.config.parent])
        wait_s = envs.parse_float_env(envs.WAIT_RUNNER_TIMEOUT, 10.0)
        for runner in targets:
            try:
                self._channel.wait(runner, timeout=wait_s)
                self._channel.send(runner, "update", stage, ConnType.CONTROL)
            except (TimeoutError, ConnectionError) as e:
                _log.warning("cannot notify runner %s: %s", runner, e)

    # -- standby / world (provisioned-world live elasticity) --------------
    def world_barrier(self, name: str = "world") -> None:
        """Host-plane barrier over ALL provisioned slots (active + standby).
        Used for job-wide phases (start/shutdown) that must include peers
        currently outside the worker list."""
        world = self.config.world_peers
        if world is None or len(world) <= 1 or self._channel is None:
            return
        with trace_scope("peer.world_barrier"), stall_detector("world_barrier"):
            self._channel.barrier(world, name=f"wbarrier.{name}")

    def observe_stage(self):
        """Fetch the config server's current (cluster, version) without
        applying it — standby peers poll this to decide when to rejoin or
        shut down."""
        if not self.config.config_server:
            raise RuntimeError("observe_stage requires KF_CONFIG_SERVER")
        from kungfu_tpu.elastic.resize import fetch_cluster

        return fetch_cluster(self.config.config_server)

    def await_rejoin(self, timeout: float = 300.0, poll_period: float = 0.2) -> bool:
        """Standby peer blocks until the config server publishes a stage
        that includes it, then adopts that stage (version fence + fresh
        mesh epoch).  Returns True on rejoin; False if a newer stage
        excludes us and ``timeout`` elapses.

        The active set reached consensus on the stage before publishing
        (``fetch_cluster_with_consensus``); a joining standby peer takes
        the versioned config server as the source of truth — its first
        collective with the new membership synchronizes it with the
        survivors (device-plane collectives block until every participant
        arrives, the moral of the reference's post-update ``sess.Barrier()``,
        ``peer.go:144-166``)."""
        from kungfu_tpu.utils.retry import sleep_backoff

        deadline = time.time() + timeout
        failures = 0
        while time.time() < deadline:
            try:
                cluster, version = self.observe_stage()
            except (OSError, ValueError, KeyError) as e:
                _log.debug("stage fetch failed: %s", e)
                # a DOWN config server + every standby peer polling it =
                # a reconnect storm at recovery time; back off instead
                sleep_backoff(failures, base=poll_period, cap=2.0)
                failures += 1
                continue
            failures = 0
            if version > self.cluster_version:
                if cluster.workers.rank(self.config.self_id) is not None:
                    with self._lock:
                        self.cluster = cluster
                        self.cluster_version = version
                        if self._channel is not None:
                            self._channel.set_token(version)
                            self._channel.reset_connections()
                        self.standby = False
                        self.detached = False
                        self._retire_comm()
                    log_event(f"rejoined-v{version}-n{cluster.size()}")
                    return True
                # newer stage still excludes us: track the version so a
                # subsequent rejoin fences on the right token
                with self._lock:
                    self.cluster = cluster
                    self.cluster_version = version
                    if self._channel is not None:
                        self._channel.set_token(version)
            time.sleep(poll_period)
        return False

    # -- in-flight fault tolerance (elastic.shrink) ------------------------
    def recover_from_failure(self, failure: Optional[BaseException] = None,
                             snapshot=None, zero_boundary=None,
                             stage_boundary=None):
        """Survivor-side in-flight recovery after a collective raised
        :class:`~kungfu_tpu.comm.faults.PeerFailureError`: confirm the
        dead set by ping, run the exclusion consensus, apply the shrunk
        membership through the propose path, and return ``(shrunk,
        replay)`` — see :func:`kungfu_tpu.elastic.shrink.
        recover_from_peer_failure`.  Raises ``QuorumLostError`` (after
        signaling the failure detector) when the survivors are not a
        strict majority — the detector-driven relaunch is the last
        resort, no longer the only mechanism.

        ``zero_boundary`` (a :class:`kungfu_tpu.elastic.reshard.
        ZeroBoundary`) carries ZeRO-sharded optimizer state through the
        shrink: it is re-carved leaderlessly across the survivors (dead
        ranks' chunks served from ring-buddy mirrors) — see
        docs/zero.md.

        ``stage_boundary`` (a :class:`kungfu_tpu.parallel.pp.
        StageBoundary`) carries a pipeline stage through it the same
        way: the survivors re-balance layers over the remaining stages,
        a whole dead stage restored from its predecessor's ring-buddy
        mirror — recovery-ladder rung 10 (docs/pipeline.md)."""
        from kungfu_tpu.elastic.shrink import recover_from_peer_failure

        return recover_from_peer_failure(self, failure, snapshot,
                                         zero_boundary=zero_boundary,
                                         stage_boundary=stage_boundary)

    # -- monitoring / adaptation (reference peer.hpp GetPeerLatencies /
    # CheckInterference / GetEgressRates / SetTree) ----------------------
    def get_peer_latencies(self, samples: int = 1):
        from kungfu_tpu.monitor.adapt import get_peer_latencies

        return get_peer_latencies(self, samples)

    def get_egress_rates(self):
        if self.net_monitor is None:
            return [0.0] * self.size()
        return self.net_monitor.egress_rates(
            [str(w) for w in self.cluster.workers]
        )

    def check_interference(self) -> bool:
        from kungfu_tpu.monitor.adapt import check_interference, majority_vote_interference

        engine = self.engine()
        suspected = bool(engine and check_interference(engine))
        return majority_vote_interference(self, suspected)

    def set_tree(self, forest) -> None:
        """Install an explicit broadcast tree after cluster-wide agreement
        (reference SetTree: consensus on the tree digest, barrier, swap)."""
        from kungfu_tpu.monitor.adapt import set_tree
        from kungfu_tpu.plan.graph import Graph

        digest = Graph.from_forest_array(forest).digest_bytes()
        if not self.consensus_bytes(digest, name="set-tree"):
            raise RuntimeError("peers disagree on the proposed tree")
        self.barrier()
        engine = self.engine()
        if engine is not None:
            set_tree(engine, forest)

    # -- p2p blob store (gossip) -----------------------------------------
    def save(self, name: str, blob, version: Optional[str] = None,
             copy: bool = True) -> None:
        """Save into this peer's gossip store.  Names under ``kf.`` are
        reserved for the control plane (served from a separate store).
        ``copy=False`` hands over the caller's buffer (never mutate it
        after) — the gossip hot path publishes ~100 MiB fused models."""
        self.store.save(name, blob, version, copy=copy)

    def request(self, target_rank: int, name: str,
                version: Optional[str] = None,
                timeout: float = 60.0) -> Optional[bytes]:
        """Pull a named blob from a peer's versioned store
        (reference ``p2p.go:15-41``, ``handler/p2p.go:102-120``).
        ``kf.``-prefixed names are answered from the target's
        control-plane store."""
        from kungfu_tpu.store import remote_request

        target = self.cluster.workers[target_rank]
        return remote_request(self, target, name, version, timeout=timeout)

    def request_into(self, target_rank: int, name: str, buf,
                     version: Optional[str] = None,
                     timeout: float = 60.0,
                     send_retries: Optional[int] = None):
        """Pull a named blob INTO a preallocated buffer — zero-copy on
        the native backend (see :func:`remote_request_into`).
        ``send_retries`` bounds the request's connect ladder (miss-
        tolerant callers like gossip fail fast on a dead target)."""
        from kungfu_tpu.store import remote_request_into

        target = self.cluster.workers[target_rank]
        return remote_request_into(self, target, name, buf, version,
                                   timeout=timeout,
                                   send_retries=send_retries)
