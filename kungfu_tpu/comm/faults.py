"""Typed data-path failure vocabulary.

Before this module, a dead peer surfaced as whichever low-level error
happened to fire first — a ``TimeoutError`` from a rendezvous queue, a
``ConnectionError`` from a pooled socket, or nothing at all (a hang) —
and the only recovery was the detector-driven whole-job relaunch.  The
in-flight fault-tolerance path needs the failure *attributed*: every
engine collective primitive now runs under a per-peer deadline and, on
exhaustion, raises :class:`PeerFailureError` carrying the suspect rank,
which :func:`kungfu_tpu.elastic.shrink.recover_from_peer_failure` turns
into an exclusion consensus among the survivors.

``PeerFailureError`` subclasses ``ConnectionError`` deliberately: every
existing ``except (OSError, ConnectionError, TimeoutError)`` site keeps
working, while new code can catch the typed form and recover in-flight.
"""

from __future__ import annotations

from typing import Optional


class PeerFailureError(ConnectionError):
    """A collective primitive exhausted its per-peer deadline/retries.

    ``rank`` is the *suspect* — the peer this primitive was talking to —
    or ``None`` when the failing layer cannot attribute blame (the
    native executor reports only collective-level failure); recovery
    then probes liveness itself (``elastic.shrink.find_dead_ranks``).
    A suspect is a hint, not a verdict: a peer blocked on the real
    victim times out toward an innocent neighbor, so the shrink path
    re-confirms every suspect by ping before proposing eviction.
    """

    def __init__(
        self,
        rank: Optional[int],
        peer=None,
        op: str = "",
        phase: str = "",
        cause: Optional[BaseException] = None,
    ):
        self.rank = rank
        self.peer = peer
        self.op = op
        self.phase = phase
        self.cause = cause
        who = f"rank {rank} ({peer})" if rank is not None else "unattributed peer"
        super().__init__(
            f"collective {op!r} {phase or 'failed'} toward {who}: {cause}"
        )


class SliceExcludedError(RuntimeError):
    """This worker is ALIVE but its slice is not: the ping-confirmed
    dead set covers part of its slice, and a half-dead slice has no
    within-slice (ICI) mesh left — it must not silently keep training
    (:mod:`kungfu_tpu.elastic.slices`).  The surviving slices exclude
    the whole slice; a worker catching this should stop cleanly (its
    runner sees an orderly exit, not a crash) and wait for redeployment
    of the repaired slice."""

    def __init__(self, slice_id: int, dead_ranks):
        self.slice_id = slice_id
        self.dead_ranks = sorted(dead_ranks)
        super().__init__(
            f"slice {slice_id} is degraded (dead ranks {self.dead_ranks}); "
            "this surviving member is excluded with it — a half-dead "
            "slice must not keep training"
        )


class ServeOverloadError(RuntimeError):
    """Typed admission rejection (kf-serve router): accepted-but-
    unfinished requests already fill the bounded queue
    (``KF_SERVE_QUEUE_DEPTH``).  Overload must surface as an immediate,
    client-visible rejection the caller can back off on — not as an
    unbounded queue whose tail latency quietly eats the e2e SLO
    (docs/serving.md)."""

    def __init__(self, depth: int, limit: int):
        self.depth = depth
        self.limit = limit
        super().__init__(
            f"serving queue at capacity ({depth}/{limit} accepted "
            "requests in flight); rejecting admission"
        )


class RequestLostError(RuntimeError):
    """A replayed serving request ran out of live workers (or replay
    attempts): the router could not honor its zero-loss contract for
    this request.  Carries the request id and the committed tokens so
    the caller can resubmit without losing the paid-for prefix."""

    def __init__(self, rid: str, committed, why: str = ""):
        self.rid = rid
        self.committed = list(committed)
        super().__init__(
            f"request {rid!r} lost after {len(self.committed)} committed "
            f"token(s): {why or 'no live workers remain'}"
        )


class QuorumLostError(RuntimeError):
    """Shrink-to-survivors cannot proceed: the surviving set is not a
    strict majority of the current membership.  The caller's last resort
    is the detector-driven full restart (signal via
    :func:`kungfu_tpu.monitor.signals.monitor_report_down`)."""

    def __init__(self, survivors: int, total: int):
        self.survivors = survivors
        self.total = total
        super().__init__(
            f"{survivors} survivor(s) of {total} is not a quorum; "
            "falling back to detector-driven restart"
        )
