"""Host-side message channel — the control plane between worker processes.

TPU-native analog of the reference's rchannel
(``srcs/go/rchannel/{connection,client,server,handler}``): typed,
named messages over TCP between peers, rendezvous-by-name receive queues,
connect retries while peers come up, and **version-token fencing** — every
COLLECTIVE message is queued under the cluster-version token it was sent
with and only ever *read* under the receiver's current token, so stale
payloads can never alias a later epoch's collectives (the moral equivalent
of the reference's connection-token check, ``connection.go:28-47,77-87``;
we queue-and-isolate rather than drop so a future-epoch message arriving
early is preserved).

This layer deliberately does *not* carry gradient traffic (that is the
device plane, :mod:`kungfu_tpu.comm.device`).  It exists for the phases
when no mesh exists or data must move peer-to-peer off the ICI:

* membership consensus + barrier during elastic resize;
* the versioned blob store pulls of PairAveraging gossip;
* heartbeat / failure-detection signals.

Wire format (little-endian), one message per connection:

    magic u32 | token u32 | conn_type u8 | src_len u16 | src utf8
    | name_len u16 | name utf8 | payload_len u32 | payload

Two interoperable backends implement the same wire format and API:

* :class:`NativeHostChannel` — the accept loop, framed decode, rendezvous
  queues, fencing, and pooled sender run in **C++ threads**
  (:file:`kungfu_tpu/native/transport.cpp`), the analog of the
  reference's native Go transport;
* :class:`PyHostChannel` — pure-Python sockets, always available.

:func:`HostChannel` is the factory; select with ``KF_TPU_HOST_TRANSPORT``
(``native`` | ``python`` | ``auto``, default auto = native when the
toolchain/.so is available).
"""

from __future__ import annotations

import enum
import os
import queue
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from kungfu_tpu.monitor import timeline
from kungfu_tpu.plan.peer import PeerID, parse_peer_id
from kungfu_tpu.plan.peerlist import PeerList
from kungfu_tpu.utils.log import get_logger
from kungfu_tpu.utils.retry import jittered

_log = get_logger("host-chan")

MAGIC = 0x4B465450  # "KFTP"
# shared with transport.cpp kMaxFrame/kMaxMetaLen: the wire is
# unauthenticated, so lengths from a stray connection are bounded, and
# senders enforce the same bound loudly (error next to its cause, not a
# silent remote connection drop)
MAX_FRAME = 3 << 30
MAX_META_LEN = 4096
CONNECT_RETRIES = 500
CONNECT_RETRY_PERIOD_S = 0.2  # reference: 500 x 200ms (config.go:16-18)
#: per-attempt TCP connect timeout — exported because deadline-bounded
#: callers (engine._send) size their retry ladders by the worst case a
#: single rung can block (a SYN-dropping dead host burns this in full)
CONNECT_TIMEOUT_S = 10.0

USE_UNIXSOCK = "KF_TPU_USE_UNIXSOCK"

#: default ceiling on the load-scaled pools (``KF_CONFIG_HOST_POOL_MAX``)
HOST_POOL_CAP_DEFAULT = 16

#: PEER_TO_PEER name space reserved for the serving plane (kf-serve
#: request/progress/completion frames, serve/router.py).  Defined here —
#: the transport layer both planes import — because the blob store's
#: p2p handler must SKIP these names (its own responder would race a
#: _FAIL reply onto a serve request id): one constant, two readers,
#: zero drift (docs/serving.md)
SERVE_NAME_PREFIX = "req.srv"


def host_pool_size(n_peers: int, floor: int = 2,
                   pool: str = "host") -> int:
    """Responder/sender pool size scaled with the peer count.

    A fixed pool is wrong at both ends: 2 responder threads serialize a
    16-peer cluster's concurrent blob pulls behind the slowest receiver,
    and a thread per peer on a 256-worker job is 256 idle stacks.  So:
    one slot per peer, floored at ``floor`` (a 2-peer world still wants
    request/response overlap) and capped by ``KF_CONFIG_HOST_POOL_MAX``
    (default 16 — beyond that the loopback/NIC is the bottleneck, not
    thread count).  The cap is the operator's ceiling, so it wins over
    the floor on a thread-constrained host.  The chosen size is exported
    as the ``kf_host_pool_size{pool=...}`` registry gauge — labeled per
    pool (engine sender/chunk pool vs p2p responders), since the two
    scale from different floors — so kftop//metrics can confirm the
    scaling actually happened."""
    from kungfu_tpu.monitor.registry import REGISTRY
    from kungfu_tpu.utils import envs

    cap = max(1, envs.parse_int_env(envs.HOST_POOL_MAX,
                                    HOST_POOL_CAP_DEFAULT))
    size = max(1, min(cap, max(int(floor), int(n_peers))))
    REGISTRY.gauge("kf_host_pool_size", pool=pool).set(size)
    return size


def unixsock_enabled() -> bool:
    """Colocated peers use a unix domain socket (reference
    ``UseUnixSock=true``, sockfile ``plan/addr.go:24``); opt out with
    ``KF_TPU_USE_UNIXSOCK=0``."""
    return os.environ.get(USE_UNIXSOCK, "1").lower() not in ("0", "false", "no")


def unix_sock_path(host: str, port: int) -> str:
    """Must match the C++ transport's scheme (transport.cpp).  Keyed by
    host AND port: loopback-alias multi-host simulations give worker j
    the same port on every host (``gen_peer_list``), so a port-only
    sockfile would alias two different peers on one machine.  Sockfiles
    live in a per-uid mode-0700 directory (override: ``KF_SOCK_DIR``) so
    another local user on a shared host can neither squat the path nor
    pre-bind it to intercept collective traffic."""
    base = os.environ.get("KF_SOCK_DIR") or f"/tmp/kf-tpu-{os.getuid()}"
    os.makedirs(base, mode=0o700, exist_ok=True)
    # an existing dir must actually be OURS and private — makedirs with
    # exist_ok says nothing about who owns it (a squatter could pre-create
    # it 0777 and then swap sockfiles under us); raising OSError makes
    # every caller fall back to TCP-only
    st = os.lstat(base)
    import stat as _stat

    if (
        not _stat.S_ISDIR(st.st_mode)
        or st.st_uid != os.getuid()
        or (st.st_mode & 0o077) != 0
    ):
        raise OSError(f"unsafe socket dir {base}: not a private dir owned by uid {os.getuid()}")
    return f"{base}/{host}-{port}.sock"


class ConnType(enum.IntEnum):
    """Parity with reference ``message.go:12-17``."""

    PING = 1
    CONTROL = 2
    COLLECTIVE = 3
    PEER_TO_PEER = 4


class _Msg:
    __slots__ = ("token", "conn_type", "src", "name", "payload")

    def __init__(self, token, conn_type, src, name, payload):
        self.token = token
        self.conn_type = conn_type
        self.src = src
        self.name = name
        self.payload = payload


class HeaderCodec:
    """Single authority for the fixed fields of the wire header.

    Every frame starts with ``magic u32 | token u32 | conn_type u8 |
    src_len u16``, and the two later length prefixes (``name_len u16``,
    ``payload_len u32``) complete the framing.  The C++ decoder
    (:file:`kungfu_tpu/native/transport.cpp` ``encode_head`` /
    ``decode_head``) reads the same bytes at the same offsets; kf-verify's
    ``wire-contract`` rule diffs the two sides and anchors on this class,
    so the format string exists in exactly one place per language —
    a drifted copy can no longer hide at a second pack/unpack site.
    """

    #: magic u32 | token u32 | conn_type u8 | src_len u16
    HEAD_FMT = "<IIBH"
    HEAD_SIZE = struct.calcsize(HEAD_FMT)  # 11 — mirrors C++ `head[11]`
    #: name_len u16
    NAME_LEN_FMT = "<H"
    NAME_LEN_SIZE = struct.calcsize(NAME_LEN_FMT)
    #: payload_len u32
    PAYLOAD_LEN_FMT = "<I"
    PAYLOAD_LEN_SIZE = struct.calcsize(PAYLOAD_LEN_FMT)

    @staticmethod
    def pack_head(token: int, conn_type: int, src: bytes, name: bytes,
                  payload_len: int) -> bytes:
        return (
            struct.pack(HeaderCodec.HEAD_FMT, MAGIC, token, conn_type, len(src))
            + src
            + struct.pack(HeaderCodec.NAME_LEN_FMT, len(name))
            + name
            + struct.pack(HeaderCodec.PAYLOAD_LEN_FMT, payload_len)
        )

    @staticmethod
    def unpack_head(head: bytes) -> Tuple[int, int, int, int]:
        """``(magic, token, conn_type, src_len)`` from the fixed prefix."""
        return struct.unpack(HeaderCodec.HEAD_FMT, head)

    @staticmethod
    def unpack_name_len(raw: bytes) -> int:
        (name_len,) = struct.unpack(HeaderCodec.NAME_LEN_FMT, raw)
        return name_len

    @staticmethod
    def unpack_payload_len(raw: bytes) -> int:
        (payload_len,) = struct.unpack(HeaderCodec.PAYLOAD_LEN_FMT, raw)
        return payload_len


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        # connection-lifetime reader: each stream thread blocks here for
        # as long as its peer keeps the connection; close() shutdown()s
        # the socket, which unblocks this recv with b""
        chunk = sock.recv(n - len(buf))  # kflint: allow(blocking-io)
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        buf += chunk
    return buf


def _payload_nbytes(payload) -> int:
    return len(payload) if isinstance(payload, bytes) else memoryview(payload).nbytes


def _encode_head(token: int, conn_type: int, src: str, name: str, nbytes: int) -> bytes:
    sb, nb = src.encode(), name.encode()
    if nbytes > MAX_FRAME:
        raise ValueError(
            f"payload of {nbytes} bytes exceeds the 3 GiB frame limit"
        )
    return HeaderCodec.pack_head(token, conn_type, sb, nb, nbytes)


def _encode(token: int, conn_type: int, src: str, name: str, payload: bytes) -> bytes:
    return _encode_head(token, conn_type, src, name, len(payload)) + payload


def _decode(sock: socket.socket) -> _Msg:
    magic, token, conn_type, src_len = HeaderCodec.unpack_head(
        _read_exact(sock, HeaderCodec.HEAD_SIZE)
    )
    if magic != MAGIC:
        raise ValueError(f"bad magic {magic:#x}")
    if src_len > MAX_META_LEN:
        raise ValueError(f"src field of {src_len} bytes over limit")
    src = _read_exact(sock, src_len).decode()
    name_len = HeaderCodec.unpack_name_len(
        _read_exact(sock, HeaderCodec.NAME_LEN_SIZE)
    )
    if name_len > MAX_META_LEN:
        raise ValueError(f"name field of {name_len} bytes over limit")
    name = _read_exact(sock, name_len).decode()
    payload_len = HeaderCodec.unpack_payload_len(
        _read_exact(sock, HeaderCodec.PAYLOAD_LEN_SIZE)
    )
    if payload_len > MAX_FRAME:
        raise ValueError(f"payload of {payload_len} bytes over the frame limit")
    payload = _read_exact(sock, payload_len)
    return _Msg(token, conn_type, src, name, payload)


class _ChannelOps:
    """Control-plane collectives shared by both backends (star-rooted at
    rank 0: fine for control traffic — small payloads, infrequent; the
    device plane handles bulk data)."""

    def wait(self, peer: PeerID, timeout: float = 120.0) -> None:
        """Poll-ping until the peer is up (reference ``client.go:47-59``)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.ping(peer):
                return
            time.sleep(CONNECT_RETRY_PERIOD_S)
        raise TimeoutError(f"peer {peer} not up after {timeout}s")

    def _rank(self, peers: PeerList) -> int:
        r = peers.rank(self.self_id)
        if r is None:
            raise RuntimeError(f"{self.self_id} not in {peers}")
        return r

    def gather_bytes(self, data: bytes, peers: PeerList, name: str,
                     send_retries: int = CONNECT_RETRIES) -> Optional[List[bytes]]:
        """Root (rank 0) returns all peers' payloads in rank order.
        ``send_retries`` bounds the connect ladder toward the root —
        failure-recovery callers (the shrink consensus) run exactly when
        peers are dying and must get their ``ConnectionError`` in
        seconds, not after the full 500-rung bring-up window."""
        rank = self._rank(peers)
        if rank == 0:
            out = [data]
            for p in list(peers)[1:]:
                out.append(self.recv(p, name))
            return out
        self.send(peers[0], name, data, retries=send_retries)
        return None

    def broadcast_bytes(self, data: Optional[bytes], peers: PeerList, name: str,
                        send_retries: int = CONNECT_RETRIES) -> bytes:
        rank = self._rank(peers)
        if rank == 0:
            assert data is not None
            for p in list(peers)[1:]:
                self.send(p, name, data, retries=send_retries)
            return data
        return self.recv(peers[0], name)

    def allgather_bytes(self, data: bytes, peers: PeerList, name: str) -> List[bytes]:
        gathered = self.gather_bytes(data, peers, name + ".g")
        if self._rank(peers) == 0:
            blob = _pack_list(gathered)
        else:
            blob = None
        return _unpack_list(self.broadcast_bytes(blob, peers, name + ".b"))

    def barrier(self, peers: PeerList, name: str = "barrier") -> None:
        self.gather_bytes(b"", peers, name + ".in")
        self.broadcast_bytes(b"" if self._rank(peers) == 0 else None, peers, name + ".out")

    def consensus_bytes(self, data: bytes, peers: PeerList, name: str = "consensus",
                        send_retries: int = CONNECT_RETRIES) -> bool:
        """True iff all peers supplied identical bytes
        (control-plane analog of ``session.go:124-155``)."""
        gathered = self.gather_bytes(data, peers, name + ".g",
                                     send_retries=send_retries)
        if self._rank(peers) == 0:
            ok = all(g == gathered[0] for g in gathered)
            self.broadcast_bytes(b"\x01" if ok else b"\x00", peers, name + ".b",
                                 send_retries=send_retries)
            return ok
        return self.broadcast_bytes(None, peers, name + ".b") == b"\x01"


class PyHostChannel(_ChannelOps):
    """Pure-Python backend.

    ``token`` is the cluster version; bump it with :meth:`set_token` on
    membership change — COLLECTIVE queues of older epochs are purged and
    any late stale-epoch arrival is discarded at enqueue (fencing).
    """

    def __init__(self, self_id: PeerID, token: int = 0, bind_host: str = "", monitor=None):
        self.self_id = self_id
        self._token = token
        #: optional NetMonitor recording egress/ingress byte counts
        self.monitor = monitor
        self._queues: Dict[Tuple[int, str, str, int], queue.Queue] = {}
        self._qlock = threading.Lock()
        self._control_handlers = []
        self._p2p_handlers = []
        self._pool: Dict[PeerID, list] = {}
        self._pool_lock = threading.Lock()

        chan = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # stream loop: a pooled client sends many messages on one
                # connection (reference Stream(), handler.go:30-41)
                while True:
                    try:
                        msg = _decode(self.request)
                    except (ConnectionError, ValueError, OSError) as e:
                        _log.debug("connection done: %s", e)
                        return
                    chan._dispatch(msg, self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((bind_host or "0.0.0.0", self_id.port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

        # composed server: second listener on the colocated-peer sockfile
        self._unix_server = None
        self._unix_path = None
        if unixsock_enabled():
            class UnixServer(socketserver.ThreadingUnixStreamServer):
                daemon_threads = True

            try:
                path = unix_sock_path(self_id.host, self_id.port)
                if os.path.exists(path):
                    os.unlink(path)
                self._unix_server = UnixServer(path, Handler)
                self._unix_path = path
                threading.Thread(
                    target=self._unix_server.serve_forever, daemon=True
                ).start()
            except OSError as e:  # TCP-only is fine (e.g. /tmp unwritable)
                _log.debug("no unix listener: %s", e)
                self._unix_server = None

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        self.reset_connections()
        self._server.shutdown()
        self._server.server_close()
        if self._unix_server is not None:
            self._unix_server.shutdown()
            self._unix_server.server_close()
            try:
                os.unlink(self._unix_path)
            except OSError:
                pass

    def set_token(self, token: int) -> None:
        """Move to a new cluster epoch; purge collective queues of older
        epochs (their contents can never legally be read again)."""
        self._token = token
        with self._qlock:
            dead = [
                k for k in self._queues
                if k[0] == ConnType.COLLECTIVE and k[3] < token
            ]
            for k in dead:
                del self._queues[k]

    @property
    def token(self) -> int:
        return self._token

    # -- dispatch --------------------------------------------------------
    def _queue(self, conn_type: int, src: str, name: str, token: int = 0) -> queue.Queue:
        # COLLECTIVE queues are keyed by epoch token so a stale queued
        # payload can never alias a same-named collective of a later epoch
        with self._qlock:
            if conn_type == ConnType.COLLECTIVE and token < self._token:
                # late stale-epoch arrival: nothing will ever read it and
                # the purge already ran — don't retain the payload
                return queue.Queue()
            key = (conn_type, src, name, token if conn_type == ConnType.COLLECTIVE else 0)
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = queue.Queue()
            return q

    def _dispatch(self, msg: _Msg, sock: socket.socket) -> None:
        if self.monitor is not None:
            self.monitor.ingress(msg.src, len(msg.payload))
        if msg.conn_type == ConnType.PING:
            try:
                sock.sendall(_encode(self._token, ConnType.PING, str(self.self_id), msg.name, b""))
            except OSError:
                pass
            return
        # COLLECTIVE fencing: messages are queued under their epoch token and
        # only ever read under the receiver's *current* token.  Stale-epoch
        # payloads land in queues nobody reads (purged on set_token); a
        # future-epoch message arriving before this peer bumps its token is
        # preserved, not dropped — the sender already moved to the new epoch
        # and will not retry (drop-at-dispatch would deadlock the first
        # post-resize collective).
        if msg.conn_type == ConnType.COLLECTIVE and msg.token != self._token:
            _log.debug(
                "queueing %s from %s under epoch %d (current %d)",
                msg.name, msg.src, msg.token, self._token,
            )
        if msg.conn_type == ConnType.CONTROL and self._control_handlers:
            for h in list(self._control_handlers):
                h(msg.name, msg.payload, msg.src)
            return
        if (
            msg.conn_type == ConnType.PEER_TO_PEER
            and msg.name.startswith("req.")
            and self._p2p_handlers
        ):
            for h in list(self._p2p_handlers):
                h(msg.name, msg.payload, msg.src)
            return
        self._queue(msg.conn_type, msg.src, msg.name, msg.token).put(msg.payload)

    def on_control(self, handler) -> None:
        """Register ``handler(name, payload, src)`` for CONTROL messages."""
        self._control_handlers.append(handler)

    def on_p2p_request(self, handler) -> None:
        """Register ``handler(name, payload, src)`` for PEER_TO_PEER messages
        whose name starts with ``req.`` (the blob-store responder)."""
        self._p2p_handlers.append(handler)

    # -- client side -----------------------------------------------------
    def _connect(self, peer: PeerID, retries=CONNECT_RETRIES) -> socket.socket:
        colocated = unixsock_enabled() and peer.host == self.self_id.host
        last = None
        for _ in range(retries):
            if colocated:
                try:
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    s.settimeout(CONNECT_TIMEOUT_S)
                    s.connect(unix_sock_path(peer.host, peer.port))
                    return s
                except OSError:
                    pass  # peer may be TCP-only; fall through
            try:
                return socket.create_connection((peer.host, peer.port), timeout=CONNECT_TIMEOUT_S)
            except OSError as e:
                last = e
                # jittered, mean-preserving: the 500 x 200 ms reference
                # window holds, but N workers retrying one cold peer
                # decorrelate instead of re-colliding every 200 ms
                time.sleep(jittered(CONNECT_RETRY_PERIOD_S))
        raise ConnectionError(f"cannot reach {peer} after {retries} retries: {last}")

    def _pooled(self, peer: PeerID):
        """Persistent per-peer send connection slot + its lock (reference
        keeps a connection pool in rchannel/client; per-message connect
        would exhaust ephemeral ports on the gradient path).  The connect
        itself happens in send() *under* the entry lock, so concurrent
        first sends cannot double-connect."""
        with self._pool_lock:
            entry = self._pool.get(peer)
            if entry is None:
                entry = self._pool[peer] = [None, threading.Lock()]
            return entry

    def send(
        self,
        peer: PeerID,
        name: str,
        payload,
        conn_type: ConnType = ConnType.COLLECTIVE,
        retries: int = CONNECT_RETRIES,
    ) -> None:
        # header and payload sent separately so a large payload (any
        # contiguous buffer, not just bytes) is never concat-copied;
        # sendall accepts buffer-protocol objects directly
        nbytes = _payload_nbytes(payload)
        head = _encode_head(self._token, conn_type, str(self.self_id), name, nbytes)
        # enabled() guard BEFORE building the kwargs: this runs per chunk
        # per peer, and the disabled path must not pay str()/dict cost
        if timeline.enabled():
            timeline.event("send", name, peer=str(peer), nbytes=nbytes,
                           conn=int(conn_type))
        if self.monitor is not None:
            # payload bytes on both sides (ingress counts the same), so
            # egress/ingress totals of a symmetric exchange match
            self.monitor.egress(str(peer), nbytes)
        entry = self._pooled(peer)
        with entry[1]:
            if entry[0] is None:
                entry[0] = self._connect(peer, retries)
            try:
                entry[0].sendall(head)
                entry[0].sendall(payload)
            except OSError:
                # stale pooled socket (peer restarted): reconnect once
                try:
                    entry[0].close()
                except OSError:
                    pass
                entry[0] = None
                entry[0] = self._connect(peer, retries)
                try:
                    entry[0].sendall(head)
                    entry[0].sendall(payload)
                except OSError:
                    # a HALF-written frame must never stay pooled: a
                    # caller-level retry would append a fresh frame onto
                    # the desynced stream and the receiver would parse
                    # payload bytes as headers (silent corruption risk,
                    # not just a dropped connection)
                    try:
                        entry[0].close()
                    except OSError:
                        pass
                    entry[0] = None
                    raise

    def chaos_partial_send(
        self,
        peer: PeerID,
        name: str,
        payload,
        nbytes: int,
        conn_type: ConnType = ConnType.COLLECTIVE,
    ) -> None:
        """Fault-injection primitive (``reset`` clauses, chaos-only —
        never on a production code path): transmit a frame whose header
        promises the full payload, deliver only the first ``nbytes``
        bytes, then kill the socket.  The receiver's stream loop observes
        peer-closed-mid-message — byte-for-byte what a worker dying
        mid-chunk produces — on a throwaway connection, so the pooled
        sender socket stays intact for the retry that follows."""
        head = _encode_head(
            self._token, conn_type, str(self.self_id), name,
            _payload_nbytes(payload),
        )
        sock = self._connect(peer, retries=5)
        try:
            sock.sendall(head)
            sock.sendall(memoryview(payload).cast("B")[:nbytes])
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def reset_connections(self) -> None:
        """Drop pooled connections (on membership change; reference
        ``client.go:82`` ResetConnections).  Sockets are closed without
        taking the per-entry send locks: a sender stuck in the reconnect
        loop toward a dead peer must not block the reset (its in-flight
        sendall fails fast when the socket closes under it)."""
        with self._pool_lock:
            entries = list(self._pool.values())
            self._pool.clear()
        for entry in entries:
            sock = entry[0]
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def recv(
        self, src: PeerID, name: str, conn_type: ConnType = ConnType.COLLECTIVE,
        timeout: Optional[float] = 60.0,
    ) -> bytes:
        try:
            payload = self._queue(
                conn_type, str(src), name, self._token
            ).get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"recv {name!r} from {src} timed out after {timeout}s") from None
        if timeline.enabled():
            timeline.event("recv", name, peer=str(src), nbytes=len(payload),
                           conn=int(conn_type))
        return payload

    def recv_into(
        self, src: PeerID, name: str, buf,
        conn_type: ConnType = ConnType.COLLECTIVE,
        timeout: Optional[float] = 60.0,
    ) -> bool:
        """API parity with the native backend's zero-copy receive; the
        pure-Python path necessarily copies (bytes off the queue → buf).
        False = size mismatch, payload left queued."""
        q = self._queue(conn_type, str(src), name, self._token)
        try:
            payload = q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"recv_into {name!r} from {src} timed out after {timeout}s"
            ) from None
        mv = memoryview(buf).cast("B")
        if len(payload) != mv.nbytes:
            # put it back for the recv() fallback (FIFO position is moot:
            # rendezvous names are unique per op instance)
            q.put(payload)
            return False
        mv[:] = payload
        if timeline.enabled():
            timeline.event("recv", name, peer=str(src), nbytes=mv.nbytes,
                           conn=int(conn_type))
        return True

    def post_recv(
        self, src: PeerID, name: str, buf,
        conn_type: ConnType = ConnType.COLLECTIVE,
    ):
        """API parity with the native backend's pre-registered receive;
        the pure-Python path has no registration, so this is recv_into
        deferred to ``wait()``."""
        chan = self

        class _Posted:
            def wait(self, timeout: Optional[float] = 60.0) -> bool:
                return chan.recv_into(src, name, buf, conn_type, timeout)

            def abort(self) -> None:
                pass

        return _Posted()

    def ping(self, peer: PeerID, timeout: float = 10.0) -> bool:
        try:
            with socket.create_connection((peer.host, peer.port), timeout=timeout) as sock:
                sock.sendall(_encode(self._token, ConnType.PING, str(self.self_id), "ping", b""))
                _decode(sock)
                return True
        except (OSError, ValueError, ConnectionError):
            return False


class NativeHostChannel(_ChannelOps):
    """C++ backend: same API and wire format, served by native threads
    (:file:`kungfu_tpu/native/transport.cpp`).  Python is entered only
    for registered control/p2p handlers and monitor accounting."""

    def __init__(self, self_id: PeerID, token: int = 0, bind_host: str = "", monitor=None):
        from kungfu_tpu.native.transport import NativeTransport

        self.self_id = self_id
        self.monitor = monitor
        self._t = NativeTransport(
            str(self_id), self_id.port, bind_host=bind_host, token=token,
            use_unix=unixsock_enabled(),
        )
        self._control_handlers = []
        self._p2p_handlers = []
        self._t.set_control_handler(self._run_handlers(self._control_handlers))
        self._t.set_p2p_handler(self._run_handlers(self._p2p_handlers))
        self._ingress_seen: Dict[str, int] = {}
        self._egress_seen: Dict[str, int] = {}
        self._ingress_stop = threading.Event()
        self._ingress_thread: Optional[threading.Thread] = None
        if monitor is not None:
            # the C++ side counts ingress bytes; feed deltas to the
            # NetMonitor at its own sampling granularity
            self._ingress_thread = threading.Thread(
                target=self._ingress_poll, daemon=True
            )
            self._ingress_thread.start()

    @staticmethod
    def _run_handlers(handlers):
        def run(name: str, payload: bytes, src: str) -> bool:
            if not handlers:
                return False  # fall through to the C++ rendezvous queue
            for h in list(handlers):
                h(name, payload, src)
            return True

        return run

    def _ingress_poll(self) -> None:
        # both directions are counted in C++ (the native engine executor
        # sends without crossing this wrapper); this thread feeds deltas
        # into the NetMonitor at its own granularity
        while not self._ingress_stop.wait(0.5):
            try:
                ingress = self._t.ingress_totals()
                egress = self._t.egress_totals()
            except Exception:  # noqa: BLE001 - channel torn down mid-poll
                return
            for src, total in ingress.items():
                delta = total - self._ingress_seen.get(src, 0)
                if delta > 0:
                    self._ingress_seen[src] = total
                    self.monitor.ingress(src, delta)
            for peer, total in egress.items():
                delta = total - self._egress_seen.get(peer, 0)
                if delta > 0:
                    self._egress_seen[peer] = total
                    self.monitor.egress(peer, delta)

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        self._ingress_stop.set()
        if self._ingress_thread is not None:
            # the poll thread must be out of the native handle before the
            # C++ channel is freed (a poll on a freed handle is a segfault,
            # not an exception)
            self._ingress_thread.join(timeout=5)
            if self._ingress_thread.is_alive():
                # monitor sink wedged: leaking the native channel beats a
                # guaranteed segfault in the still-running poll thread
                _log.warning("ingress poll thread stuck; leaking native channel")
                return
            self._ingress_thread = None
        self._t.close()

    def set_token(self, token: int) -> None:
        self._t.set_token(token)

    @property
    def token(self) -> int:
        return self._t.token

    def on_control(self, handler) -> None:
        self._control_handlers.append(handler)

    def on_p2p_request(self, handler) -> None:
        self._p2p_handlers.append(handler)

    # -- client side -----------------------------------------------------
    def send(
        self,
        peer: PeerID,
        name: str,
        payload: bytes,
        conn_type: ConnType = ConnType.COLLECTIVE,
        retries: int = CONNECT_RETRIES,
    ) -> None:
        # egress is counted in the C++ send (shared with the native engine
        # executor) and polled by _ingress_poll — no wrapper-side count,
        # which would double it.  The timeline mark covers every frame
        # that crosses THIS wrapper; fully-native engine collectives
        # bypass it and surface as their collective span instead.
        if timeline.enabled():
            timeline.event("send", name, peer=str(peer),
                           nbytes=_payload_nbytes(payload),
                           conn=int(conn_type))
        self._t.send(str(peer), name, payload, int(conn_type), retries)

    def recv(
        self, src: PeerID, name: str, conn_type: ConnType = ConnType.COLLECTIVE,
        timeout: Optional[float] = 60.0,
    ) -> bytes:
        payload = self._t.recv(str(src), name, int(conn_type), timeout)
        if timeline.enabled():
            timeline.event("recv", name, peer=str(src), nbytes=len(payload),
                           conn=int(conn_type))
        return payload

    def recv_into(
        self, src: PeerID, name: str, buf,
        conn_type: ConnType = ConnType.COLLECTIVE,
        timeout: Optional[float] = 60.0,
    ) -> bool:
        """Zero-copy receive into ``buf`` (writable contiguous buffer):
        socket→buffer in the C++ stream thread, no allocation or queue
        hop (reference RecvInto, ``handler/collective.go:34-65``).
        False = size mismatch, payload left queued — use :meth:`recv`."""
        return self._t.recv_into(str(src), name, int(conn_type), timeout, buf)

    def post_recv(
        self, src: PeerID, name: str, buf,
        conn_type: ConnType = ConnType.COLLECTIVE,
    ):
        """Pre-register ``buf`` for a zero-copy receive BEFORE the
        matching request is dispatched — the response then streams
        socket→buf even when the responder wins the race that makes
        plain :meth:`recv_into` detour through the queue.  ``wait()``
        returns True when filled, False on a queued size mismatch (fall
        back to :meth:`recv`); call ``abort()`` if the request was never
        sent."""
        t, s, ct = self._t, str(src), int(conn_type)
        handle = t.recv_begin(s, name, ct, buf)

        class _Posted:
            # the native handle is consumed by finish/abort — single shot.
            # _buf pins the destination: the C++ stream thread writes into
            # it until finish/abort resolves, so the registration must
            # keep the buffer alive even if the caller drops their
            # reference first (use-after-free otherwise).  INSTANCE
            # attributes — a class-level `_buf` would only be shadowed by
            # the release assignment, keeping the buffer pinned for the
            # handle's whole lifetime
            def __init__(self):
                self._h = handle
                self._buf = buf

            def wait(self, timeout: Optional[float] = 60.0) -> bool:
                if self._h is None:  # mismatching payload already queued
                    return False
                h, self._h = self._h, None
                try:
                    return t.recv_finish(s, name, ct, timeout, h)
                finally:
                    self._buf = None

            def abort(self) -> None:
                if self._h is not None:
                    h, self._h = self._h, None
                    t.recv_abort(s, name, ct, h)
                    self._buf = None

        return _Posted()

    def ping(self, peer: PeerID, timeout: float = 10.0) -> bool:
        return self._t.ping(str(peer), timeout)

    def reset_connections(self) -> None:
        self._t.reset_connections()


def _backend() -> str:
    mode = os.environ.get("KF_TPU_HOST_TRANSPORT", "auto").lower()
    if mode in ("native", "python"):
        return mode
    from kungfu_tpu.native import transport as _nt

    return "native" if _nt.available() else "python"


def HostChannel(self_id: PeerID, token: int = 0, bind_host: str = "", monitor=None):
    """Factory: the native (C++) channel when available, else Python."""
    if _backend() == "native":
        try:
            return NativeHostChannel(self_id, token=token, bind_host=bind_host, monitor=monitor)
        except RuntimeError:  # toolchain raced away; stay functional
            _log.warning("native transport unavailable, using python backend")
    return PyHostChannel(self_id, token=token, bind_host=bind_host, monitor=monitor)


def bind_own_host_channel(self_id: PeerID, token: int = 0, monitor=None):
    """Bind preferring the peer's own advertised address — compose-style
    local clusters give every loopback-alias "host" the same ports, so
    two same-port endpoints coexist on one machine distinguished by alias
    IP — falling back to the wildcard when that address is not locally
    bindable (a NAT'd or load-balanced advertised address)."""
    try:
        return HostChannel(self_id, token=token, bind_host=self_id.host,
                           monitor=monitor)
    except OSError as e:
        _log.warning(
            "cannot bind %s (%s); binding the wildcard instead",
            self_id.host, e,
        )
        return HostChannel(self_id, token=token, monitor=monitor)


def _pack_list(items: List[bytes]) -> bytes:
    out = [struct.pack("<I", len(items))]
    for it in items:
        out.append(struct.pack("<I", len(it)))
        out.append(it)
    return b"".join(out)


def _unpack_list(blob: bytes) -> List[bytes]:
    (n,), off = struct.unpack_from("<I", blob), 4
    items = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", blob, off)
        off += 4
        items.append(blob[off : off + ln])
        off += ln
    return items
