"""Communication layer.

Two planes, mirroring the reference's split between its Go collective engine
(``srcs/go/kungfu/session``) and its control connections:

* :mod:`kungfu_tpu.comm.device` — the **data plane**: a
  :class:`Communicator` wrapping one *mesh epoch* (an immutable
  ``jax.sharding.Mesh`` + cluster version).  Collectives lower to XLA/ICI
  (``psum``/``all_gather``/``ppermute``) under ``shard_map``; this replaces
  both the reference's graph-driven Go allreduce and its NCCL subsystem.

* :mod:`kungfu_tpu.comm.host` — the **control plane**: TCP/Unix-socket
  message channels between worker processes (rendezvous-by-name, connection
  tokens fencing cluster versions), used for barrier/consensus during
  membership changes (when no mesh exists), gossip blob exchange, and
  heartbeats.  The rchannel analog.
"""

from kungfu_tpu.comm.device import Communicator
from kungfu_tpu.comm.host import HostChannel, ConnType

__all__ = ["Communicator", "HostChannel", "ConnType"]
