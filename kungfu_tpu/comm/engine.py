"""Graph-driven collective engine over the host channel.

Direct capability parity with the reference's Go collective engine
(``srcs/go/kungfu/session/{session,allreduce,shard}.go``): collectives
executed by walking (reduce-graph, broadcast-graph) pairs generated from
the 8 named strategies, with buffers **chunked** and each chunk hashed onto
a strategy pair for multi-graph load balancing (``session.go:292-321``,
``shard.go:11-31``).

Role in the TPU build: the **multi-process data path when no shared XLA
mesh exists** — N worker processes (CPU backend tests, or gossip/elastic
phases between mesh epochs) allreduce gradients over TCP exactly like the
reference; the TPU hot path remains :mod:`kungfu_tpu.comm.device`.  This is
also where strategy adaptation is observable: each engine call records
per-strategy throughput (see :mod:`kungfu_tpu.monitor`).

The reduce inner loop runs in the native C++ module
(:mod:`kungfu_tpu.native`, the ``std_transform_2`` analog) with a numpy
fallback when the native build is unavailable.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kungfu_tpu import native
from kungfu_tpu.chaos import controller_for as _chaos_controller_for
from kungfu_tpu.comm.faults import PeerFailureError
from kungfu_tpu.comm.host import CONNECT_TIMEOUT_S, ConnType, HostChannel
from kungfu_tpu.monitor import timeline
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.utils import envs
from kungfu_tpu.utils.retry import sleep_backoff
from kungfu_tpu.plan import (
    Strategy,
    auto_select,
    gen_binary_tree,
    gen_binary_tree_star,
    gen_circular_graph_pair,
    gen_multi_binary_tree_star,
    gen_multi_star,
    gen_star,
    gen_tree,
)
from kungfu_tpu.plan.topology import (
    gen_clique,
    gen_cross_binary_tree,
    gen_cross_ring_pairs,
)
from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.peerlist import PeerList
from kungfu_tpu.utils.log import get_logger

_log = get_logger("engine")

CHUNK_SIZE = 1 << 20  # 1 MiB, reference session.go:292-316


#: colocated peers (single-host cluster, unix-socket transport) pipeline
#: the socket→reduce stages best with smaller chunks: measured on the
#: loopback harness, RING over 256 KiB chunks reaches 1.03 GiB/s bus
#: bandwidth at np=4 where the 1 MiB reference default gets 0.66
#: (docs/perf.md); cross-host traffic keeps the reference's 1 MiB.
CHUNK_SIZE_COLOCATED = 256 << 10


def engine_chunk_size(colocated: bool = False) -> int:
    """Chunk size for graph sharding (``KF_CONFIG_CHUNK_SIZE`` bytes).
    MUST be identical on every peer — chunk boundaries and tags derive
    from it, and a mismatch surfaces as collective timeouts.  The
    launcher propagates the launcher-shell env to all workers, so set it
    where the job is launched, not per worker (``colocated`` is derived
    from the shared peer list, so it is consistent by construction).
    Non-positive values fall back to the default (0 would
    divide-by-zero the chunk count)."""
    default = CHUNK_SIZE_COLOCATED if colocated else CHUNK_SIZE
    v = envs.parse_int_env(envs.CHUNK_SIZE, default)
    return v if v > 0 else default


def engine_threads() -> int:
    """Native executor worker threads (``KF_CONFIG_ENGINE_THREADS``).
    Default adapts to the machine: on a 1-core CI box thread thrash
    costs ~20% (measured), on real hosts chunk parallelism wins."""
    import os

    return envs.parse_int_env(
        envs.ENGINE_THREADS, min(8, max(1, os.cpu_count() or 1))
    )


def engine_timeout_s() -> float:
    """Native executor per-collective timeout (``KF_CONFIG_ENGINE_TIMEOUT``
    seconds) — round-2 VERDICT: a large slow-network collective must be
    tunable past the old hardcoded 60 s."""
    return envs.parse_float_env(envs.ENGINE_TIMEOUT, 60.0)


def overlap_depth_default() -> int:
    """Bound on in-flight async collective handles per engine
    (``KF_CONFIG_OVERLAP_DEPTH``, default 2).  Issuing past the window
    blocks the caller until a handle completes — the backpressure that
    keeps a depth-k software pipeline from ballooning into
    buffer-everything.  Purely local: the window changes *when* this
    process's collectives run, never their tags or issue order, so peers
    may legally run different depths (and the depth is a learnable knob,
    :class:`kungfu_tpu.policy.bandit.OverlapDepthBandit`).  Non-positive
    values fall back to the default, like every engine env reader
    (``engine_chunk_size``); depth 1 IS the serial window — set that to
    disable overlap."""
    v = envs.parse_int_env(envs.OVERLAP_DEPTH, 2)
    return v if v > 0 else 2


def peer_deadline_s() -> float:
    """Per-peer deadline for one collective primitive
    (``KF_CONFIG_PEER_DEADLINE`` seconds; default = the engine timeout).
    A send/recv that cannot complete toward one peer within this window
    raises :class:`PeerFailureError` carrying the suspect rank instead of
    hanging — the entry point of the shrink-to-survivors recovery path
    (see ``elastic/shrink.py``)."""
    return envs.parse_float_env(envs.PEER_DEADLINE, engine_timeout_s())


#: ceiling on the connect-ladder length handed to ``channel.send`` per
#: retry attempt; the actual ladder is derived from the remaining
#: per-peer deadline (see ``_send``), this just bounds the fast case
_SEND_CONNECT_RETRIES = 10

#: "caller did not choose a chaos identity" — distinct from an explicit
#: ``None`` (= a late joiner with no bootstrap rank, which must use the
#: rank-less controller like every other chaos hook does for it)
_CHAOS_RANK_UNSET = object()

REDUCE_OPS = native.REDUCE_OPS  # single source of op names

#: worker count of :meth:`CollectiveEngine.async_pool` — the hard
#: ceiling on concurrently RUNNING caller-level async ops per engine.
#: Callers that keep windows of handles in flight (pipeline prefetch,
#: bucket pipelines) must bound them below this number or queued sends
#: can starve behind blocked recvs; ``parallel/pp.py`` asserts its
#: window against this constant at plan-validation time, and the
#: kf-verify protocol checker (``analysis/protoverify.py``) re-derives
#: the bound statically.
ASYNC_POOL_WORKERS = 8

#: Static protocol metadata for every public wire op of
#: :class:`CollectiveEngine` — the declarative issue-site table the
#: kf-verify abstract interpreter (``analysis/commgraph.py``) extracts
#: comm sequences from.  MUST stay a pure literal dict: the analysis
#: layer reads it via ``ast.literal_eval`` without importing this
#: module (kflint runs in bare CI images with no numpy/jax).
#:
#: Per op: ``kind`` ("collective" = group rendezvous over every engine
#: peer; "p2p-send"/"p2p-recv" = point-to-point toward the rank in the
#: first positional arg), ``group`` (the membership axis a collective
#: rendezvouses over), ``tag`` (how the caller ``name`` becomes the
#: wire rendezvous tag; ``{name}`` is the caller's argument), and
#: ``blocking`` (False = returns a :class:`CollectiveHandle`; the
#: wait/fence discipline is checked by handle-discipline and the
#: kf-verify wait-for-graph pass).  ``analysis/protoverify.py``
#: cross-checks this table against the actual method defs both ways,
#: so drift (new wire op without metadata, metadata for a removed op)
#: is a lint finding, not silent rot.
COMM_OP_SPECS = {
    "all_reduce":          {"kind": "collective", "group": "world",
                            "tag": "{name}", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "broadcast":           {"kind": "collective", "group": "world",
                            "tag": "{name}", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "reduce":              {"kind": "collective", "group": "world",
                            "tag": "{name}.r", "blocking": True,
                            "name_pos": 3, "peer_pos": None},
    "gather":              {"kind": "collective", "group": "world",
                            "tag": "{name}.g", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "all_gather":          {"kind": "collective", "group": "world",
                            "tag": "{name}.ag", "blocking": True,
                            "name_pos": 1, "peer_pos": None},
    "reduce_scatter":      {"kind": "collective", "group": "world",
                            "tag": "{name}.rs", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "local_reduce":        {"kind": "collective", "group": "slice",
                            "tag": "{name}.lr", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "local_broadcast":     {"kind": "collective", "group": "slice",
                            "tag": "{name}.lb", "blocking": True,
                            "name_pos": 1, "peer_pos": None},
    "cross_all_reduce":    {"kind": "collective", "group": "cross",
                            "tag": "{name}.x", "blocking": True,
                            "name_pos": 2, "peer_pos": None},
    "send_to":             {"kind": "p2p-send", "group": "pair",
                            "tag": "{name}", "blocking": True,
                            "name_pos": 2, "peer_pos": 0},
    "recv_from":           {"kind": "p2p-recv", "group": "pair",
                            "tag": "{name}", "blocking": True,
                            "name_pos": 1, "peer_pos": 0},
    "send_async":          {"kind": "p2p-send", "group": "pair",
                            "tag": "{name}", "blocking": False,
                            "name_pos": 2, "peer_pos": 0},
    "recv_async":          {"kind": "p2p-recv", "group": "pair",
                            "tag": "{name}", "blocking": False,
                            "name_pos": 1, "peer_pos": 0},
    "all_reduce_async":    {"kind": "collective", "group": "world",
                            "tag": "{name}", "blocking": False,
                            "name_pos": 2, "peer_pos": None},
    "reduce_scatter_async": {"kind": "collective", "group": "world",
                             "tag": "{name}.rs", "blocking": False,
                             "name_pos": 2, "peer_pos": None},
    "all_gather_async":    {"kind": "collective", "group": "world",
                            "tag": "{name}.ag", "blocking": False,
                            "name_pos": 1, "peer_pos": None},
}


def build_strategy_graphs(
    strategy: Strategy, peers: PeerList
) -> List[Tuple[Graph, Graph]]:
    """Generate the (reduce, broadcast) graph pairs for a strategy over the
    given peer list (reference ``session/strategy.go:90-174``)."""
    n = len(peers)
    host_ranks = list(peers.partition_by_host().values())
    if strategy == Strategy.AUTO:
        strategy = auto_select(len(host_ranks))
    if strategy == Strategy.STAR:
        return [gen_star(n)]
    if strategy == Strategy.MULTI_STAR:
        return gen_multi_star(n, host_ranks)
    if strategy == Strategy.RING:
        return [gen_circular_graph_pair(n, shift=s) for s in range(n)]
    if strategy == Strategy.CLIQUE:
        return gen_clique(n)
    if strategy == Strategy.TREE:
        return [gen_tree(n, host_ranks)]
    if strategy == Strategy.BINARY_TREE:
        return [gen_binary_tree(n)]
    if strategy == Strategy.BINARY_TREE_STAR:
        return [gen_binary_tree_star(n, host_ranks)]
    if strategy == Strategy.MULTI_BINARY_TREE_STAR:
        return gen_multi_binary_tree_star(n, host_ranks)
    raise ValueError(f"unhandled strategy {strategy}")


def build_cross_strategy_graphs(
    strategy: Strategy, peers: PeerList
) -> List[Tuple[Graph, Graph]]:
    """Cross-host-stage strategies for hierarchical allreduce (reference
    ``session/strategy.go:188-210`` genCrossStrategyList): RING runs ring
    rotations over the local masters; every other strategy runs one
    binary tree over them."""
    n = len(peers)
    masters = [ranks[0] for ranks in peers.partition_by_host().values() if ranks]
    if strategy == Strategy.RING:
        return gen_cross_ring_pairs(n, masters)
    return gen_cross_binary_tree(n, masters)


def name_based_hash(name: str) -> int:
    """Name-based chunk→strategy hash (reference ``shard.go:17-23``): all
    chunks of one tensor share a strategy keyed by its name, balancing
    load across *tensors* instead of across chunks."""
    return sum(ord(c) * ord(c) for c in name)


# -- async collective plane (kf-overlap) -----------------------------------
#: process-wide in-flight accounting behind the ``kf_overlap_inflight``
#: gauge: in-process multi-rank clusters (every chaos/overlap test) run
#: several engines in one registry, so the gauge is the SUM of their
#: windows — "returned to 0" then means no rank leaked a handle
_inflight_lock = threading.Lock()
_inflight_total = 0

#: observed-at-wait hidden-wire fraction buckets (a ratio in [0, 1],
#: not a latency — the default latency buckets would collapse it)
_EFFICIENCY_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


def _inflight_adjust(delta: int) -> int:
    global _inflight_total
    with _inflight_lock:
        _inflight_total += delta
        total = _inflight_total
        # set INSIDE the lock: Gauge is last-write-wins, and two
        # concurrent completions setting 1-then-0 out of order would
        # strand the gauge nonzero after a full drain — the exact value
        # the demos and chaos tests assert returns to 0
        REGISTRY.gauge("kf_overlap_inflight").set(total)
    return total


class CollectiveHandle:
    """A collective in flight: issued now, settled at :meth:`wait`.

    The completion contract mirrors the sync path exactly — whatever the
    collective would have raised inline (typed
    :class:`~kungfu_tpu.comm.faults.PeerFailureError` with the suspect
    rank attached, an injected chaos death, a protocol error) is raised
    at :meth:`wait` instead of hanging; the per-peer deadline machinery
    runs inside the collective, so a handle always settles in bounded
    time even when a peer silently dies mid-flight.

    Lifetime discipline (enforced by the ``handle-discipline`` kflint
    rule): every handle is waited on every control-flow path, never
    dropped, and never held across a membership change —
    :meth:`CollectiveEngine.drain_async` fences the window at
    resize/shrink boundaries."""

    __slots__ = ("tag", "op", "nbytes", "_event", "_result", "_error",
                 "_t_issue", "_t_complete", "_observed")

    def __init__(self, tag: str, op: str, nbytes: int):
        self.tag = tag
        self.op = op
        self.nbytes = nbytes
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._t_issue = time.perf_counter()
        self._t_complete: Optional[float] = None
        self._observed = False

    # -- issuer side ------------------------------------------------------
    def _settle(self, result=None, error: Optional[BaseException] = None):
        self._t_complete = time.perf_counter()
        self._result = result
        self._error = error
        self._event.set()

    # -- owner side -------------------------------------------------------
    def done(self) -> bool:
        """True once the collective settled (successfully or not)."""
        return self._event.is_set()

    def error(self) -> Optional[BaseException]:
        """The settled failure, or None (not yet settled / succeeded)."""
        return self._error

    def wait(self, timeout: Optional[float] = None):
        """Block until the collective settles; return its result or
        re-raise its typed failure.  Observes the hidden-wire fraction
        into ``kf_overlap_efficiency`` on first call: 1.0 = the wire
        time was fully hidden under the caller's compute."""
        t_wait = time.perf_counter()
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"handle {self.tag!r} not complete after {timeout}s "
                "(the collective's own deadline machinery should settle "
                "it; is KF_CONFIG_PEER_DEADLINE larger than this wait?)")
        if self._error is not None:
            # no efficiency observation for a failed collective: a
            # doomed handle waited on late would record hidden≈1.0 —
            # "wire fully hidden" for a transfer that delivered nothing
            # — skewing the histogram toward 1.0 during fault storms,
            # exactly when operators read it
            raise self._error
        if not self._observed:
            self._observed = True
            wire = (self._t_complete or t_wait) - self._t_issue
            hidden = 1.0 if wire <= 0 else max(
                0.0, min(1.0, (t_wait - self._t_issue) / wire))
            REGISTRY.histogram(
                "kf_overlap_efficiency", buckets=_EFFICIENCY_BUCKETS
            ).observe(hidden)
        return self._result


class CollectiveEngine:
    """Executes graph collectives for one peer over its host channel."""

    def __init__(
        self,
        channel: HostChannel,
        peers: PeerList,
        strategy: Strategy = Strategy.AUTO,
        chaos_rank=_CHAOS_RANK_UNSET,
    ):
        self.channel = channel
        self.peers = peers
        self.rank = peers.rank(channel.self_id)
        if self.rank is None:
            raise ValueError(f"{channel.self_id} not in {peers}")
        self.strategy = strategy
        self._graphs = build_strategy_graphs(strategy, peers)
        self._cross_graphs = build_cross_strategy_graphs(strategy, peers)
        # derived from the shared peer list → identical on every peer
        self._colocated = len(peers.hosts()) <= 1
        # chunk→strategy hash mode (reference shard.go:25-31); read once at
        # engine construction, like the reference reads config at init
        import os

        self._hash_name_based = (
            os.environ.get(envs.STRATEGY_HASH_METHOD, "").strip().upper() == "NAME"
        )
        #: fault injection (None unless KF_CHAOS_SPEC is set — the hot
        #: path pays one attribute load + branch when disabled).
        #: ``chaos_rank`` is the process's STABLE identity (its bootstrap
        #: rank, Peer.chaos_rank()): a shrink promotes survivor ranks, and
        #: a rank-scoped fault clause must not re-target the promoted
        #: survivor of the very failure it injected.  An explicit ``None``
        #: (a late joiner with no bootstrap rank) selects the rank-less
        #: controller, matching every other chaos hook for that process;
        #: engines built directly (tests) default to the current rank.
        self._chaos = _chaos_controller_for(
            self.rank if chaos_rank is _CHAOS_RANK_UNSET else chaos_rank
        )
        #: identity stamped on timeline events: the STABLE bootstrap rank
        #: when the owner supplied one (a shrink renumbers self.rank on
        #: the rebuilt engine, and a merged kftrace timeline must keep
        #: one track per process — a renumbered survivor would otherwise
        #: alias a pre-shrink peer's track); engines built directly
        #: (tests, no resize in play) use the live rank
        self._timeline_rank = (
            chaos_rank
            if chaos_rank is not _CHAOS_RANK_UNSET and chaos_rank is not None
            else self.rank
        )
        #: resolved once — _send/_recv run per chunk per peer, and a
        #: per-call env parse on that path is measurable noise (engines
        #: are rebuilt each mesh epoch, so retuning still lands)
        self._peer_deadline = peer_deadline_s()
        #: resolved once for the same reason: _begin_collective runs on
        #: every public collective, and the registry lookup is a lock +
        #: dict hash it doesn't need to repay per call
        self._coll_counter = REGISTRY.counter("kf_engine_collectives_total")
        self._seq = 0
        self._lock = threading.Lock()
        self._stats_lock = threading.Lock()  # guards stats/_window swaps
        self._peers_csv = ",".join(str(p) for p in peers)
        self._graph_ser: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        from concurrent.futures import ThreadPoolExecutor

        from kungfu_tpu.comm.host import host_pool_size

        # sender/chunk pool scaled with peer count (floor 8 preserves
        # the measured chunk-pipelining win on small clusters; wider
        # worlds get up to KF_CONFIG_HOST_POOL_MAX concurrent chunks)
        self._pool = ThreadPoolExecutor(
            max_workers=host_pool_size(len(peers), floor=8, pool="engine"),
            thread_name_prefix="kf-engine",
        )
        self._async_pool: Optional[ThreadPoolExecutor] = None
        # per-strategy-pair accounting for adaptation: cumulative
        # (bytes, seconds), a recent window (reset on throughputs()), and
        # the best window rate ever observed (the reference compares recent
        # throughput against the recorded best, adaptiveStrategies.go)
        self.stats = [[0, 0.0] for _ in self._graphs]
        self._window = [[0, 0.0] for _ in self._graphs]
        self.best_throughputs = [0.0 for _ in self._graphs]
        # swap-eligibility epoch (kf-adapt): collectives executed since
        # the last strategy swap — the bandit driver refuses to judge an
        # arm that has not carried real traffic yet (mark_swap resets)
        self._colls_total = 0
        self._colls_at_swap = 0
        # kf-overlap: the bounded in-flight window for async handles.
        # A plain count + condition (not a Semaphore) so the depth can
        # be retuned live (set_overlap_depth) without rebuilding
        self._overlap_depth = overlap_depth_default()
        self._overlap_cond = threading.Condition()
        self._inflight_handles: set = set()
        #: ``fn(nbytes, depth, seconds)`` per completed async collective
        #: — the kf-adapt latency feed (None = disabled)
        self._latency_hook = None

    # -- public collectives ----------------------------------------------
    def all_reduce(
        self, x: np.ndarray, op: str = "sum", name: str = "", record: bool = True,
        inplace: bool = False,
    ) -> np.ndarray:
        """Chunked graph allreduce (reference ``allreduce.go:11`` +
        ``runStrategies``).  ``record=False`` keeps control-plane traffic
        (e.g. interference votes) out of the throughput window so the
        adaptation signal only sees data-plane transfers.

        ``inplace=True`` reduces directly in ``x``'s buffer and returns
        ``x`` — skips one full defensive copy, the NCCL in-place
        allreduce analog; the input values are clobbered.  The contract
        is honored for ANY writable ndarray (a non-contiguous view pays
        a staging copy but still receives the result); a read-only input
        raises instead of silently downgrading."""
        if op not in REDUCE_OPS and op != "mean":
            raise ValueError(f"op {op!r}")
        self._begin_collective(name or "all_reduce")
        eff_op = "sum" if op == "mean" else op
        if inplace and not x.flags["WRITEABLE"]:
            raise ValueError("inplace=True requires a writable array")
        orig = x
        x = np.ascontiguousarray(x)
        flat = x.reshape(-1)
        tag = name or f"ar{self._next_seq()}"
        with timeline.span(
            "collective", f"engine.all_reduce[{flat.nbytes}B]",
            rank=self._timeline_rank, op="all_reduce", tag=tag, nbytes=flat.nbytes,
            trace=self._trace_id("all_reduce", tag),
        ):
            out = self._run_over_graphs(
                flat, eff_op, tag, self._graphs, record=record, inplace=inplace
            )
        out = out.reshape(x.shape)
        if op == "mean":
            out = np.divide(out, len(self.peers), out=out if inplace else None)
        if inplace:
            # the Python fallback, a mean divide, or a non-contiguous
            # staging copy may have produced a fresh array — the inplace
            # contract says the CALLER's buffer holds the result either way
            if not np.shares_memory(out, orig):
                np.copyto(orig, out)
            return orig
        return out

    def _begin_collective(self, tag: str) -> None:
        """Entry hook of every public collective: ticks the unified
        collective counter (the live plane's per-push rate source) and
        advances the injector's ``coll`` counter — ``die:coll=N`` means
        the Nth engine collective of any kind, so an experiment against
        a loop that opens with a parameter broadcast still dies where
        the spec says."""
        self._coll_counter.inc()
        with self._stats_lock:
            self._colls_total += 1
        if self._chaos is not None:
            self._chaos.on_collective(tag)

    def _trace_id(self, op: str, tag: str) -> str:
        """kf-xray derived cross-rank trace id: every participant
        computes the identical id from the cluster version (the
        channel's epoch token), the current step, and the collective's
        op/tag — the same logical collective links across ranks in a
        merged trace with no extra wire bytes (docs/xray.md)."""
        return timeline.collective_trace_id(
            getattr(self.channel, "token", 0), timeline.current_step(),
            op, tag)

    def broadcast(self, x: np.ndarray, root: int = 0, name: str = "") -> np.ndarray:
        self._begin_collective(name or "broadcast")
        with self._lock:
            seq = self._seq
            self._seq += 1
        tag = name or f"bc{seq}"
        _, bcast_g = gen_star(len(self.peers), center=root)
        flat = np.ascontiguousarray(x).reshape(-1)
        with timeline.span(
            "collective", "engine.broadcast", rank=self._timeline_rank,
            op="broadcast", tag=tag, nbytes=flat.nbytes,
            trace=self._trace_id("broadcast", tag),
        ):
            out = self._run_bcast(flat.copy(), f"{tag}", bcast_g)
        return out.reshape(x.shape)

    def reduce(self, x: np.ndarray, root: int = 0, op: str = "sum", name: str = "") -> np.ndarray:
        """Reduce to ``root`` (reference ``session.go:157-161``): only the
        root returns the reduced value; other ranks get their input back."""
        self._begin_collective(name or "reduce")
        tag = (name or f"rd{self._next_seq()}") + ".r"
        flat = np.ascontiguousarray(x).reshape(-1)
        eff_op = "sum" if op == "mean" else op
        reduce_g, _ = gen_star(len(self.peers), center=root)
        me = self.rank
        acc = flat.copy()
        with timeline.span("collective", "engine.reduce", rank=self._timeline_rank,
                           op="reduce", tag=tag, nbytes=flat.nbytes,
                           trace=self._trace_id("reduce", tag)):
            for prev in reduce_g.prevs(me):
                data = np.frombuffer(self._recv(prev, tag), dtype=flat.dtype)
                acc = native.transform2(acc, data, eff_op)
            for nxt in reduce_g.nexts(me):
                self._send(nxt, tag, acc.tobytes())
        if me == root and op == "mean":
            acc = acc / len(self.peers)
        return acc.reshape(x.shape) if me == root else x

    def gather(self, x: np.ndarray, root: int = 0, name: str = "") -> Optional[np.ndarray]:
        """Root returns [n, ...] stacked in rank order; others None
        (reference gathers to rank 0, ``session.go:189-211``)."""
        self._begin_collective(name or "gather")
        tag = (name or f"ga{self._next_seq()}") + ".g"
        flat = np.ascontiguousarray(x).reshape(-1)
        with timeline.span("collective", "engine.gather", rank=self._timeline_rank,
                           op="gather", tag=tag, nbytes=flat.nbytes,
                           trace=self._trace_id("gather", tag)):
            if self.rank == root:
                parts = []
                for r in range(len(self.peers)):
                    if r == root:
                        parts.append(flat)
                    else:
                        parts.append(
                            np.frombuffer(self._recv(r, tag), dtype=flat.dtype)
                        )
                return np.stack(parts).reshape((len(self.peers),) + x.shape)
            self._send(root, tag, flat.tobytes())
            return None

    def all_gather(self, x: np.ndarray, name: str = "") -> np.ndarray:
        """Direct full-exchange (reference ``allgather.go:17-45``): every
        peer sends to every other; returns [n, ...] in rank order."""
        self._begin_collective(name or "all_gather")
        tag = (name or f"ag{self._next_seq()}") + ".ag"
        flat = np.ascontiguousarray(x).reshape(-1)
        me = self.rank
        with timeline.span("collective", "engine.all_gather", rank=self._timeline_rank,
                           op="all_gather", tag=tag, nbytes=flat.nbytes,
                           trace=self._trace_id("all_gather", tag)):
            for r in range(len(self.peers)):
                if r != me:
                    self._send(r, tag, flat.tobytes())
            parts = []
            for r in range(len(self.peers)):
                if r == me:
                    parts.append(flat)
                else:
                    parts.append(
                        np.frombuffer(self._recv(r, tag), dtype=flat.dtype)
                    )
        return np.stack(parts).reshape((len(self.peers),) + x.shape)

    def reduce_scatter(self, x: np.ndarray, op: str = "sum",
                       name: str = "") -> np.ndarray:
        """Reduce-scatter over the host plane: every rank contributes a
        full flat buffer and receives the 1/n chunk it owns (rank-major,
        zero-padded to ``n * chunk``) reduced across all ranks.  Direct
        exchange: each rank sends every OTHER rank that rank's chunk of
        its local buffer — per-rank wire volume ``(n-1)/n`` of the
        buffer, the bandwidth-optimal half of an allreduce, and the
        host-plane analog of the ZeRO-2 gradient collective
        (:meth:`kungfu_tpu.comm.device.Communicator.reduce_scatter`)."""
        if op not in REDUCE_OPS and op != "mean":
            raise ValueError(f"op {op!r}")
        self._begin_collective(name or "reduce_scatter")
        eff_op = "sum" if op == "mean" else op
        tag = (name or f"rs{self._next_seq()}") + ".rs"
        flat = np.ascontiguousarray(x).reshape(-1)
        n = len(self.peers)
        me = self.rank
        chunk = -(-flat.shape[0] // n) if flat.shape[0] else 0
        padded = np.zeros((chunk * n,), flat.dtype)
        padded[: flat.shape[0]] = flat
        with timeline.span(
            "collective", f"engine.reduce_scatter[{flat.nbytes}B]",
            rank=self._timeline_rank, op="reduce_scatter", tag=tag,
            nbytes=flat.nbytes, trace=self._trace_id("reduce_scatter", tag),
        ):
            for r in range(n):
                if r != me:
                    self._send(
                        r, f"{tag}.{r}",
                        padded[r * chunk:(r + 1) * chunk].tobytes())
            acc = padded[me * chunk:(me + 1) * chunk].copy()
            for r in range(n):
                if r == me:
                    continue
                data = np.frombuffer(
                    self._recv(r, f"{tag}.{me}"), dtype=flat.dtype)
                acc = native.transform2(acc, data, eff_op)
        if op == "mean":
            acc = acc / n
        return acc

    # -- point-to-point (kf-pipeline) --------------------------------------
    def send_to(self, rank: int, data, name: str) -> int:
        """Deadline-bounded point-to-point send to ``rank`` on the
        engine's wire (same retry/deadline/chaos machinery as the
        collective sends — a dead receiver raises typed
        :class:`PeerFailureError` naming the suspect instead of riding
        the channel's full connect ladder).  ``data`` is an ndarray or
        bytes; returns the payload size.  The pipeline-parallel
        activation hop (``parallel/pp.py``) — NOT a collective: it does
        not tick the collective counter and ``die:coll=N`` clauses do
        not count it (``delay``/``die`` send-scoped clauses still fire
        inside ``_send``)."""
        if isinstance(data, np.ndarray):
            payload = np.ascontiguousarray(data).tobytes()
        else:
            payload = bytes(data)
        with timeline.span(
            "collective", f"engine.send[{len(payload)}B]",
            rank=self._timeline_rank, op="send", tag=name,
            nbytes=len(payload),
            # op "p2p" on BOTH halves: sender and receiver must derive
            # the IDENTICAL trace id or the hop never forms a
            # cross-rank causal edge in a merged trace
            trace=self._trace_id("p2p", name),
        ):
            self._send(rank, name, payload)
        return len(payload)

    def recv_from(self, rank: int, name: str, dtype=None, shape=None):
        """Deadline-bounded point-to-point receive from ``rank``.
        Returns raw bytes, or an ndarray when ``dtype`` is given
        (reshaped to ``shape`` when that is too).  Timeouts surface as
        typed :class:`PeerFailureError` with the suspect rank — the
        same contract as every collective recv."""
        with timeline.span(
            "collective", "engine.recv", rank=self._timeline_rank,
            op="recv", tag=name, nbytes=0,
            trace=self._trace_id("p2p", name),
        ):
            data = self._recv(rank, name)
        if dtype is None:
            return data
        out = np.frombuffer(data, dtype=dtype)
        return out.reshape(shape) if shape is not None else out

    def send_async(self, rank: int, data, name: str) -> CollectiveHandle:
        """Issue a point-to-point send and return immediately with a
        :class:`CollectiveHandle` (kf-pipeline: the 1F1B activation
        hop rides the async plane so the wire hides under stage
        compute).  The tag is fixed HERE, at issue time on the calling
        thread — the ``handle-discipline`` lint polices the handle's
        lifetime exactly like the async collectives'."""
        nbytes = data.nbytes if hasattr(data, "nbytes") else len(data)
        return self._issue_async(
            "send", name, nbytes, lambda: self.send_to(rank, data, name))

    def recv_async(self, rank: int, name: str, dtype=None,
                   shape=None) -> CollectiveHandle:
        """Issue a point-to-point receive; the payload (and any typed
        failure) surfaces at ``handle.wait()``.  The 1F1B prefetch
        primitive: posting the recv one op early hides the DCN hop
        under the current microbatch's compute.

        Each in-flight async op occupies one async-pool slot until it
        settles; callers owning MANY handles (a pipeline schedule)
        must bound their outstanding set below the pool size — see
        ``parallel/pp.py``'s prefetch discipline."""
        return self._issue_async(
            "recv", name, 0,
            lambda: self.recv_from(rank, name, dtype=dtype, shape=shape))

    # -- async collectives (kf-overlap) ------------------------------------
    def all_reduce_async(self, x: np.ndarray, op: str = "sum",
                         name: str = "", record: bool = True
                         ) -> CollectiveHandle:
        """Issue a chunked graph allreduce and return immediately with a
        :class:`CollectiveHandle`; the result (and any typed failure)
        surfaces at ``handle.wait()``.  The wire protocol is identical
        to :meth:`all_reduce` — the tag is fixed HERE, in issue order on
        the calling thread, so peers mixing sync and async issue styles
        still rendezvous."""
        tag = name or f"ar{self._next_seq()}"
        nbytes = np.asarray(x).nbytes
        return self._issue_async(
            "all_reduce", tag, nbytes,
            lambda: self.all_reduce(x, op=op, name=tag, record=record))

    def reduce_scatter_async(self, x: np.ndarray, op: str = "sum",
                             name: str = "") -> CollectiveHandle:
        """Async :meth:`reduce_scatter` — the ZeRO-2/3 gradient-bucket
        pipeline primitive (``parallel/zero.py::host_bucket_pipeline``
        issues bucket i+1 here while bucket i's optimizer math runs)."""
        base = name or f"rs{self._next_seq()}"
        nbytes = np.asarray(x).nbytes
        return self._issue_async(
            "reduce_scatter", base, nbytes,
            lambda: self.reduce_scatter(x, op=op, name=base))

    def all_gather_async(self, x: np.ndarray, name: str = ""
                         ) -> CollectiveHandle:
        """Async :meth:`all_gather` — the ZeRO-3 parameter-bucket
        prefetch primitive."""
        base = name or f"ag{self._next_seq()}"
        nbytes = np.asarray(x).nbytes
        return self._issue_async(
            "all_gather", base, nbytes, lambda: self.all_gather(x, name=base))

    def _issue_async(self, op: str, tag: str, nbytes: int,
                     fn) -> CollectiveHandle:
        """Admit one collective into the bounded in-flight window and
        run it on the async pool.  Blocks while ``overlap_depth`` handles
        are already in flight (completion — success OR typed failure —
        releases a slot; a slot is never released by ``wait()``, so an
        unwaited handle cannot deadlock the window)."""
        pool = self.async_pool()
        with self._overlap_cond:
            while len(self._inflight_handles) >= self._overlap_depth:
                self._overlap_cond.wait()
            handle = CollectiveHandle(tag, op, nbytes)
            self._inflight_handles.add(handle)
            depth_now = len(self._inflight_handles)
        total = _inflight_adjust(+1)
        if timeline.enabled():
            timeline.event("overlap", "issue", rank=self._timeline_rank,
                           op=op, tag=tag, nbytes=nbytes,
                           inflight=depth_now, inflight_total=total)

        def run():
            err = None
            t0 = time.perf_counter()
            try:
                out = fn()
            except BaseException as e:  # noqa: BLE001 - settled at wait()
                err = e
                out = None
            dt = time.perf_counter() - t0
            # one critical section for the whole completion: gauge
            # decrement, window removal, settle, notify.  Ordering races
            # on either side otherwise — a drainer waking on the empty
            # set must find the handle already settled (the chaos tests
            # read hb.error() right after a drain), and a waiter woken
            # by _settle must find the gauge already decremented (the
            # demos assert it reads 0 the moment every wait returned).
            # Lock nesting is cond → _inflight_lock only, never the
            # reverse — no cycle.
            with self._overlap_cond:
                total_now = _inflight_adjust(-1)
                self._inflight_handles.discard(handle)
                left = len(self._inflight_handles)
                handle._settle(out, err)
                self._overlap_cond.notify_all()
            if timeline.enabled():
                timeline.event(
                    "overlap", "complete", rank=self._timeline_rank,
                    op=op, tag=tag, nbytes=nbytes, inflight=left,
                    inflight_total=total_now, dur=round(dt, 6),
                    error=type(err).__name__ if err is not None else None)
            hook = self._latency_hook
            if hook is not None and err is None:
                try:
                    hook(nbytes, self._overlap_depth, dt)
                except Exception:  # noqa: BLE001 - observability only
                    _log.exception("overlap latency hook failed")

        pool.submit(run)
        return handle

    @property
    def overlap_depth(self) -> int:
        """The in-flight window bound currently in force."""
        return self._overlap_depth

    def set_overlap_depth(self, depth: int) -> None:
        """Retune the in-flight window live.  Safe mid-flight: shrinking
        only delays FUTURE issues (already-issued handles finish), and
        growth wakes blocked issuers immediately.  Local backpressure
        only — never part of the wire protocol, so no fence is needed."""
        if depth < 1:
            raise ValueError(f"overlap depth must be >= 1, got {depth}")
        with self._overlap_cond:
            self._overlap_depth = int(depth)
            self._overlap_cond.notify_all()

    def inflight(self) -> int:
        """Issued-and-unsettled handle count on THIS engine."""
        with self._overlap_cond:
            return len(self._inflight_handles)

    def drain_async(self, timeout: Optional[float] = None) -> int:
        """Block until every in-flight handle settles; returns how many
        were drained.  THE membership fence: a handle may never cross a
        resize/shrink (its tags and peer set belong to the old epoch),
        so ``Peer._propose`` and the shrink ladder drain here first.
        Settling is deadline-bounded by construction (every send/recv
        inside a collective runs under the per-peer deadline), so a
        bare drain cannot hang on a dead peer — it observes the typed
        failure and moves on; the failure still belongs to the handle's
        owner and re-raises at that handle's ``wait()``."""
        deadline = (time.monotonic() + timeout) if timeout is not None else None
        with self._overlap_cond:
            drained = len(self._inflight_handles)
            while self._inflight_handles:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"{len(self._inflight_handles)} async handle(s) "
                            f"still in flight after {timeout}s drain")
                self._overlap_cond.wait(remaining)
        return drained

    def set_latency_hook(self, fn) -> None:
        """Install ``fn(nbytes, depth, seconds)`` to receive each
        completed async collective's measured wall time — the kf-adapt
        feed that makes the overlap depth a learnable arm
        (:class:`kungfu_tpu.policy.bandit.OverlapDepthBandit`).  Pass
        ``None`` to disable."""
        self._latency_hook = fn

    # -- hierarchical (host-partitioned) collectives ----------------------
    # Local = peers sharing this peer's host; the local root is the
    # lowest-global-rank peer on each host (reference local masters).
    def _local_ranks(self) -> List[int]:
        host = self.peers[self.rank].host
        return [r for r, p in enumerate(self.peers) if p.host == host]

    def _local_roots(self) -> List[int]:
        seen = {}
        for r, p in enumerate(self.peers):
            seen.setdefault(p.host, r)
        return sorted(seen.values())

    def _subset_reduce(self, flat, ranks: List[int], root: int, op: str, tag: str):
        """Star-reduce over a rank subset; result lands on ``root``."""
        me = self.rank
        acc = flat.copy()
        if me == root:
            for r in ranks:
                if r != root:
                    data = np.frombuffer(self._recv(r, tag), dtype=flat.dtype)
                    acc = native.transform2(acc, data, op)
        else:
            self._send(root, tag, flat.tobytes())
        return acc

    def _subset_bcast(self, flat, ranks: List[int], root: int, tag: str):
        me = self.rank
        if me == root:
            for r in ranks:
                if r != root:
                    self._send(r, tag, flat.tobytes())
            return flat
        return np.frombuffer(self._recv(root, tag), dtype=flat.dtype).copy()

    def local_reduce(self, x: np.ndarray, op: str = "sum", name: str = "") -> np.ndarray:
        """Reduce among same-host peers; result on the local root
        (reference ``LocalReduce``).  Non-roots get their input back."""
        self._begin_collective(name or "local_reduce")
        tag = (name or f"lr{self._next_seq()}") + ".lr"
        flat = np.ascontiguousarray(x).reshape(-1)
        ranks = self._local_ranks()
        root = min(ranks)
        with timeline.span("collective", "engine.local_reduce",
                           rank=self._timeline_rank, op="local_reduce", tag=tag,
                           nbytes=flat.nbytes,
                           trace=self._trace_id("local_reduce", tag)):
            acc = self._subset_reduce(
                flat, ranks, root, "sum" if op == "mean" else op, tag)
        if self.rank == root:
            if op == "mean":
                acc = acc / len(ranks)
            return acc.reshape(x.shape)
        return x

    def local_broadcast(self, x: np.ndarray, name: str = "") -> np.ndarray:
        """Broadcast from the local root to same-host peers."""
        self._begin_collective(name or "local_broadcast")
        tag = (name or f"lb{self._next_seq()}") + ".lb"
        flat = np.ascontiguousarray(x).reshape(-1)
        ranks = self._local_ranks()
        with timeline.span("collective", "engine.local_broadcast",
                           rank=self._timeline_rank, op="local_broadcast", tag=tag,
                           nbytes=flat.nbytes,
                           trace=self._trace_id("local_broadcast", tag)):
            out = self._subset_bcast(flat, ranks, min(ranks), tag)
        return out.reshape(x.shape)

    def cross_all_reduce(self, x: np.ndarray, op: str = "sum", name: str = "") -> np.ndarray:
        """Hierarchical allreduce (reference ``allreduce.go:38``
        CrossAllReduce + the ScheduledHierarchical pattern): local reduce
        to the host roots, allreduce among roots, local broadcast."""
        self._begin_collective(name or "cross_all_reduce")
        base = name or f"xa{self._next_seq()}"
        eff_op = "sum" if op == "mean" else op
        flat = np.ascontiguousarray(x).reshape(-1)
        local = self._local_ranks()
        local_root = min(local)
        roots = self._local_roots()
        with timeline.span(
            "collective", "engine.cross_all_reduce", rank=self._timeline_rank,
            op="cross_all_reduce", tag=base, nbytes=flat.nbytes,
            trace=self._trace_id("cross_all_reduce", base),
        ):
            acc = self._subset_reduce(
                flat, local, local_root, eff_op, base + ".lr")
            if self.rank == local_root and len(roots) > 1:
                # allreduce among the host roots via the cross-stage
                # strategy graphs (ring rotations or binary tree over the
                # masters, reference strategy.go:188-210), chunked like
                # the global path
                acc = self._run_over_graphs(
                    np.ascontiguousarray(acc), eff_op, base + ".x",
                    self._cross_graphs,
                )
            acc = self._subset_bcast(acc, local, local_root, base + ".lb")
        if op == "mean":
            acc = acc / len(self.peers)
        return acc.reshape(x.shape)

    def _run_over_graphs(
        self,
        flat: np.ndarray,
        op: str,
        tag: str,
        graphs: List[Tuple[Graph, Graph]],
        record: bool = False,
        inplace: bool = False,
    ) -> np.ndarray:
        """The runStrategies core (reference ``session.go:292-321``):
        chunk ``flat``, hash each chunk onto a graph pair, run the pairs
        concurrently.  ``record`` feeds the per-strategy throughput stats
        (only meaningful for the global strategy list, whose indices the
        stats arrays are keyed by).

        When the channel is native and the dtype/op have native kernels,
        the whole loop — chunk split, hash, recv/accumulate/send — runs in
        C++ (one ctypes crossing per collective, transport.cpp
        kf_engine_all_reduce); the Python pool below is the fallback and
        the reference implementation of the same wire protocol."""
        out = self._native_run(flat, op, tag, graphs, record, inplace=inplace)
        if out is not None:
            return out
        chunks = self._split(flat)
        outs: List[Optional[np.ndarray]] = [None] * len(chunks)
        errs: List[BaseException] = []

        def run_chunk(i: int, chunk: np.ndarray):
            gi = self._choose(i, tag, len(graphs))
            reduce_g, bcast_g = graphs[gi]
            t0 = time.perf_counter()
            try:
                outs[i] = self._run_graphs(chunk, op, f"{tag}.c{i}", reduce_g, bcast_g)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)
                return
            if record:
                dt = time.perf_counter() - t0
                with self._stats_lock:
                    st = self.stats[gi]
                    st[0] += chunk.nbytes
                    st[1] += dt
                    w = self._window[gi]
                    w[0] += chunk.nbytes
                    w[1] += dt

        if len(chunks) == 1:
            run_chunk(0, chunks[0])
        else:
            futures = [
                self._pool.submit(run_chunk, i, c) for i, c in enumerate(chunks)
            ]
            for f in futures:
                f.result()
        if errs:
            raise errs[0]
        return np.concatenate(outs) if len(outs) > 1 else outs[0]

    def _next_seq(self) -> int:
        with self._lock:
            seq = self._seq
            self._seq += 1
        return seq

    # -- native executor delegation ---------------------------------------
    def _native_run(
        self, flat, op, tag, graphs, record, inplace: bool = False
    ) -> Optional[np.ndarray]:
        """Run the collective in the C++ executor when possible; None =
        caller should use the Python path."""
        import os

        if os.environ.get("KF_NATIVE_ENGINE", "1").lower() in ("0", "false", "no"):
            return None
        if self._chaos is not None:
            # fault injection lives in the Python send/recv wrappers; the
            # C++ executor would bypass every hook, so a chaos run pins
            # the reference Python path (and stays deterministic)
            return None
        t = getattr(self.channel, "_t", None)  # NativeHostChannel only
        if t is None or not hasattr(t, "engine_all_reduce"):
            return None
        code = native._DTYPE_CODES.get(flat.dtype)
        opc = native._OP_CODES.get(op)
        if code is None or opc is None:
            return None
        key = id(graphs)
        ser = self._graph_ser.get(key)
        if ser is None:
            ser = self._graph_ser[key] = self._serialize_graphs(graphs)
        data, offsets = ser
        # reduced in place; the defensive copy preserves the caller's
        # input unless it opted in to clobbering (NCCL in-place analog)
        buf = flat if inplace else np.ascontiguousarray(flat).copy()
        stats = np.zeros(len(graphs) * 2, np.float64)
        rc = t.engine_all_reduce(
            self._peers_csv, buf, flat.dtype.itemsize, code, opc,
            data, offsets, len(graphs), tag,
            1 if self._hash_name_based else 0,
            engine_chunk_size(self._colocated),
            # honor a tightened per-peer deadline on the native path too
            # (default: both are the engine timeout — no behavior change)
            min(engine_timeout_s(), peer_deadline_s()), engine_threads(), stats,
        )
        # the C++ executor reports collective-level failure without a
        # per-peer attribution — rank=None tells the recovery driver to
        # find the dead set by probing (elastic/shrink.find_dead_ranks)
        if rc == 1:
            timeline.event("deadline", tag, rank=self._timeline_rank,
                           phase="native-collective", cause="TimeoutError")
            raise PeerFailureError(
                None, op=tag, phase="native-collective",
                cause=TimeoutError(f"native collective {tag!r} timed out"),
            )
        if rc == 2:
            timeline.event("deadline", tag, rank=self._timeline_rank,
                           phase="native-collective", cause="ConnectionError")
            raise PeerFailureError(
                None, op=tag, phase="native-collective",
                cause=ConnectionError(
                    f"native collective {tag!r}: peer unreachable/closed"
                ),
            )
        if rc != 0:
            raise RuntimeError(f"native collective {tag!r} failed (rc={rc})")
        if record and graphs is self._graphs:
            with self._stats_lock:
                for gi in range(len(graphs)):
                    b, s = stats[2 * gi], stats[2 * gi + 1]
                    self.stats[gi][0] += int(b)
                    self.stats[gi][1] += s
                    self._window[gi][0] += int(b)
                    self._window[gi][1] += s
        if self.channel.monitor is not None:
            # egress accounting: every reduce/bcast next got chunk-sized
            # sends; approximate per-peer attribution is done natively for
            # ingress — skip fine-grained egress here (native sends bypass
            # the python wrapper)
            pass
        return buf

    def _serialize_graphs(self, graphs) -> Tuple[np.ndarray, np.ndarray]:
        """Me-centric adjacency serialization consumed by
        ``kf_engine_all_reduce`` (see transport.cpp for the layout)."""
        me = self.rank
        data: List[int] = []
        offsets = [0]
        for red, bc in graphs:
            for g in (red, bc):
                data.append(1 if g.is_self_loop(me) else 0)
                prevs = list(g.prevs(me))
                data.append(len(prevs))
                data.extend(prevs)
                nexts = list(g.nexts(me))
                data.append(len(nexts))
                data.extend(nexts)
            offsets.append(len(data))
        return np.asarray(data, np.int32), np.asarray(offsets, np.int32)

    # -- internals -------------------------------------------------------
    def _split(self, flat: np.ndarray) -> List[np.ndarray]:
        n_chunks = max(1, -(-flat.nbytes // engine_chunk_size(self._colocated)))
        return [np.ascontiguousarray(c) for c in np.array_split(flat, n_chunks)]

    def _choose(self, chunk_idx: int, name: str, n_graphs: Optional[int] = None) -> int:
        """Chunk→strategy hash (reference ``shard.go:11-31``): simple mode
        spreads chunks round-robin; NAME mode
        (``KF_CONFIG_STRATEGY_HASH_METHOD=NAME``) keys on the tensor name
        so whole tensors stick to one strategy."""
        n = n_graphs if n_graphs is not None else len(self._graphs)
        if self._hash_name_based:
            return name_based_hash(name) % n
        return chunk_idx % n

    def _send(self, rank: int, name: str, payload: bytes):
        """Send under a per-peer deadline: transient wire faults (a reset
        mid-chunk, a peer restarting its listener) are retried with
        jittered exponential backoff; deadline exhaustion raises
        :class:`PeerFailureError` naming the suspect instead of riding
        the channel's full 100 s connect ladder."""
        peer = self.peers[rank]
        deadline = time.monotonic() + self._peer_deadline
        attempt = 0
        while True:
            # size the channel's connect ladder by the remaining budget:
            # against a SYN-dropping dead host each rung can burn the
            # full CONNECT_TIMEOUT_S, so a fixed-length ladder would
            # blow through a tight deadline 10x over before this loop
            # ever saw the clock again (one rung of overshoot is the
            # floor — a single TCP connect cannot be subdivided)
            remaining = deadline - time.monotonic()
            retries = max(1, min(_SEND_CONNECT_RETRIES,
                                 int(remaining / CONNECT_TIMEOUT_S)))
            try:
                if self._chaos is not None:
                    self._chaos.on_send(
                        rank, name, payload, channel=self.channel, peer=peer
                    )
                self.channel.send(
                    peer, name, payload, ConnType.COLLECTIVE, retries=retries,
                )
                return
            except (ConnectionError, TimeoutError, OSError) as e:
                if time.monotonic() >= deadline:
                    timeline.event(
                        "deadline", name, rank=self._timeline_rank, peer=rank,
                        phase="send", cause=type(e).__name__,
                    )
                    raise PeerFailureError(
                        rank, peer, op=name, phase="send", cause=e
                    ) from e
                timeline.event(
                    "retry", name, rank=self._timeline_rank, peer=rank,
                    attempt=attempt, cause=type(e).__name__,
                )
                sleep_backoff(attempt, base=0.05, cap=1.0)
                attempt += 1

    def _recv(self, rank: int, name: str) -> bytes:
        peer = self.peers[rank]
        if self._chaos is not None:
            self._chaos.on_recv(rank, name)
        try:
            return self.channel.recv(
                peer, name, ConnType.COLLECTIVE, timeout=self._peer_deadline
            )
        except (TimeoutError, ConnectionError) as e:
            timeline.event("deadline", name, rank=self._timeline_rank, peer=rank,
                           phase="recv", cause=type(e).__name__)
            raise PeerFailureError(
                rank, peer, op=name, phase="recv", cause=e
            ) from e

    def _recv_into(self, rank: int, name: str, arr: np.ndarray) -> None:
        """Receive a same-shaped payload into ``arr`` via the registered
        zero-copy path (native: socket→buffer in the C++ stream thread).
        Graph collectives exchange deterministically-sized chunks, so a
        size mismatch is a protocol violation — diagnosed loudly, not
        papered over."""
        peer = self.peers[rank]
        if self._chaos is not None:
            self._chaos.on_recv(rank, name)
        try:
            filled = self.channel.recv_into(
                peer, name, arr, ConnType.COLLECTIVE, timeout=self._peer_deadline
            )
        except (TimeoutError, ConnectionError) as e:
            timeline.event("deadline", name, rank=self._timeline_rank, peer=rank,
                           phase="recv", cause=type(e).__name__)
            raise PeerFailureError(
                rank, peer, op=name, phase="recv", cause=e
            ) from e
        if filled:
            return
        data = self.channel.recv(peer, name, ConnType.COLLECTIVE)
        raise ValueError(
            f"collective {name!r} from rank {rank}: expected {arr.nbytes} "
            f"bytes, got {len(data)} — peers disagree on the chunk layout "
            "(mixed strategy/epoch?)"
        )

    def _run_graphs(
        self, chunk: np.ndarray, op: str, tag: str, reduce_g: Graph, bcast_g: Graph
    ) -> np.ndarray:
        """The reference hot loop (``session.go:222-290`` runGraphs):
        reduce stage — recv from graph prevs, accumulate, send to nexts;
        broadcast stage — recv final value, forward to nexts."""
        me = self.rank
        acc = chunk.copy() if reduce_g.is_self_loop(me) else None

        # reduce stage: wait for all prevs, accumulate (native C++ kernel,
        # numpy fallback — kungfu_tpu/native/reduce.cpp).  Receives land
        # directly in a registered scratch buffer (zero-copy on the native
        # transport: no per-message allocation or queue hop).
        scratch: Optional[np.ndarray] = None
        for prev in reduce_g.prevs(me):
            if scratch is None:
                scratch = np.empty_like(chunk)
            self._recv_into(prev, tag + ".r", scratch)
            if acc is None:
                acc = scratch
                scratch = None  # acc now owns it; next prev gets a fresh one
            else:
                acc = native.transform2(acc, scratch, op)
        if acc is None:
            acc = chunk.copy()
        for nxt in reduce_g.nexts(me):
            self._send(nxt, tag + ".r", acc.tobytes())

        # broadcast stage: roots already hold the result
        if not bcast_g.is_self_loop(me):
            prevs = bcast_g.prevs(me)
            if prevs:
                acc = np.empty_like(chunk)
                self._recv_into(prevs[0], tag + ".b", acc)
        for nxt in bcast_g.nexts(me):
            self._send(nxt, tag + ".b", acc.tobytes())
        return acc

    def _run_bcast(self, buf: np.ndarray, tag: str, bcast_g: Graph) -> np.ndarray:
        me = self.rank
        if not bcast_g.is_self_loop(me):
            prevs = bcast_g.prevs(me)
            if prevs:
                buf = np.frombuffer(self._recv(prevs[0], tag + ".b"), dtype=buf.dtype).copy()
        for nxt in bcast_g.nexts(me):
            self._send(nxt, tag + ".b", buf.tobytes())
        return buf

    def async_pool(self):
        """Per-engine executor for caller-level async collectives (torch
        binding et al.).  Per-engine — never shared across in-process
        engines — and FIFO with the caller's deterministic submission
        order, so equal-sized pools run identical op prefixes on every
        rank and cannot cross-starve.  Distinct from ``_pool`` (the chunk
        pool) so a blocked caller-level op cannot occupy a chunk slot."""
        with self._lock:
            if self._async_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._async_pool = ThreadPoolExecutor(
                    max_workers=ASYNC_POOL_WORKERS,
                    thread_name_prefix="kf-engine-async"
                )
            return self._async_pool

    def close(self) -> None:
        """Shut the worker pools down (engines are rebuilt per mesh
        epoch; leaking 8 threads per epoch would grow unboundedly)."""
        self._pool.shutdown(wait=False)
        if self._async_pool is not None:
            self._async_pool.shutdown(wait=False)

    # -- adaptation hooks ------------------------------------------------
    def throughputs(self) -> List[float]:
        """Per-strategy-pair achieved GiB/s over the window since the last
        call; also updates :attr:`best_throughputs`
        (reference ``strategy.go:17-56``)."""
        out = []
        with self._stats_lock:
            for i, (b, t) in enumerate(self._window):
                rate = (b / t / 2**30) if t > 0 else 0.0
                out.append(rate)
                if rate > self.best_throughputs[i]:
                    self.best_throughputs[i] = rate
                self._window[i][0] = 0
                self._window[i][1] = 0.0
        return out

    def total_throughputs(self) -> List[float]:
        """Lifetime per-strategy-pair GiB/s."""
        with self._stats_lock:
            return [(b / t / 2**30) if t > 0 else 0.0 for b, t in self.stats]

    def window_peek(self) -> List[Tuple[int, float]]:
        """Non-destructive view of the recent per-strategy-pair window:
        ``[(bytes, seconds), ...]`` accumulated since the last
        :meth:`throughputs` call.  The kf-adapt window export: unlike
        ``throughputs()`` it does NOT reset the window, so the bandit
        driver and the interference checker can read the same window
        without racing each other's resets."""
        with self._stats_lock:
            return [(int(b), float(t)) for b, t in self._window]

    def mark_swap(self) -> None:
        """Open a new swap-eligibility epoch: collectives before this
        point no longer count toward :meth:`swap_eligible` (called by
        the adaptation drivers right after a fenced strategy swap, so
        the next verdict is about the NEW arm only)."""
        with self._stats_lock:
            self._colls_at_swap = self._colls_total

    def collectives_since_swap(self) -> int:
        """Collectives executed in the current swap-eligibility epoch."""
        with self._stats_lock:
            return self._colls_total - self._colls_at_swap

    def swap_eligible(self, min_collectives: int = 2) -> bool:
        """Whether the active strategy has carried enough real traffic
        since the last swap to be judged — the hysteresis gate that
        stops a bandit (or any adaptation driver) from thrashing
        strategies faster than it can measure them."""
        return self.collectives_since_swap() >= max(0, int(min_collectives))

    def set_strategy(self, strategy: Strategy) -> None:
        """Swap the strategy set (reference ``SetGlobalStrategy`` +
        ``adaptation.go:8-28``; caller is responsible for the barrier +
        consensus fencing around the swap)."""
        # kf-overlap: a handle in flight walks the OLD graphs — swapping
        # them under it would tear the wire protocol mid-collective.
        # Free when the window is empty (the fenced-swap drivers barrier
        # before calling here, so it always is in practice).
        self.drain_async()
        self.strategy = strategy
        self._graphs = build_strategy_graphs(strategy, self.peers)
        self._cross_graphs = build_cross_strategy_graphs(strategy, self.peers)
        self._graph_ser.clear()
        with self._stats_lock:
            self.stats = [[0, 0.0] for _ in self._graphs]
            self._window = [[0, 0.0] for _ in self._graphs]
            self.best_throughputs = [0.0 for _ in self._graphs]
            # a swap opens a fresh eligibility epoch by definition
            self._colls_at_swap = self._colls_total
