"""Device-plane communicator: mesh epochs + XLA/ICI collectives.

This is the TPU-native replacement for the reference's collective engine
(``srcs/go/kungfu/session/session.go`` — graph-driven chunked allreduce over
TCP) and its NCCL subsystem (``srcs/cpp/src/nccl``).  Design:

* A :class:`Communicator` is an **immutable mesh epoch**: a cluster
  membership + version + a ``jax.sharding.Mesh`` over the participating
  devices.  Elastic resize never mutates a communicator — it builds a new
  one (the analog of the reference's new-``Session``-per-membership-change,
  ``peer/peer.go:144-166``, and of ``ResetNcclHelper``).

* Collectives are compiled: each eager call dispatches to a cached
  ``jit(shard_map(...))`` whose body is a ``jax.lax`` collective.  XLA
  schedules and routes them over ICI — there is no per-message routing
  graph, no chunking (XLA tiles transfers), and no launch-order scheduler
  (SPMD compilation fixes a global order; the reference needed a dedicated
  NCCL thread + ``LinearExecutor`` for this, ``scheduler.cpp:37-77``).

* The mesh is 2-D ``(host, local)`` mirroring the reference's hierarchy of
  local/cross/global strategy lists (``session/strategy.go:176-210``):
  ``local_*`` collectives reduce over the intra-host axis, ``cross_*`` over
  the inter-host axis, global ones over both.

Eager semantics (single-controller): a "peer" is a mesh device; values are
**stacked** on a leading peer axis of size ``n`` and collectives return the
stacked result (e.g. ``all_reduce(x)[i] == x.sum(0)`` for every ``i``).
Inside user jit code, use :mod:`kungfu_tpu.ops` with the communicator's
axis names instead — that is the hot path.

Multi-controller semantics (mesh spans >1 process, e.g. a provisioned
elastic world or a real multi-host slice): the global stacked array is
never materialized on one host — each process passes and receives its
**addressable slice** (leading axis = its own device count in this mesh).
The conversion is pure layout (``host_local_array_to_global_array``), the
collective itself still compiles to one XLA program over the sub-mesh;
processes outside the mesh don't participate at all, which is what makes
re-carved mesh epochs (live elastic resize) possible.
"""

from __future__ import annotations

import functools
import math
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from kungfu_tpu.utils.jaxcompat import axis_size, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kungfu_tpu.monitor import timeline
from kungfu_tpu.plan.cluster import Cluster
from kungfu_tpu.utils.log import get_logger

_log = get_logger("device")

HOST_AXIS = "kf_host"
LOCAL_AXIS = "kf_local"
GLOBAL_AXES = (HOST_AXIS, LOCAL_AXIS)

_REDUCE_OPS = ("sum", "min", "max", "prod", "mean")


def _traced_collective(name: str, op: str, n: int, version: int, fn,
                       nbytes: Optional[int] = None,
                       sched: Optional[str] = None, hook=None):
    """Run an eager collective under a device-plane timeline span.

    JAX dispatch is asynchronous — the eager call returns once the op is
    enqueued — so an un-fenced span would time dispatch, not execution,
    and a straggler-stalled collective would record microseconds (the
    exact signal kftrace exists to expose, inverted).  Traced runs
    therefore block on the result inside the span; untraced runs (the
    production default) keep the async fast path untouched.

    ``nbytes``/``sched`` stamp the span for the per-schedule latency
    rings (kf-adapt), and ``hook`` — the communicator's latency hook —
    receives ``(nbytes, sched, seconds)`` for every measured collective.
    An installed hook forces the fence even with tracing off: the bandit
    needs real execution times, not dispatch times."""
    if not timeline.enabled() and hook is None:
        return fn()
    attrs = {"op": op, "n": n, "version": version,
             # kf-xray derived cross-rank trace id: every process of the
             # mesh computes the identical id from (version, step, op,
             # name) — zero extra wire bytes (docs/xray.md)
             "trace": timeline.collective_trace_id(
                 version, timeline.current_step(), op, name)}
    if nbytes is not None:
        attrs["nbytes"] = nbytes
    if sched is not None:
        attrs["sched"] = sched
    t0 = time.perf_counter()
    with timeline.span("device", name, **attrs):
        out = fn()
        jax.block_until_ready(out)
    if hook is not None and nbytes is not None and sched is not None:
        try:
            hook(nbytes, sched, time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 — observers must not break comm
            _log.warning("latency hook failed: %s", e)
    return out


def _tree_stack_check(n: int, x):
    for leaf in jax.tree_util.tree_leaves(x):
        if leaf.shape[0] != n:
            raise ValueError(
                f"stacked collective input must have leading peer axis {n}, got {leaf.shape}"
            )


class Communicator:
    """One mesh epoch.  Immutable; resize creates a new instance."""

    def __init__(
        self,
        cluster: Optional[Cluster] = None,
        version: int = 0,
        devices: Optional[Sequence] = None,
        local_size: Optional[int] = None,
        strategy: str = "psum",
        on_strategy_change: Optional[Callable[[str], None]] = None,
    ):
        self.cluster = cluster
        self.version = version
        self._strategy = "psum"
        self._on_strategy_change = on_strategy_change
        #: per-payload-bucket schedule overrides (kf-adapt): bucket index
        #: (ops.schedules.size_bucket) -> schedule name.  Empty = every
        #: size rides the global strategy.  Deliberately NOT carried
        #: across mesh epochs — a resize is a new regime; the bandit
        #: driver re-explores (monitor/adapt_device.py)
        self._bucket_strategy: dict = {}
        #: kf-adapt latency hook: called (nbytes, sched, seconds) after
        #: every measured eager collective (None = untimed fast path)
        self._latency_hook: Optional[Callable] = None
        self.set_strategy(strategy)
        devs = list(devices) if devices is not None else list(jax.devices())
        n = len(devs)
        if local_size is None:
            local_size = self._infer_local_size(cluster, n)
        if n % local_size != 0:
            raise ValueError(f"{n} devices not divisible by local_size={local_size}")
        self._n = n
        self._local = local_size
        self._hosts = n // local_size
        self.mesh = Mesh(
            np.asarray(devs).reshape(self._hosts, self._local), GLOBAL_AXES
        )
        self.axis = GLOBAL_AXES  # pass to kungfu_tpu.ops inside user jit code
        self._fns = {}
        # multi-controller: eager stacked convention degrades to the
        # addressable slice (leading axis = this process's device count)
        self._multiproc = len({d.process_index for d in devs}) > 1
        if self._multiproc:
            pi = jax.process_index()
            self._local_n = sum(1 for d in devs if d.process_index == pi)
            if self._local_n == 0:
                raise ValueError(
                    "current process owns no device in this communicator "
                    "(standby peers must not build communicators)"
                )
        else:
            self._local_n = n

    @staticmethod
    def _infer_local_size(cluster: Optional[Cluster], n: int) -> int:
        """Use the cluster's per-host worker counts when they evenly tile the
        device count; else flat (1 logical host) — LOUDLY, because a flat
        mesh changes ``local_*``/``cross_*`` semantics (local collectives
        span everything, cross collectives become no-ops)."""
        if cluster is not None and cluster.size() > 0:
            parts = [len(v) for v in cluster.workers.partition_by_host().values()]
            if len(set(parts)) == 1 and n % (n // len(parts) or 1) == 0:
                per_host = n // len(parts)
                if per_host * len(parts) == n and per_host >= 1:
                    return per_host
            _log.warning(
                "uneven host partition %s over %d devices: mesh degrades to "
                "flat 1x%d — local_* collectives will span ALL devices and "
                "cross_* collectives become no-ops; pass local_size= "
                "explicitly to keep a hierarchical mesh",
                parts, n, n,
            )
        return n

    # -- strategy --------------------------------------------------------
    @property
    def strategy(self) -> str:
        """Active allreduce schedule (``kungfu_tpu.ops.schedules``)."""
        return self._strategy

    def set_strategy(self, name: str) -> None:
        """Select the compiled allreduce schedule — the device-plane
        analog of the reference's ``SetGlobalStrategy``
        (``session/adaptation.go:8-28``).  Swapping re-jits on next use
        (compiled programs are cached per (op, shape, strategy) key).
        Like every collective here, all controller processes must make
        the same call at the same point; consensus/fencing for adaptive
        swaps rides the same driver machinery as the host plane
        (:mod:`kungfu_tpu.monitor.adaptive`).
        """
        from kungfu_tpu.ops.schedules import ALLREDUCE_SCHEDULES

        if name not in ALLREDUCE_SCHEDULES:
            raise ValueError(
                f"unknown strategy {name!r}; one of {ALLREDUCE_SCHEDULES}"
            )
        self._strategy = name
        if self._on_strategy_change is not None:
            # let an owning Peer record the choice durably, so a resize
            # racing this call cannot rebuild the next epoch without it
            self._on_strategy_change(name)

    # -- per-bucket schedule table (kf-adapt) -----------------------------
    def set_bucket_strategy(self, bucket: int, name: Optional[str]) -> None:
        """Install ``name`` as the allreduce schedule for one payload
        bucket (:data:`kungfu_tpu.ops.schedules.SIZE_BUCKETS`) — the
        online swap hook of the size-bucketed schedule table: small
        control tensors and large fused gradient buckets carry
        independently-learned winners.  ``None`` clears the override.
        Swaps re-jit lazily (programs are cached per (op, shape,
        schedule)); like :meth:`set_strategy`, all controller processes
        must make the same call at the same point — the bandit driver's
        consensus fence (:mod:`kungfu_tpu.monitor.adapt_device`) owns
        that discipline."""
        from kungfu_tpu.ops.schedules import (ALLREDUCE_SCHEDULES,
                                              SIZE_BUCKETS)

        if not 0 <= bucket < len(SIZE_BUCKETS):
            raise ValueError(
                f"bucket {bucket} out of range [0, {len(SIZE_BUCKETS)})")
        if name is None:
            self._bucket_strategy.pop(bucket, None)
            return
        if name not in ALLREDUCE_SCHEDULES:
            raise ValueError(
                f"unknown strategy {name!r}; one of {ALLREDUCE_SCHEDULES}")
        self._bucket_strategy[bucket] = name

    def strategy_for_bucket(self, bucket: int) -> str:
        """Active schedule for one payload bucket (global strategy when
        no override is installed)."""
        return self._bucket_strategy.get(bucket, self._strategy)

    def strategy_for(self, nbytes: int) -> str:
        """Active schedule for a payload of ``nbytes``."""
        if not self._bucket_strategy:
            return self._strategy
        from kungfu_tpu.ops.schedules import size_bucket

        return self.strategy_for_bucket(size_bucket(nbytes))

    def bucket_strategies(self) -> dict:
        """Installed per-bucket overrides, ``{bucket_index: name}``."""
        return dict(self._bucket_strategy)

    def bucket_summary(self) -> str:
        """Compact ``"small=psum,large=ring"`` rendering of the installed
        bucket table ("" when empty) — the active-arm column kftop shows
        per rank (docs/monitoring.md)."""
        if not self._bucket_strategy:
            return ""
        from kungfu_tpu.ops.schedules import SIZE_BUCKETS

        return ",".join(
            f"{SIZE_BUCKETS[b]}={n}"
            for b, n in sorted(self._bucket_strategy.items())
        )

    def set_latency_hook(self, fn: Optional[Callable]) -> None:
        """Install ``fn(nbytes, sched, seconds)`` to receive the measured
        execution time of every eager collective — the bandit driver's
        feed.  The hook forces result-fencing on the eager path (the
        measurement is execution, not dispatch); pass ``None`` to restore
        the async fast path."""
        self._latency_hook = fn

    def autotune_strategy(self, nbytes: int = 4 << 20, trials: int = 3) -> str:
        """Measure every allreduce schedule on a representative buffer on
        THIS mesh and install the fastest — the reference's AUTO strategy
        (``strategy.go:90-99``) decided by measurement instead of a
        host-count table.  Collective and deterministic: each process
        times the same compiled programs, the per-schedule times are
        averaged across the mesh (a device-plane mean), and every process
        picks the same argmin.  Call at startup or after a resize, at the
        same point on every controller."""
        from kungfu_tpu.ops.schedules import ALLREDUCE_SCHEDULES

        x = jnp.asarray(
            np.random.default_rng(0).standard_normal(
                (self._local_n, max(1, nbytes // 4))
            ),
            jnp.float32,
        )
        prev = self._strategy
        cached_before = set(self._fns)
        try:
            times = self._time_schedules(x, max(1, trials))
            if all(t is None for t in times):
                # every candidate failed to lower: a measurement-harness
                # bug, not a preference — picking the argmin of sentinel
                # values would silently install an unmeasured schedule
                raise RuntimeError(
                    "autotune: no allreduce schedule could be timed on "
                    "this mesh (see preceding warnings)"
                )
            # agree across processes: average each schedule's time over
            # the mesh, so controllers with skewed clocks still pick one
            # winner (1e9 = "did not lower"; it dominates any real time
            # even after mean-dilution)
            agreed = self._agree(
                [t if t is not None and math.isfinite(t) else 1e9
                 for t in times],
                op="mean",
            )
        finally:
            self._strategy = prev
            # the probe shape never recurs in training: drop its compiled
            # programs instead of carrying them for the communicator's
            # lifetime
            for key in set(self._fns) - cached_before:
                del self._fns[key]
        idx = int(np.argmin(agreed))
        win_t = float(agreed[idx])
        if not math.isfinite(win_t) or win_t <= 0.0 or win_t >= 1e8:
            # a 0.0 s / non-finite / sentinel "winner" is a measurement
            # failure, not a preference — installing it is how the old
            # 1 KiB 1-trial startup probe coin-flipped the schedule
            # (ROADMAP #4).  Keep the incumbent and say so loudly.
            _log.warning(
                "autotune: winning time %r is not a credible measurement "
                "(times %s); keeping %r",
                win_t, list(map(float, agreed)), self._strategy,
            )
            return self._strategy
        winner = ALLREDUCE_SCHEDULES[idx]
        _log.info(
            "autotune: %s over %s",
            winner,
            {s: round(float(t) * 1e3, 3)
             for s, t in zip(ALLREDUCE_SCHEDULES, agreed)},
        )
        self.set_strategy(winner)
        return winner

    def _agree(self, row, op: str) -> np.ndarray:
        """Reduce a small per-controller vector over the mesh and return
        the agreed row — always over the default psum path (the machinery
        under measurement must not carry its own agreement traffic).
        Bucket overrides and the latency hook are suspended for the same
        reason: agreement traffic must neither ride a schedule under
        test nor land in the bandit's measurement windows."""
        stacked = jnp.broadcast_to(
            jnp.asarray(row, jnp.float32), (self._local_n, len(row))
        )
        prev = self._strategy
        prev_buckets, self._bucket_strategy = self._bucket_strategy, {}
        prev_hook, self._latency_hook = self._latency_hook, None
        self._strategy = "psum"
        try:
            return np.asarray(self.all_reduce(stacked, op=op))[0]
        finally:
            self._strategy = prev
            self._bucket_strategy = prev_buckets
            self._latency_hook = prev_hook

    def _time_schedules(self, x, trials):
        """Per-schedule seconds for one allreduce of ``x``, measured the
        way ``bench.py`` had to learn: remote-execution backends ack
        ``block_until_ready`` early and serve byte-identical dispatches
        from a result cache, and congestion arrives in bursts.  So:
        compile ONE program per (schedule, K) that chains K salted
        allreduces and returns a scalar (host materialization is the only
        real fence), difference two K values so the constant RTT cancels,
        and interleave all candidates with per-candidate running mins so
        a burst cannot land on just one schedule's measurement.

        Multi-controller meshes use the SAME chained-K harness: the whole
        chain is one shard_map program over the sub-mesh, and only its
        scalar output crosses the host-slice boundary — the eager
        fallback that round 3 flagged (which would re-admit relay timing
        artifacts on relay-fronted backends) is gone."""
        from jax.experimental import multihost_utils as mh

        from kungfu_tpu.ops.schedules import (ALLREDUCE_SCHEDULES,
                                              all_reduce_scheduled)

        k_lo, k_hi = 4, 16
        spec = self._spec_in()
        if self._multiproc:
            xg = mh.host_local_array_to_global_array(
                x if isinstance(x, jax.Array) else np.asarray(x),
                self.mesh, spec)
        else:
            xg = x

        def make(k, sched):
            # one compiled program: salt in, K chained allreduces, a
            # scalar out.  The fori_loop lives at the jit level and chains
            # whole shard_map programs — a loop INSIDE shard_map would
            # change the carry's varying-manual-axes type after the first
            # reduce and fail to trace.
            def one(s):
                return all_reduce_scheduled(
                    s, GLOBAL_AXES, op="mean", schedule=sched)

            inner = shard_map(
                one, mesh=self.mesh, in_specs=(spec,), out_specs=spec)

            def chain(c, salt):
                c = c + salt
                c = jax.lax.fori_loop(0, k, lambda _, y: inner(y), c)
                return jnp.sum(c[..., :1])

            # AOT compile is LOCAL (no collective executes): asymmetric
            # compile/lowering failures — the common failure class, since
            # identical processes lower deterministically — are agreed on
            # below before any probe collective is dispatched.  A RUNTIME
            # failure on one controller mid-collective can still strand
            # peers; like any hung collective that is the failure
            # detector's job (monitor/detector.py), not this harness's.
            compiled = jax.jit(chain).lower(xg, jnp.float32(0.5)).compile()

            def run(salt):
                out = compiled(xg, jnp.float32(salt))
                # materializing the (replicated) scalar on the host is the
                # only real fence; addressable_data keeps it local in
                # multi-controller mode
                return float(np.asarray(out.addressable_data(0)))

            return run

        progs = {}
        for sched in ALLREDUCE_SCHEDULES:
            try:
                progs[sched] = (make(k_lo, sched), make(k_hi, sched))
            except Exception as e:  # noqa: BLE001 — may not lower
                _log.warning("autotune: schedule %s failed: %s", sched, e)
                progs[sched] = math.inf

        if self._multiproc:
            # agree on the timeable set before the first probe collective:
            # schedules any controller could not compile are dropped on
            # ALL controllers (a min-reduce of the ok bitmask over the
            # default psum path)
            agreed_ok = self._agree(
                [0.0 if progs[s] is math.inf else 1.0
                 for s in ALLREDUCE_SCHEDULES],
                op="min",
            )
            for s, okv in zip(ALLREDUCE_SCHEDULES, agreed_ok):
                if okv < 1.0 and progs[s] is not math.inf:
                    _log.warning(
                        "autotune: schedule %s dropped (failed on a peer)", s)
                    progs[s] = math.inf

        for p in progs.values():  # warm the agreed set
            if p is not math.inf:
                p[0](0.5)
                p[1](0.5)

        rng = np.random.default_rng(1234)
        best = {s: [math.inf, math.inf] for s in progs}
        for _ in range(trials):
            for sched, p in progs.items():
                if p is math.inf:
                    continue
                lo, hi = p
                for idx, f in ((0, lo), (1, hi)):
                    salt = rng.random()
                    t0 = time.perf_counter()
                    f(salt)
                    best[sched][idx] = min(
                        best[sched][idx], time.perf_counter() - t0
                    )
        out = []
        for sched in ALLREDUCE_SCHEDULES:
            p = progs[sched]
            if p is math.inf:
                out.append(None)
            else:
                out.append(
                    max((best[sched][1] - best[sched][0]) / (k_hi - k_lo),
                        1e-9)
                )
        return out

    # -- metadata --------------------------------------------------------
    @property
    def size(self) -> int:
        return self._n

    @property
    def num_hosts(self) -> int:
        return self._hosts

    @property
    def local_size(self) -> int:
        return self._local

    @property
    def addressable_n(self) -> int:
        """Leading-axis size of eager collective arguments: ``size`` in
        single-controller mode, this process's device count in
        multi-controller mode."""
        return self._local_n

    def __repr__(self):
        return (
            f"Communicator(v{self.version}, {self._n} devices as "
            f"{self._hosts}x{self._local})"
        )

    # -- compiled collective factory -------------------------------------
    def _spec_in(self):
        # leading peer axis split over both mesh axes
        return P(GLOBAL_AXES)

    def _cached(self, key, build: Callable):
        fn = self._fns.get(key)
        if fn is None:
            fn = build()
            if self._multiproc:
                fn = self._local_slice_wrap(fn)
            self._fns[key] = fn
        return fn

    def _local_slice_wrap(self, fn):
        """Multi-controller calling convention: the caller passes its
        addressable slice; we lift it to a global array over the mesh, run
        the compiled collective, and hand back the addressable slice of
        the result.  Layout-only — no extra communication."""
        from jax.experimental import multihost_utils as mh

        spec = self._spec_in()

        # the legitimate pass-through layout is an array on THIS
        # process's slice of the mesh: host_local_array_to_global_array
        # lifts exactly that.  (Comparing against the FULL mesh device
        # set could never match a host-local slice in multi-controller
        # mode — every eager input then paid a numpy materialization —
        # and the one layout it did match, a fully-global array, is the
        # input the lift would mis-handle.)
        local_mesh_devs = set(self.mesh.local_devices)

        def wrapped(a):
            # jax arrays already on this process's mesh devices pass
            # through (layout-only resharding); anything else — host
            # data, or an array committed elsewhere (the process-default
            # device, or an already-global array) — pays a numpy
            # materialization of the local slice, which also rejects
            # non-addressable inputs loudly
            local = (
                a if isinstance(a, jax.Array)
                and a.sharding.device_set <= local_mesh_devs
                else np.asarray(a)
            )
            g = mh.host_local_array_to_global_array(local, self.mesh, spec)
            out = fn(g)
            return mh.global_array_to_host_local_array(out, self.mesh, spec)

        return wrapped

    def _shard_jit(self, body, out_replicated=False):
        spec = self._spec_in()
        out_spec = P() if out_replicated else spec
        f = shard_map(body, mesh=self.mesh, in_specs=(spec,), out_specs=out_spec)
        return jax.jit(f)

    # -- collectives (eager, stacked) ------------------------------------
    def all_reduce(self, x, op: str = "sum"):
        """Stacked allreduce: out[i] = reduce_j x[j].  Pytrees supported.
        The schedule is resolved per payload bucket
        (:meth:`strategy_for`); the span/latency-hook attribution uses
        the dominant (largest) leaf — the one that governs the time."""
        if op not in _REDUCE_OPS:
            raise ValueError(f"op {op!r} not in {_REDUCE_OPS}")
        _tree_stack_check(self._local_n, x)
        dom_nbytes = max(
            (getattr(leaf, "nbytes", 0)
             for leaf in jax.tree_util.tree_leaves(x)),
            default=0,
        )
        return _traced_collective(
            "device.all_reduce", "all_reduce", self._n, self.version,
            lambda: jax.tree_util.tree_map(
                lambda a: self._all_reduce_leaf(a, op, GLOBAL_AXES), x),
            nbytes=int(dom_nbytes),
            sched=self.strategy_for(int(dom_nbytes)) if op != "prod"
            else "psum",
            hook=self._latency_hook,
        )

    def _all_reduce_leaf(self, a, op, axes):
        a = jnp.asarray(a)
        sched = self.strategy_for(a.nbytes) if op != "prod" else "psum"
        key = ("ar", op, axes, a.shape, a.dtype.name, sched)

        def build():
            def body(s):
                if sched != "psum":
                    return self._scheduled_body(s, op, axes, sched)
                if op == "sum":
                    return jax.lax.psum(s, axes)
                if op == "mean":
                    return jax.lax.pmean(s, axes)
                if op == "min":
                    return jax.lax.pmin(s, axes)
                if op == "max":
                    return jax.lax.pmax(s, axes)
                # prod: gather then reduce (no pprod primitive)
                g = jax.lax.all_gather(s, axes, axis=0, tiled=False)
                g = g.reshape((-1,) + s.shape)
                return jnp.prod(g, axis=0)

            return self._shard_jit(body)

        return self._cached(key, build)(a)

    def _scheduled_body(self, s, op, axes, sched: Optional[str] = None):
        """Non-default schedule over the REQUESTED axes (global or one of
        the local/cross sub-axes).  ``all_reduce_scheduled`` owns the
        hierarchical decomposition: the schedule applies to the FIRST
        non-trivial axis (cross-host in ``(host, local)`` order) after
        the inner axes fold with one-hop psum.  ``sched`` is resolved by
        the CALLER (per-bucket dispatch) — reading ``self._strategy``
        here would ignore an installed bucket override at trace time."""
        from kungfu_tpu.ops.schedules import all_reduce_scheduled

        return all_reduce_scheduled(
            s, axes, op=op,
            schedule=sched if sched is not None else self._strategy)

    def reduce(self, x, root: int = 0, op: str = "sum"):
        """Root-valid reduce (reference ``session.go:157-165``): peer
        ``root``'s slice holds the reduction, every other peer's slice is
        its own input, untouched.  (The reduction itself still computes on
        all devices — on the torus a psum costs the same as reduce-to-root
        — only the *visible result* honors reference semantics.)"""
        if op not in _REDUCE_OPS:
            raise ValueError(f"op {op!r} not in {_REDUCE_OPS}")
        if not 0 <= root < self._n:
            raise ValueError(f"root {root} out of range [0, {self._n})")
        _tree_stack_check(self._local_n, x)

        def leaf(a):
            a = jnp.asarray(a)
            key = ("rd", op, root, a.shape, a.dtype.name)

            def build():
                def body(s):
                    if op == "sum":
                        red = jax.lax.psum(s, GLOBAL_AXES)
                    elif op == "mean":
                        red = jax.lax.pmean(s, GLOBAL_AXES)
                    elif op == "min":
                        red = jax.lax.pmin(s, GLOBAL_AXES)
                    elif op == "max":
                        red = jax.lax.pmax(s, GLOBAL_AXES)
                    else:  # prod
                        g = jax.lax.all_gather(s, GLOBAL_AXES, axis=0, tiled=False)
                        red = jnp.prod(g.reshape((-1,) + s.shape), axis=0)
                    return jnp.where(_flat_index() == root, red, s)

                return self._shard_jit(body)

            return self._cached(key, build)(a)

        return jax.tree_util.tree_map(leaf, x)

    def broadcast(self, x, root: int = 0):
        """out[i] = x[root] for all i."""
        if not 0 <= root < self._n:
            raise ValueError(f"root {root} out of range [0, {self._n})")
        _tree_stack_check(self._local_n, x)

        def leaf(a):
            a = jnp.asarray(a)
            key = ("bc", root, a.shape, a.dtype.name)

            def build():
                def body(s):
                    # where() not mask-multiply: non-root NaN must not
                    # poison the psum (broadcast recovers diverged replicas)
                    contrib = jnp.where(_flat_index() == root, s, jnp.zeros_like(s))
                    return jax.lax.psum(contrib, GLOBAL_AXES)

                return self._shard_jit(body)

            return self._cached(key, build)(a)

        return _traced_collective(
            "device.broadcast", "broadcast", self._n, self.version,
            lambda: jax.tree_util.tree_map(leaf, x))

    def first_slot_of_process(self, proc: int) -> int:
        """First flat device slot owned by jax process ``proc`` — the
        slot a :meth:`broadcast` roots on to broadcast *that process's*
        value (the mesh is carved in worker-rank order, so a worker's
        devices are contiguous in flat-slot order)."""
        for i, d in enumerate(self.mesh.devices.ravel()):
            if d.process_index == proc:
                return i
        raise ValueError(
            f"process {proc} owns no device in this communicator")

    def broadcast_value(self, value, root_slot: int = 0):
        """Broadcast ONE host value from ``root_slot``'s process without
        the stacked eager convention: every process passes its own
        ``value`` (ignored unless it owns the root slot) and receives the
        root's as numpy.  Unlike ``broadcast(np.broadcast_to(v, (n,)+...))``
        this never materializes n stacked model copies in host RAM — each
        local device gets the single row by runtime ``device_put`` and the
        global array is assembled shard-wise (used by the post-resize
        parameter re-sync, where ``value`` is a full fused model)."""
        a = np.asarray(value)
        if not 0 <= root_slot < self._n:
            raise ValueError(f"root {root_slot} out of range [0, {self._n})")
        key = ("bcv", root_slot, a.shape, a.dtype.name)
        fn = self._fns.get(key)
        if fn is None:
            def body(s):
                contrib = jnp.where(_flat_index() == root_slot, s,
                                    jnp.zeros_like(s))
                return jax.lax.psum(contrib, GLOBAL_AXES)

            # deliberately NOT _cached(): no host-local wrap — the global
            # array is assembled here, one row per addressable device
            fn = self._shard_jit(body)
            self._fns[key] = fn
        pi = jax.process_index() if self._multiproc else None
        local_devs = [d for d in self.mesh.devices.ravel()
                      if pi is None or d.process_index == pi]
        rows = [jax.device_put(a[None], d) for d in local_devs]
        g = jax.make_array_from_single_device_arrays(
            (self._n,) + a.shape, self.data_sharding(), rows)
        out = fn(g)
        return np.asarray(out.addressable_shards[0].data)[0]

    def all_gather(self, x):
        """out[i] = stack_j x[j] — every peer sees all slices; eager result
        has shape [n, n, ...] (reference ``allgather.go:17-45``)."""
        _tree_stack_check(self._local_n, x)

        def leaf(a):
            a = jnp.asarray(a)
            key = ("ag", a.shape, a.dtype.name)

            def build():
                def body(s):
                    g = jax.lax.all_gather(s, GLOBAL_AXES, axis=0, tiled=True)
                    return jnp.broadcast_to(g[None], (s.shape[0],) + g.shape)

                return self._shard_jit(body)

            return self._cached(key, build)(a)

        return _traced_collective(
            "device.all_gather", "all_gather", self._n, self.version,
            lambda: jax.tree_util.tree_map(leaf, x))

    def reduce_scatter(self, x, op: str = "sum", bucket_bytes: int = 4 << 20):
        """Stacked reduce-scatter — the ZeRO-2/3 gradient collective:
        ``out[i] = reduce_j(x[j])[chunk i]`` where the reduced buffer is
        carved into ``n`` equal chunks (zero-padded up to ``n * chunk``).
        Eager result has shape ``[n, chunk]``: each peer's slice is the
        1/n of the reduction it owns — (n-1)/n of the all-reduce wire
        bytes, the measured delta in ``bench.py --zero``.

        The collective runs **bucketed** (``bucket_bytes`` per piece,
        the gradient-bucket fusion of :mod:`kungfu_tpu.ops.schedules`
        folded to reduce-scatter-sized pieces), so XLA gets independent
        program points to overlap with neighboring compute.

        When the bandit (or the user) has installed ``pallas_ring`` for
        this payload's size bucket, each bucket's scatter rides the
        in-kernel-overlap ring kernel instead of ``lax.psum_scatter`` —
        same mesh-major chunk geometry, one more measured arm."""
        if op not in ("sum", "mean"):
            raise ValueError(
                f"reduce_scatter supports sum/mean, got {op!r}")
        _tree_stack_check(self._local_n, x)
        n = self._n

        def leaf(a):
            a = jnp.asarray(a)
            flat_sched = ("pallas_ring"
                          if self.strategy_for(a.nbytes) == "pallas_ring"
                          else "lax")
            key = ("rs", op, a.shape, a.dtype.name, int(bucket_bytes),
                   flat_sched)

            def build():
                from kungfu_tpu.ops.schedules import (bucket_widths,
                                                      reduce_scatter_flat)

                flat_len = int(np.prod(a.shape[1:], dtype=np.int64))
                chunk = math.ceil(flat_len / n) if flat_len else 0
                widths = bucket_widths(
                    chunk, n, a.dtype.itemsize, int(bucket_bytes))
                axes = [ax for ax, sz in
                        zip(self.mesh.axis_names, self.mesh.devices.shape)
                        if sz > 1]

                def body(s):
                    g = s.reshape(s.shape[0], -1)
                    pad = chunk * n - flat_len
                    if pad:
                        g = jnp.concatenate(
                            [g, jnp.zeros((s.shape[0], pad), g.dtype)], -1)
                    if flat_sched == "pallas_ring":
                        # the stacked eager convention leaves exactly one
                        # row per device inside shard_map: apply the ring
                        # kernel to it directly (a pallas_call under a
                        # size-1 vmap would stress the batching rule for
                        # nothing)
                        out = reduce_scatter_flat(
                            g[0], axes, chunk, widths,
                            schedule=flat_sched)[None]
                    else:
                        out = jax.vmap(
                            lambda row: reduce_scatter_flat(
                                row, axes, chunk, widths))(g)
                    if op == "mean":
                        out = out / n
                    return out

                return self._shard_jit(body)

            return self._cached(key, build)(a)

        return _traced_collective(
            "device.reduce_scatter", "reduce_scatter", self._n, self.version,
            lambda: jax.tree_util.tree_map(leaf, x))

    def all_gather_shard(self, x, bucket_bytes: int = 4 << 20):
        """Inverse of :meth:`reduce_scatter`: every peer contributes its
        ``[chunk]`` slice and receives the concatenation in peer order —
        eager result ``[n, n * chunk]`` (every row identical).  Bucketed
        like the scatter so the pair round-trips through the same piece
        layout (``all_gather_shard(reduce_scatter(x))`` re-assembles the
        reduction, zero padding included)."""
        _tree_stack_check(self._local_n, x)
        n = self._n

        def leaf(a):
            a = jnp.asarray(a)
            flat_sched = ("pallas_ring"
                          if self.strategy_for(a.nbytes) == "pallas_ring"
                          else "lax")
            key = ("ags", a.shape, a.dtype.name, int(bucket_bytes),
                   flat_sched)

            def build():
                from kungfu_tpu.ops.schedules import (all_gather_flat,
                                                      bucket_widths)

                chunk = int(np.prod(a.shape[1:], dtype=np.int64))
                widths = bucket_widths(
                    chunk, n, a.dtype.itemsize, int(bucket_bytes))
                axes = [ax for ax, sz in
                        zip(self.mesh.axis_names, self.mesh.devices.shape)
                        if sz > 1]

                def body(s):
                    g = s.reshape(s.shape[0], -1)
                    if flat_sched == "pallas_ring":
                        # one row per device (see reduce_scatter)
                        return all_gather_flat(
                            g[0], axes, widths, schedule=flat_sched)[None]
                    return jax.vmap(
                        lambda row: all_gather_flat(row, axes, widths))(g)

                return self._shard_jit(body)

            return self._cached(key, build)(a)

        return _traced_collective(
            "device.all_gather_shard", "all_gather", self._n, self.version,
            lambda: jax.tree_util.tree_map(leaf, x))

    def gather(self, x, root: int = 0):
        """DELIBERATE SEMANTIC DIVERGENCE from the reference: the
        reference's Gather delivers the stacked result to rank 0 only and
        leaves other peers' buffers untouched (``session.go:189-211``).
        On the device plane every peer receives the stacked copy
        (= :meth:`all_gather`): an all-gather over ICI costs the same as a
        gather-to-root, and the stacked eager calling convention cannot
        express per-peer result shapes.  Root-only gather semantics live on
        the host plane (:meth:`kungfu_tpu.comm.engine.CollectiveEngine.gather`)."""
        return self.all_gather(x)

    def local_all_reduce(self, x, op: str = "sum"):
        """Reduce over the intra-host mesh axis only."""
        return self._axis_reduce(x, op, (LOCAL_AXIS,))

    def cross_all_reduce(self, x, op: str = "sum"):
        """Reduce over the inter-host axis (the local-masters stage of the
        reference's hierarchical allreduce, ``allreduce.go:38``)."""
        return self._axis_reduce(x, op, (HOST_AXIS,))

    def _axis_reduce(self, x, op, axes):
        _tree_stack_check(self._local_n, x)
        return jax.tree_util.tree_map(lambda a: self._all_reduce_leaf(jnp.asarray(a), op, axes), x)

    def local_broadcast(self, x):
        """Broadcast each host's local-rank-0 slice to its host peers."""
        _tree_stack_check(self._local_n, x)

        def leaf(a):
            a = jnp.asarray(a)
            key = ("lbc", a.shape, a.dtype.name)

            def build():
                def body(s):
                    idx = jax.lax.axis_index(LOCAL_AXIS)
                    contrib = jnp.where(idx == 0, s, jnp.zeros_like(s))
                    return jax.lax.psum(contrib, (LOCAL_AXIS,))

                return self._shard_jit(body)

            return self._cached(key, build)(a)

        return jax.tree_util.tree_map(leaf, x)

    # -- group / fused variants ------------------------------------------
    def group_all_reduce(self, tensors: List, op: str = "sum", fuse: bool = True):
        """Allreduce a list of stacked tensors.  With ``fuse=True`` they are
        flattened into one buffer for a single collective (the reference's
        tensor-fusion optimization, ``ops/__init__.py:29-46``); XLA usually
        fuses anyway, but one launch keeps small-tensor latency flat."""
        if not fuse:
            return [self.all_reduce(t, op) for t in tensors]
        from kungfu_tpu.ops.fuse import fuse as _fuse, defuse as _defuse

        flat, treedef = _fuse(tensors, batch_axes=1)
        out = self.all_reduce(flat, op)
        return _defuse(out, treedef, batch_axes=1)

    # -- sync primitives --------------------------------------------------
    def barrier(self) -> None:
        """1-element allreduce + block (reference ``session.go:102-113``).
        In multi-controller mode this synchronizes exactly the processes
        whose devices are in this mesh epoch."""
        x = jnp.ones((self._local_n, 1), dtype=jnp.int32)
        with timeline.span("device", "device.barrier",
                           op="barrier", n=self._n, version=self.version):
            jax.block_until_ready(self.all_reduce(x))

    def consensus(self, x) -> bool:
        """True iff every peer's slice is bit-identical — allreduce MIN ==
        allreduce MAX (reference ``session.go:124-155``)."""
        _tree_stack_check(self._local_n, x)
        ok = True
        for leaf in jax.tree_util.tree_leaves(x):
            a = jnp.asarray(leaf)
            if a.dtype == jnp.bool_:
                a = a.astype(jnp.int32)
            lo = self._all_reduce_leaf(a, "min", GLOBAL_AXES)
            hi = self._all_reduce_leaf(a, "max", GLOBAL_AXES)
            ok = ok and bool(jnp.all(lo == hi))
        return ok

    def consensus_bytes(self, digests: Sequence[bytes]) -> bool:
        """Consensus over per-peer byte strings (cluster digests): True iff
        all ``n`` digests agree.  The caller must supply one digest per
        peer — in single-controller mode the controller holds all peers'
        state, so it has all digests; broadcasting ONE local value and
        comparing it to itself is a tautology, not consensus (round-1
        VERDICT).  Cross-process consensus belongs to the host plane
        (:meth:`kungfu_tpu.peer.Peer.consensus_bytes`)."""
        if isinstance(digests, (bytes, bytearray)):
            raise TypeError(
                "consensus_bytes needs one digest per peer "
                f"(a sequence of {self._n}); a single local byte string "
                "cannot witness cross-peer agreement — use "
                "Peer.consensus_bytes for host-plane consensus"
            )
        if len(digests) != self._local_n:
            raise ValueError(
                f"expected {self._local_n} digests (one per addressable "
                f"peer slot), got {len(digests)}"
            )
        width = max((len(d) for d in digests), default=0)
        rows = [
            np.frombuffer(d.ljust(width, b"\0"), dtype=np.uint8).astype(np.int32)
            for d in digests
        ]
        # length disagreement must fail even when padding collides
        lens = np.asarray([[len(d)] for d in digests], dtype=np.int32)
        stacked = np.concatenate([np.stack(rows), lens], axis=1) if width else lens
        return self.consensus(jnp.asarray(stacked))

    # -- sharding helpers -------------------------------------------------
    def data_sharding(self) -> NamedSharding:
        """Sharding for a global batch split over all peers (DP)."""
        return NamedSharding(self.mesh, P(GLOBAL_AXES))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def _flat_index():
    """Global peer index inside shard_map over the 2-D mesh."""
    h = jax.lax.axis_index(HOST_AXIS)
    l = jax.lax.axis_index(LOCAL_AXIS)
    return h * axis_size(LOCAL_AXIS) + l
