"""kungfu_tpu — a TPU-native adaptive distributed training framework.

A ground-up re-design of the capabilities of KungFu (reference:
``srcs/go``, ``srcs/cpp``, ``srcs/python`` of DingtongHan/KungFu-1) for
TPU hardware:

* the **data plane** (allreduce / broadcast / barrier / allgather) lowers to
  XLA/ICI collectives via ``jax.lax`` under ``shard_map`` over a
  ``jax.sharding.Mesh`` — this replaces both the reference's Go TCP/Unix-socket
  collective engine (reference ``srcs/go/kungfu/session``) and its NCCL
  subsystem (reference ``srcs/cpp/src/nccl``);
* the **control plane** (launcher, membership, config server, failure
  detector, p2p blob store, consensus) is a host-side runtime under
  :mod:`kungfu_tpu.runner`, :mod:`kungfu_tpu.elastic` and
  :mod:`kungfu_tpu.store`;
* the **algorithm layer** (distributed optimizers, monitoring,
  adaptation policies) is pure JAX under :mod:`kungfu_tpu.optimizers`
  and :mod:`kungfu_tpu.monitor`.

Top-level convenience API (parity with reference
``srcs/python/kungfu/python/__init__.py``):

    >>> import kungfu_tpu as kf
    >>> kf.init()
    >>> kf.current_rank(), kf.cluster_size()
"""

from kungfu_tpu import ops  # noqa: F401
from kungfu_tpu.python import (  # noqa: F401
    current_rank,
    current_local_rank,
    current_local_size,
    cluster_size,
    detached,
    init,
    finalize,
    propose_new_size,
    resize,
    run_barrier,
    uid,
    current_communicator,
)


def launch_multiprocess(fn, np_, *args, **kwargs):
    """Single-machine multi-process launch (reference
    ``kungfu.cmd.launch_multiprocess``); see
    :func:`kungfu_tpu.runner.mp.launch_multiprocess`."""
    from kungfu_tpu.runner.mp import launch_multiprocess as _lm

    return _lm(fn, np_, *args, **kwargs)

__version__ = "0.1.0"
