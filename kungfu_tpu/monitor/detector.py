"""Failure-detection server.

Parity with the fork's monitor server
(``srcs/go/kungfu/runner/monitorserver/monitor.go``, documented in
``docs/monitor_proposal.md``):

* listens on ``<host>:7756`` for worker heartbeat signals
  (``begin``/``end``/``epoch``/``trainend`` per rank);
* a rank is flagged **down** when a batch ``begin`` has no matching
  ``end`` for ``stall_timeout`` seconds (default 10s, ``monitor.go:111``)
  — or when its heartbeats stop entirely;
* on detection, records ``min`` completed epoch across ranks (the restart
  point) and fans ``otherdown:<minEpoch>`` out to the other hosts'
  detectors so every MonitoredRun restarts in lockstep
  (``monitor.go:116-167``);
* ``trainend`` from all ranks → finish flag.

Consumed by :func:`kungfu_tpu.runner.monitored.monitored_run`.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from kungfu_tpu.monitor import timeline
from kungfu_tpu.utils.log import get_logger

_log = get_logger("detector")

DEFAULT_DETECTOR_PORT = 7756  # reference monitor.go
DEFAULT_STALL_TIMEOUT_S = 10.0
#: allowance while a rank is known to be compiling (first-ever batch, or
#: an explicit ``grace`` signal after a resize re-jit).  SURVEY §7 hard
#: part: a 10 s batch-stall timeout cannot tell a 20-40 s first XLA
#: compile from a dead host — the reference never had to (CUDA kernels
#: launch immediately); on TPU the first step and every post-resize step
#: ARE multi-ten-second stalls on a healthy rank.
DEFAULT_COMPILE_GRACE_S = 120.0
CHECK_PERIOD_S = 1.0


@dataclass
class DetectorResults:
    down_flag: bool = False
    epoch_num: int = 0  # min completed epoch across ranks at detection time
    finish_flag: bool = False


@dataclass
class _RankState:
    last_begin: float = 0.0
    last_end: float = 0.0
    open_begin: bool = False
    epochs_done: int = 0
    finished: bool = False
    seen: bool = False
    first_seen: float = 0.0  # wall time of this incarnation's first signal
    batches_done: int = 0  # completed begin/end pairs
    grace_pending: bool = False  # a grace signal awaits its batch
    in_grace_batch: bool = False  # the current open batch is compile-covered


class DetectorServer:
    """One per runner host.  ``peer_hosts`` are the *other* runner hosts'
    detector addresses for the fan-out."""

    def __init__(
        self,
        expected_ranks: int,
        port: int = DEFAULT_DETECTOR_PORT,
        peer_hosts: Optional[List[str]] = None,
        stall_timeout: float = DEFAULT_STALL_TIMEOUT_S,
        compile_grace: float = DEFAULT_COMPILE_GRACE_S,
        host: str = "0.0.0.0",
        require_all_seen: bool = True,
    ):
        self.expected_ranks = expected_ranks
        self.port = port
        self.peer_hosts = peer_hosts or []
        self.stall_timeout = stall_timeout
        self.compile_grace = max(compile_grace, stall_timeout)
        self.require_all_seen = require_all_seen
        self.results = DetectorResults()
        self._ranks: Dict[int, _RankState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                _log.debug(fmt, *args)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                fanout = None
                try:
                    sig = json.loads(self.rfile.read(n).decode())
                    fanout = srv._on_signal(sig)
                    code = 200
                except (ValueError, KeyError) as e:
                    _log.warning("bad signal: %s", e)
                    code = 400
                self.send_response(code)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")
                if fanout is not None:
                    # after the response, without srv._lock held
                    srv._fanout(fanout)

            def do_GET(self):
                body = json.dumps(
                    {
                        "down": srv.results.down_flag,
                        "epoch": srv.results.epoch_num,
                        "finished": srv.results.finish_flag,
                    }
                ).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._threads: List[threading.Thread] = []

    # -- signal intake ---------------------------------------------------
    def _rank(self, r: int) -> _RankState:
        st = self._ranks.get(r)
        if st is None:
            st = self._ranks[r] = _RankState()
        return st

    def _on_signal(self, sig: dict) -> Optional[dict]:
        """Handle one signal; returns a fan-out payload for the caller to
        post AFTER releasing the lock (a blocked peer must never stall
        heartbeat intake)."""
        kind = sig["kind"]
        now = time.time()
        timeline.event("signal", kind, rank=sig.get("rank"),
                       epoch=sig.get("epoch"))
        with self._lock:
            if kind == "otherdown":
                # a failure report; epoch < 0 means the sender had no rank
                # state (non-main host, or a worker-side quorum-loss
                # escalation) — fall back to what this host knows
                already_down = self.results.down_flag
                self.results.down_flag = True
                epoch = int(sig.get("epoch", -1))
                if epoch < 0:
                    epoch = min((s.epochs_done for s in self._ranks.values()), default=0)
                self.results.epoch_num = epoch
                if sig.get("relay") or already_down:
                    # detector-to-detector relays stop here (one hop, no
                    # cascade), and an already-down round was fanned out
                    # when it started
                    return None
                # worker-originated report (monitor_report_down, the
                # quorum-loss escalation): this detector is the only one
                # that heard it, and once down_flag is set _check_once
                # stops scanning — without a relay the other hosts'
                # MonitoredRuns would never join the restart round
                return {"kind": "otherdown", "epoch": epoch, "relay": True}
            if kind == "otherfinish":
                self.results.finish_flag = True
                return None
            st = self._rank(int(sig["rank"]))
            if st.finished and kind in ("begin", "grace"):
                # a fresh incarnation reusing a finished rank id (restart
                # or rejoin): stale state would either skip monitoring
                # forever or judge its cold compile by the batch timeout
                st = self._ranks[int(sig["rank"])] = _RankState(
                    epochs_done=st.epochs_done
                )
            if not st.seen:
                st.first_seen = now
            st.seen = True
            if kind == "begin":
                st.last_begin, st.open_begin = now, True
                # anchor the grace window at the batch it covers — a
                # pending grace consumed here allows compile_grace FROM
                # THIS BEGIN, however long the announcement preceded it
                st.in_grace_batch = st.grace_pending
                st.grace_pending = False
            elif kind == "end":
                st.last_end, st.open_begin = now, False
                st.batches_done += 1
                st.in_grace_batch = False  # grace dies with its batch
            elif kind == "grace":
                # the worker announces an upcoming known-long stall (a
                # resize re-jit, or a fresh process about to cold-compile)
                st.grace_pending = True
            elif kind == "epoch":
                st.epochs_done = max(st.epochs_done, int(sig["epoch"]) + 1)
            elif kind == "trainend":
                st.finished = True
                if all(s.finished for s in self._ranks.values()) and (
                    len(self._ranks) >= self.expected_ranks or not self.require_all_seen
                ):
                    self.results.finish_flag = True
                    return {"kind": "otherfinish"}
            else:
                raise KeyError(f"unknown signal kind {kind!r}")
        return None

    # -- detection loop --------------------------------------------------
    def _check_once(self) -> None:
        now = time.time()
        fanout = None
        with self._lock:
            if self.results.down_flag or self.results.finish_flag:
                return
            for r, st in self._ranks.items():
                if st.finished:
                    continue
                # compile-aware allowance: the first-ever batch (cold
                # XLA compile, 20-40s on TPU) and any batch announced by
                # a grace signal (resize re-jit) get compile_grace
                # instead of the batch-stall timeout — a healthy TPU
                # rank's first step IS a multi-ten-second stall (SURVEY
                # §7 hard part: slow-compile vs dead-host).  The grace is
                # per-batch: it expires at that batch's `end`, so a rank
                # that compiles fast and then dies is caught on the
                # normal clock.
                compiling = st.batches_done == 0 or st.in_grace_batch
                allow = self.compile_grace if compiling else self.stall_timeout
                stalled_in_batch = st.open_begin and now - st.last_begin > allow
                # a rank that goes silent *between* batches (hung data
                # loader, dead host) has open_begin False — give it a
                # longer grace (3x) on total heartbeat silence
                last_seen = max(st.last_begin, st.last_end)
                silent = (
                    not st.open_begin
                    and last_seen > 0
                    and now - last_seen > max(3 * self.stall_timeout, allow)
                )
                # a rank that only ever signalled grace/epoch and then
                # died has last_begin == last_end == 0, so the
                # last_seen > 0 guard above never fires — "seen but never
                # began a batch within the compile allowance" is a stall
                # too (the compile window is exactly how long a healthy
                # rank may legitimately take to reach its first begin)
                never_began = (
                    last_seen == 0
                    and st.first_seen > 0
                    and now - st.first_seen > self.compile_grace
                )
                if stalled_in_batch or silent or never_began:
                    min_epoch = min(
                        (s.epochs_done for s in self._ranks.values()), default=0
                    )
                    why, since = (
                        ("begin without end", st.last_begin) if stalled_in_batch
                        else ("heartbeat silence", last_seen) if silent
                        else ("signalled but never began a batch", st.first_seen)
                    )
                    _log.warning(
                        "rank %d down (%s for %.0fs); restart epoch %d",
                        r, why, now - since, min_epoch,
                    )
                    timeline.event("down", f"rank{r}", rank=r, why=why,
                                   epoch=min_epoch)
                    self.results.down_flag = True
                    self.results.epoch_num = min_epoch
                    fanout = {"kind": "otherdown", "epoch": min_epoch,
                              "relay": True}
                    break
        if fanout is not None:
            self._fanout(fanout)

    def _fanout(self, sig: dict, attempts: int = 3) -> None:
        """Post to every peer host's detector, outside any lock; a few
        retries with backoff — a lost fan-out strands the receiving host in
        the old round forever, so it is worth insisting.

        One thread per host: the hosts most worth telling about a failure
        are exactly the ones most likely to contain it, so a sequential
        loop head-of-line-blocks every healthy host's restart behind the
        dead host's full retry ladder (observed: ~10 s of added restart
        skew per unreachable predecessor in the list)."""
        from kungfu_tpu import chaos

        ctl = chaos.controller_for(None)
        threads = []
        for host in self.peer_hosts:
            if ctl is not None and ctl.drop_fanout(host):
                continue  # injected fan-out loss (drop_fanout clause)
            t = threading.Thread(
                target=self._fanout_one, args=(host, sig, attempts), daemon=True
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join()

    def _fanout_one(self, host: str, sig: dict, attempts: int) -> None:
        for i in range(attempts):
            try:
                post_signal(host, self.port, sig, timeout=3)
                return
            except OSError as e:
                if i == attempts - 1:
                    _log.warning(
                        "fanout to %s failed after %d attempts: %s", host, attempts, e
                    )
                else:
                    time.sleep(0.5 * (i + 1))

    def _loop(self):
        while not self._stop.wait(CHECK_PERIOD_S):
            self._check_once()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "DetectorServer":
        t1 = threading.Thread(target=self._server.serve_forever, daemon=True)
        t2 = threading.Thread(target=self._loop, daemon=True)
        t1.start()
        t2.start()
        self._threads = [t1, t2]
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()

    def report_local_down(self) -> None:
        """Mark a locally-observed failure (e.g. worker process exit) and
        fan it out to the other hosts' detectors so every MonitoredRun
        restarts in the same round.  A host with no rank state (only the
        main host receives heartbeats) sends epoch=-1 = "unknown" so
        receivers fall back to their own accounting instead of restarting
        from epoch 0."""
        with self._lock:
            if self.results.down_flag:
                return
            if self._ranks:
                min_epoch = min(s.epochs_done for s in self._ranks.values())
            else:
                min_epoch = -1
            self.results.down_flag = True
            self.results.epoch_num = max(min_epoch, 0)
        timeline.event("down", "local", epoch=min_epoch)
        self._fanout({"kind": "otherdown", "epoch": min_epoch, "relay": True})

    def min_epoch(self) -> int:
        """Min completed epochs across ranks seen so far (restart point for
        failures detected via process exit rather than heartbeat stall)."""
        with self._lock:
            return min((s.epochs_done for s in self._ranks.values()), default=0)

    def reset(self, expected_ranks: Optional[int] = None) -> None:
        """Clear state for a relaunch round."""
        with self._lock:
            self._ranks.clear()
            self.results = DetectorResults()
            if expected_ranks is not None:
                self.expected_ranks = expected_ranks


def query_detector(host: str, port: int = DEFAULT_DETECTOR_PORT, timeout: float = 3.0) -> dict:
    """GET a detector's current results — used by non-main hosts to fetch
    the authoritative restart epoch from the main host (the only detector
    that receives worker heartbeats)."""
    with urllib.request.urlopen(f"http://{host}:{port}/", timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def post_signal(host: str, port: int, sig: dict, timeout: float = 5.0) -> None:
    req = urllib.request.Request(
        f"http://{host}:{port}/signal",
        data=json.dumps(sig).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        resp.read()
