"""Adaptation signals: peer latencies, MST topology, interference votes.

Parity with the reference's adaptive-communication machinery:

* latency probing — ``GetPeerLatencies`` (``session/monitoring.go:38-64``):
  ping round-trip times to every peer;
* latency-derived topology — allgather the latency rows, run Prim's MST,
  install the tree with ``set_tree`` (``topology.cpp:84-151`` +
  ``adaptation.cpp``);
* interference detection — per-strategy throughput accounting with a
  0.8-of-best threshold and a cluster-wide majority vote
  (``session/strategy.go:17-56``, ``adaptiveStrategies.go:13-121``).
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from kungfu_tpu.plan.graph import Graph
from kungfu_tpu.plan.mst import minimum_spanning_tree
from kungfu_tpu.plan.topology import gen_default_reduce_graph
from kungfu_tpu.utils.log import get_logger

_log = get_logger("adapt")

INTERFERENCE_THRESHOLD = 0.8  # reference adaptiveStrategies.go


def get_peer_latencies(peer, samples: int = 1) -> List[float]:
    """Ping RTT (seconds) from this peer to every worker; 0.0 for self,
    **+inf for unreachable peers** — an unreachable peer must look
    infinitely expensive to the MST, not free, or the broadcast tree gets
    hubbed on a dead node."""
    from kungfu_tpu.chaos import controller_for

    channel = peer.channel
    chaos = controller_for(peer.chaos_rank())
    out: List[float] = []
    for rank, target in enumerate(peer.cluster.workers):
        if channel is None or target == peer.config.self_id:
            out.append(0.0)
            continue
        best, fails = None, 0
        for _ in range(samples):
            t0 = time.perf_counter()
            if chaos is not None:
                # delay:on=ping, inside the timed window — injected link
                # interference must be visible to the probe the MST
                # re-carve reads, i.e. inflate the measured RTT
                chaos.on_ping(rank)
            if channel.ping(target, timeout=5.0):
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            else:
                fails += 1
                # two consecutive timeouts with no success: the peer is
                # down — don't stack all `samples` timeouts before
                # reporting +inf.  (One timeout alone can be a stall on a
                # live peer, which is exactly what min-of-N filters.)
                if best is None and fails >= 2:
                    break
        out.append(best if best is not None else float("inf"))
    return out


def latency_matrix(peer, samples: int = 1) -> np.ndarray:
    """Allgather every peer's latency row into the full (n, n) matrix."""
    row = np.asarray(get_peer_latencies(peer, samples), dtype=np.float64)
    channel, workers = peer.channel, peer.cluster.workers
    if channel is None:
        return row[None, :]
    rows = channel.allgather_bytes(row.tobytes(), workers, name=f"lat.v{peer.cluster_version}")
    return np.stack([np.frombuffer(r, dtype=np.float64) for r in rows])


def minimum_spanning_tree_from_latencies(peer, samples: int = 1) -> List[int]:
    """The MinimumSpanningTree op analog: measured latencies → forest array."""
    return minimum_spanning_tree(latency_matrix(peer, samples))


def set_tree(engine, forest: List[int]) -> None:
    """Install an explicit broadcast tree on the engine
    (reference ``SetTree``/``AllReduceWith``, ``adaptation.cpp:5``).
    The caller is responsible for the cluster-wide consensus + barrier
    around the swap (reference ``adaptation.go:8-28``)."""
    bcast = Graph.from_forest_array(forest)
    reduce_g = gen_default_reduce_graph(bcast)
    with engine._stats_lock:
        engine._graphs = [(reduce_g, bcast)]
        engine.stats = [[0, 0.0]]
        engine._window = [[0, 0.0]]
        engine.best_throughputs = [0.0]
        # the tree install is a swap: open a fresh eligibility epoch
        engine._colls_at_swap = engine._colls_total
    engine._graph_ser.clear()  # native executor serializations are stale
    engine.strategy = None
    _log.info("installed explicit tree %s", forest)


def check_interference(
    engine,
    reference_throughputs: Optional[List[float]] = None,
    threshold: float = INTERFERENCE_THRESHOLD,
) -> List[int]:
    """Local interference suspicion: strategy-pair indices whose
    recent-window throughput dropped below ``threshold`` x the **recorded
    best** for that pair (reference flags a strategy under 0.8 of its
    monitored best and then majority-votes across peers,
    ``adaptiveStrategies.go:57-121``)."""
    tp = engine.throughputs()  # recent window; updates best_throughputs
    ref = reference_throughputs or engine.best_throughputs
    return [
        i for i, (t, r) in enumerate(zip(tp, ref))
        if r > 0 and t > 0 and t < threshold * r
    ]


def majority_vote_interference(peer, suspected: bool) -> bool:
    """Cluster-wide majority vote over local suspicion flags."""
    engine = peer.engine()
    if engine is None:
        return suspected
    # record=False: the 8-byte vote must not land in the throughput window
    # it is judging, or the next check compares a tiny-transfer rate
    # against the data-plane best and flags phantom interference
    votes = engine.all_reduce(
        np.array([1 if suspected else 0], np.int64), op="sum", record=False
    )
    return int(votes[0]) * 2 > peer.size()
