"""kf-sentinel detector math: deterministic changepoint + burn rates.

ONE pure-stdlib implementation shared by the two consumers, exactly the
:mod:`kungfu_tpu.monitor.skew` doctrine: the *online* plane (the
:class:`~kungfu_tpu.monitor.sentinel.Sentinel` running inside the
aggregator) and the *offline* ``kfhist --verdict`` reader both call
:func:`changepoint` over the same sample window, so a live alert and the
post-mortem replay of the durable history can never disagree — asserted
in tests and in the ``bench.py --sentinel`` gate.

The test is a **median-shift vs MAD** score, chosen for the same reasons
skew.py picks medians over means:

* *deterministic* — pure arithmetic over sorted copies, no RNG, no
  wall-clock; the same samples always yield the same verdict (the
  kf-det replay doctrine applied to alerting);
* *robust* — one straggler step (a GC pause, a preemption blip) moves a
  mean but not a median; MAD ignores outliers a standard deviation
  would square into significance;
* *scale-free* — the score is ``|median shift| / MAD``, so one
  threshold serves step times in seconds and TTFTs in milliseconds.

A quiet series has MAD 0, which would make any noise infinitely
significant — the scale is floored at ``rel_floor x |baseline median|``
(and an absolute epsilon), so a flat series needs a real *relative*
move, not a float ulp, to alert.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

#: samples per comparison window (the "recent" side; the baseline is the
#: ``BASELINE_WINDOWS`` windows before it)
DEFAULT_WINDOW = 8
#: baseline length in windows — changepoint() truncates its input to
#: ``(BASELINE_WINDOWS + 1) * window`` samples so any caller holding AT
#: LEAST that many samples computes the identical verdict (the
#: offline==online equality depends on this normalization)
BASELINE_WINDOWS = 3
#: MAD multiples of median shift before a series is "shifted"
DEFAULT_THRESHOLD = 4.0
#: scale floor as a fraction of the baseline median (quiet-series guard)
DEFAULT_REL_FLOOR = 0.02
#: absolute scale floor (a series sitting at exactly 0 stays quiet)
ABS_FLOOR = 1e-9


def median(values: Sequence[float]) -> float:
    """Median over a copy (lower-middle interpolated for even counts) —
    deterministic, input order irrelevant."""
    xs = sorted(float(v) for v in values)
    n = len(xs)
    if n == 0:
        raise ValueError("median of empty series")
    mid = n // 2
    if n % 2:
        return xs[mid]
    return (xs[mid - 1] + xs[mid]) / 2.0


def mad(values: Sequence[float], center: Optional[float] = None) -> float:
    """Median absolute deviation about ``center`` (default: the
    median) — the robust spread estimate the shift score divides by."""
    c = median(values) if center is None else center
    return median([abs(float(v) - c) for v in values])


def changepoint(values: Sequence[float],
                window: int = DEFAULT_WINDOW,
                threshold: float = DEFAULT_THRESHOLD,
                rel_floor: float = DEFAULT_REL_FLOOR) -> Optional[dict]:
    """The shared offline/online changepoint verdict for one series.

    Splits the (normalized) sample tail into ``baseline`` (older) and
    ``recent`` (last ``window`` samples) and scores the median shift in
    MAD units.  Returns ``None`` until at least two windows of samples
    exist — a detector with no baseline has no standing to alert —
    otherwise a verdict dict whose ``shifted`` bool is the alert signal
    and whose numbers are the evidence the incident bundle carries.
    """
    window = max(2, int(window))
    xs = [float(v) for v in values]
    # normalize to the bounded tail EVERY consumer agrees on: a caller
    # holding a longer history must not compute a different baseline
    xs = xs[-(BASELINE_WINDOWS + 1) * window:]
    if len(xs) < 2 * window:
        return None
    baseline, recent = xs[:-window], xs[-window:]
    base_med = median(baseline)
    base_mad = mad(baseline, base_med)
    recent_med = median(recent)
    shift = recent_med - base_med
    scale = max(base_mad, rel_floor * abs(base_med) / max(threshold, 1.0),
                ABS_FLOOR)
    score = abs(shift) / scale
    shifted = score >= threshold
    return {
        "n": len(xs),
        "window": window,
        "baseline_n": len(baseline),
        "base_median": round(base_med, 9),
        "base_mad": round(base_mad, 9),
        "recent_median": round(recent_med, 9),
        "shift": round(shift, 9),
        "score": round(score, 6),
        "threshold": threshold,
        "shifted": shifted,
        "direction": ("up" if shift > 0 else "down") if shifted else "flat",
    }


def window_verdicts(series: Dict[str, Sequence[float]],
                    window: int = DEFAULT_WINDOW,
                    threshold: float = DEFAULT_THRESHOLD) -> Dict[str, dict]:
    """:func:`changepoint` per named series, sorted keys, Nones dropped —
    the ``verdicts`` object both ``/alerts`` and ``kfhist --verdict``
    publish (one call site shape, so the equality assertion is a plain
    ``==`` over JSON)."""
    out: Dict[str, dict] = {}
    for name in sorted(series):
        v = changepoint(series[name], window=window, threshold=threshold)
        if v is not None:
            out[name] = v
    return out


def burn_fraction(values: Sequence[float], budget: float,
                  window: int) -> Optional[dict]:
    """Fraction of the last ``window`` samples over ``budget`` — one leg
    of a multi-window burn-rate rule.  ``None`` until the window is
    full (a part-filled window would alias a single bad sample into a
    high rate)."""
    window = max(1, int(window))
    xs = [float(v) for v in values]
    if len(xs) < window:
        return None
    tail = xs[-window:]
    over = sum(1 for v in tail if v > budget)
    return {"window": window, "over": over,
            "frac": round(over / window, 6)}


def slo_burn(values: Sequence[float], budget: float,
             short_window: int, long_window: int,
             short_frac: float, long_frac: float) -> Optional[dict]:
    """The classic two-window burn-rate test: alert only when BOTH the
    short window (fast burn — it is happening now) and the long window
    (sustained burn — it is not one blip) exceed their budget-violation
    fractions.  ``None`` until the long window fills."""
    short = burn_fraction(values, budget, short_window)
    long = burn_fraction(values, budget, long_window)
    if short is None or long is None:
        return None
    burning = short["frac"] >= short_frac and long["frac"] >= long_frac
    return {
        "budget": budget,
        "short": short,
        "long": long,
        "short_frac": short_frac,
        "long_frac": long_frac,
        "burning": burning,
    }
