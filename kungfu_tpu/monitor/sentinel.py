"""kf-sentinel: the aggregator's judging plane — history, detection, alerts.

kfmon (PR 9) made the cluster *visible*; this module makes it
*accountable*.  A :class:`Sentinel` attached to the
:class:`~kungfu_tpu.monitor.aggregator.ClusterAggregator` samples the
cluster rollup on a period, and per sample:

1. **remembers** — appends the rollup series (and each rank's condensed
   row) to the durable :mod:`~kungfu_tpu.monitor.history` rings under
   ``KF_SENTINEL_DIR``, so ``scripts/kfhist`` can answer "when did step
   time start drifting" long after the run — and after the process — is
   gone;
2. **judges** — runs the deterministic detector
   (:mod:`~kungfu_tpu.monitor.detect`: median-shift changepoints per
   series, two-window SLO burn rates, watermark rules) over its rolling
   sample buffers.  The buffers are capped at EXACTLY the tail
   :func:`~kungfu_tpu.monitor.detect.changepoint` normalizes to, so the
   online verdict and ``kfhist --verdict`` replayed over the durable
   history are the SAME object — asserted in tests and the ``bench.py
   --sentinel`` gate (the skew.py one-implementation doctrine applied to
   alerting);
3. **alerts** — a rule crossing its line is edge-triggered ONCE (the
   ``_active`` set; no wall-clock cooldown, so fake-clock tests are
   deterministic): ``timeline.event("alert", rule, force=True)`` ticks
   ``kf_alerts_total{rule=...}`` and lands in the flight recorder, and
   an **incident flight record** — bounded evidence: the recent history
   window, the merged timeline tail, the kf-xray verdict naming the
   culprit rank/edge, the detector verdicts, and the active config
   vector — is atomically dumped under ``KF_SENTINEL_DIR/incidents/``.

Cost contract: with ``KF_SENTINEL_DIR`` unset there IS no sentinel —
:func:`Sentinel.from_env` returns ``None``, the aggregator's hook is a
``None`` check, and ``/cluster`` is byte-identical to the pre-sentinel
plane (asserted in tests).  Attached, the work is one
``cluster_view()`` + O(series) arithmetic per ``KF_SENTINEL_PERIOD``,
outside the aggregator lock.

Env reads are direct ``os.environ`` via the mirror constants below
(defaults pinned equal to :func:`kungfu_tpu.utils.envs.sentinel_knobs`
and :class:`kungfu_tpu.serve.slo.SLORules` by tests): this module must
stay importable from the stubbed ``kfhist``/``kftop`` context where the
jax-adjacent packages cannot load.  Stdlib-only, like every monitor/
module.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Callable, Dict, List, Optional

from kungfu_tpu.monitor import detect, history, timeline
from kungfu_tpu.monitor import ledger as ledgerlib
from kungfu_tpu.monitor.aggregator import field, sum_metric

# env mirror constants (utils/envs.py registers the same tokens;
# sentinel_knobs() pins the defaults both sides must agree on)
DIR_ENV = history.DIR_ENV
PERIOD_ENV = "KF_SENTINEL_PERIOD"
WINDOW_ENV = "KF_SENTINEL_WINDOW"
THRESHOLD_ENV = "KF_SENTINEL_THRESHOLD"
MFU_FLOOR_ENV = "KF_SENTINEL_MFU_FLOOR"
STEP_CEILING_ENV = "KF_SENTINEL_STEP_CEILING_S"
WARMUP_ENV = "KF_SENTINEL_WARMUP_STEPS"
INCIDENT_WINDOW_ENV = "KF_SENTINEL_INCIDENT_WINDOW"
SLO_SHORT_ENV = "KF_SENTINEL_SLO_SHORT"
SLO_LONG_ENV = "KF_SENTINEL_SLO_LONG"
# the serving SLO budgets are the SAME tokens serve/slo.py steers by:
# one knob, two consumers (target and alarm must never disagree)
TTFT_BUDGET_ENV = "KF_SERVE_SLO_TTFT_MS"
E2E_BUDGET_ENV = "KF_SERVE_SLO_E2E_MS"

DEFAULT_PERIOD_S = 1.0
DEFAULT_WARMUP_STEPS = 32
DEFAULT_INCIDENT_WINDOW = 64
DEFAULT_SLO_SHORT = 6
DEFAULT_SLO_LONG = 24
DEFAULT_SLO_SHORT_FRAC = 0.5
DEFAULT_SLO_LONG_FRAC = 0.25
DEFAULT_TTFT_BUDGET_MS = 500.0
DEFAULT_E2E_BUDGET_MS = 5000.0

#: series the changepoint rules judge, and the shift direction that is
#: BAD (a step-time drop or an MFU rise is an improvement, not an
#: incident) — rule names are ``regress:<series>``
CHANGEPOINT_SERIES = {
    "step_time_s": "up",
    "ttft_ms": "up",
    "e2e_ms": "up",
    "mfu": "down",
    # kf-pulse: a RISING gradient noise scale means the current batch
    # size stopped averaging the noise away — the convergence-efficiency
    # regression the GNS→batch-size autopilot (ROADMAP item 4) steers by
    "gns": "up",
}

#: merged timeline events an incident flight record carries at most
INCIDENT_EVENT_TAIL = 256

#: sentinel history stream names
CLUSTER_STREAM = "cluster"


def _f(env: str, default: float) -> float:
    try:
        return float(os.environ.get(env, "") or default)
    except ValueError:
        return default


def _i(env: str, default: int) -> int:
    try:
        return int(os.environ.get(env, "") or default)
    except ValueError:
        return default


def rank_stream(rank: int) -> str:
    return f"rank-{int(rank)}"


def extract_series(view: dict) -> Dict[str, float]:
    """The cluster-rollup sample one ``/cluster`` view yields: the flat
    ``{series: float}`` dict that is appended to the durable ``cluster``
    stream AND fed to the online detector — ONE extraction, so the two
    can never see different numbers.  A quantity the view cannot supply
    yet (no serving section, no MFU gauge) is simply absent: part-time
    series accumulate identically online and offline."""
    out: Dict[str, float] = {}
    rows = field(view, "ranks") or []
    step_times = [field(r, "step_time_s") for r in rows]
    step_times = [float(v) for v in step_times if v is not None]
    if step_times:
        out["step_time_s"] = sum(step_times) / len(step_times)
    steps = [field(r, "step") for r in rows]
    steps = [int(s) for s in steps if isinstance(s, int) and s >= 0]
    if steps:
        out["step"] = float(max(steps))
    egress = sum(float((field(r, "net") or {}).get("egress_bytes", 0))
                 for r in rows)
    if rows:
        out["egress_bytes"] = egress
    opt_bytes = sum(sum_metric(field(r, "gauges"), "kf_opt_state_bytes")
                    for r in rows)
    if opt_bytes:
        out["opt_state_bytes"] = opt_bytes
    mem = sum((field(r, "gauges") or {}).get(
        'kf_device_memory_bytes{kind="in_use"}', 0.0) for r in rows)
    if mem:
        out["device_mem_bytes"] = float(mem)
    compiles = sum(sum_metric(field(r, "counters"), "kf_jit_compiles_total")
                   for r in rows)
    if compiles:
        out["jit_compiles"] = float(compiles)
    # kf-pulse gauges: every reporting rank publishes the SAME collective
    # estimate (the inner mean is a collective), so the rollup is the
    # mean over the ranks carrying the gauge — identical per-rank values
    # pass through unchanged, and a straggler snapshot cannot double-count
    gns = [(field(r, "gauges") or {}).get("kf_gns") for r in rows]
    gns = [float(v) for v in gns if v is not None]
    if gns:
        out["gns"] = sum(gns) / len(gns)
    gvar = [(field(r, "gauges") or {}).get("kf_grad_variance") for r in rows]
    gvar = [float(v) for v in gvar if v is not None]
    if gvar:
        out["grad_variance"] = sum(gvar) / len(gvar)
    xr = field(view, "xray")
    if xr:
        mfu = field(xr, "mfu")
        if mfu:
            vals = [float(v) for v in mfu.values()]
            out["mfu"] = sum(vals) / len(vals)
        for ph, v in (field(xr, "phase_seconds") or {}).items():
            out[f"phase_{ph}"] = float(v)
    srv = field(view, "serving")
    if srv:
        ttft = field(srv, "ttft_ms")
        if ttft is not None:
            out["ttft_ms"] = float(ttft)
        e2e = field(srv, "e2e_ms")
        if e2e is not None:
            out["e2e_ms"] = float(e2e)
        out["kv_bytes"] = float(field(srv, "kv_bytes") or 0)
    return out


class Sentinel:
    """The aggregator's attached judge (see module docstring).

    Constructor arguments mirror the sentinel env knobs above;
    :func:`from_env` is the production path and returns ``None`` when
    ``KF_SENTINEL_DIR`` is unset — the whole plane gated on one token.
    """

    def __init__(self, root: str,
                 keep_bytes: Optional[int] = None,
                 period_s: float = DEFAULT_PERIOD_S,
                 window: int = detect.DEFAULT_WINDOW,
                 threshold: float = detect.DEFAULT_THRESHOLD,
                 mfu_floor: float = 0.0,
                 step_ceiling_s: float = 0.0,
                 warmup_steps: int = DEFAULT_WARMUP_STEPS,
                 incident_window: int = DEFAULT_INCIDENT_WINDOW,
                 slo_budgets: Optional[Dict[str, float]] = None,
                 slo_short: int = DEFAULT_SLO_SHORT,
                 slo_long: int = DEFAULT_SLO_LONG,
                 slo_short_frac: float = DEFAULT_SLO_SHORT_FRAC,
                 slo_long_frac: float = DEFAULT_SLO_LONG_FRAC):
        self.root = root
        self.period_s = float(period_s)
        self.window = max(2, int(window))
        self.threshold = float(threshold)
        self.mfu_floor = float(mfu_floor)
        self.step_ceiling_s = float(step_ceiling_s)
        self.warmup_steps = int(warmup_steps)
        self.incident_window = max(1, int(incident_window))
        self.slo_budgets = dict(slo_budgets) if slo_budgets else {
            "ttft_ms": DEFAULT_TTFT_BUDGET_MS,
            "e2e_ms": DEFAULT_E2E_BUDGET_MS,
        }
        self.slo_short = max(1, int(slo_short))
        self.slo_long = max(self.slo_short, int(slo_long))
        self.slo_short_frac = float(slo_short_frac)
        self.slo_long_frac = float(slo_long_frac)
        self._lock = threading.Lock()
        self._cluster_ring = history.HistoryRing(root, CLUSTER_STREAM,
                                                 keep_bytes=keep_bytes)
        # the decision ledger shares the sentinel's root and detector
        # knobs; ledger_for() registers the instance so every actor's
        # env-keyed record_decision() lands in the SAME stream whose
        # sample feed _observe_locked drives
        self.ledger = ledgerlib.ledger_for(root, window=self.window,
                                           threshold=self.threshold,
                                           keep_bytes=keep_bytes)
        self._rank_rings: Dict[int, history.HistoryRing] = {}
        self._keep_bytes = keep_bytes
        # per-series rolling buffers, capped at EXACTLY the tail
        # detect.changepoint() self-normalizes to — the offline replay
        # of the durable history computes the identical verdicts
        cap = (detect.BASELINE_WINDOWS + 1) * self.window
        self._cap = cap
        self._samples: Dict[str, deque] = {}
        self._records = 0                  # cluster records appended
        self._recent: deque = deque(maxlen=self.incident_window)
        self._last_sample_t: Optional[float] = None
        self._active: set = set()          # edge-trigger state
        self._alerts: List[dict] = []      # fired-alert log (bounded)
        self._max_alerts = 256
        self._incident_seq = 0
        self._compile_baseline: Optional[float] = None

    # -- construction -----------------------------------------------------
    @classmethod
    def from_env(cls) -> Optional["Sentinel"]:
        """The production constructor: ``None`` (no sentinel, no cost)
        unless ``KF_SENTINEL_DIR`` names the history root."""
        root = (os.environ.get(DIR_ENV, "") or "").strip()
        if not root:
            return None
        return cls(
            root,
            keep_bytes=history.keep_bytes_from_env(),
            period_s=_f(PERIOD_ENV, DEFAULT_PERIOD_S),
            window=_i(WINDOW_ENV, detect.DEFAULT_WINDOW),
            threshold=_f(THRESHOLD_ENV, detect.DEFAULT_THRESHOLD),
            mfu_floor=_f(MFU_FLOOR_ENV, 0.0),
            step_ceiling_s=_f(STEP_CEILING_ENV, 0.0),
            warmup_steps=_i(WARMUP_ENV, DEFAULT_WARMUP_STEPS),
            incident_window=_i(INCIDENT_WINDOW_ENV, DEFAULT_INCIDENT_WINDOW),
            slo_budgets={
                "ttft_ms": _f(TTFT_BUDGET_ENV, DEFAULT_TTFT_BUDGET_MS),
                "e2e_ms": _f(E2E_BUDGET_ENV, DEFAULT_E2E_BUDGET_MS),
            },
            slo_short=_i(SLO_SHORT_ENV, DEFAULT_SLO_SHORT),
            slo_long=_i(SLO_LONG_ENV, DEFAULT_SLO_LONG),
        )

    # -- aggregator hook --------------------------------------------------
    def on_ingest(self, agg) -> None:
        """The aggregator's post-ingest hook (called OUTSIDE its lock,
        guarded by the caller): samples at most once per ``period_s`` of
        the aggregator's clock — which is the fake clock in tests, so
        sampling cadence is deterministic."""
        now = agg._time()
        with self._lock:
            if (self._last_sample_t is not None
                    and self.period_s > 0
                    and now - self._last_sample_t < self.period_s):
                return
            self._last_sample_t = now
        view = agg.cluster_view()
        events = agg._all_events()
        self.observe(view, events)

    # -- the sample -------------------------------------------------------
    def observe(self, view: dict, events: Optional[List[dict]] = None
                ) -> List[dict]:
        """One sentinel sample over a ``/cluster`` view: record history,
        update buffers, evaluate every rule, fire edge-triggered alerts.
        Returns the alerts fired BY THIS SAMPLE (usually empty)."""
        with self._lock:
            return self._observe_locked(view, events or [])

    def _observe_locked(self, view: dict, events: List[dict]) -> List[dict]:
        series = extract_series(view)
        wall = field(view, "wall")
        record = {
            "kfhist": 1,
            "wall": wall,
            "series": series,
            "stale": field(view, "stale") or [],
            "straggler": field(view, "straggler"),
        }
        self._cluster_ring.append(record)
        self._records += 1
        self._recent.append(record)
        # the decision ledger sees EXACTLY the records the cluster
        # stream holds, in order — its series_n positions are therefore
        # replayable offline from the durable stream (kfhist --decisions)
        try:
            self.ledger.on_sample(record)
        except Exception:  # noqa: BLE001 - the join must not take sampling down
            pass
        for row in field(view, "ranks") or []:
            rank = field(row, "rank")
            if not isinstance(rank, int):
                continue
            ring = self._rank_rings.get(rank)
            if ring is None:
                ring = self._rank_rings[rank] = history.HistoryRing(
                    self.root, rank_stream(rank),
                    keep_bytes=self._keep_bytes)
            ring.append({
                "kfhist": 1,
                "wall": wall,
                "step": field(row, "step"),
                "step_time_s": field(row, "step_time_s"),
                "strategy": field(row, "strategy"),
                "net": field(row, "net") or {},
            })
        for name, value in series.items():
            buf = self._samples.get(name)
            if buf is None:
                buf = self._samples[name] = deque(maxlen=self._cap)
            buf.append(value)
        firing = self._evaluate(view, series)
        fired = []
        fired_rules = set(firing)
        for rule in sorted(fired_rules - self._active):
            alert = {
                "rule": rule,
                "wall": wall,
                "evidence": firing[rule],
            }
            self._fire(alert, view, events)
            fired.append(alert)
        # edge-trigger bookkeeping: a rule must RECOVER before it can
        # fire again (no wall-clock cooldown — deterministic under fake
        # clocks)
        self._active = fired_rules
        return fired

    # -- rules ------------------------------------------------------------
    def verdicts(self) -> Dict[str, dict]:
        """The per-series changepoint verdicts over the current buffers
        — the SAME object ``kfhist --verdict`` rebuilds from the durable
        history (asserted in tests/bench)."""
        return detect.window_verdicts(
            {k: list(v) for k, v in self._samples.items()},
            window=self.window, threshold=self.threshold)

    def _evaluate(self, view: dict,
                  series: Dict[str, float]) -> Dict[str, dict]:
        """Every rule over the current buffers: ``{rule: evidence}`` of
        the rules satisfied RIGHT NOW (edge detection is the caller's)."""
        firing: Dict[str, dict] = {}
        verdicts = self.verdicts()
        for name, bad_direction in CHANGEPOINT_SERIES.items():
            v = verdicts.get(name)
            if v and v["shifted"] and v["direction"] == bad_direction:
                firing[f"regress:{name}"] = v
        for name, budget_ms in self.slo_budgets.items():
            buf = self._samples.get(name)
            if not buf:
                continue
            burn = detect.slo_burn(list(buf), budget_ms,
                                   self.slo_short, self.slo_long,
                                   self.slo_short_frac, self.slo_long_frac)
            if burn and burn["burning"]:
                firing[f"sloburn:{name}"] = burn
        if self.mfu_floor > 0 and 0 < series.get("mfu", self.mfu_floor + 1) \
                < self.mfu_floor:
            firing["watermark:mfu"] = {"mfu": series["mfu"],
                                       "floor": self.mfu_floor}
        if self.step_ceiling_s > 0 \
                and series.get("step_time_s", 0.0) > self.step_ceiling_s:
            firing["watermark:step_time"] = {
                "step_time_s": series["step_time_s"],
                "ceiling_s": self.step_ceiling_s}
        stale_slices = field(view, "stale_slices") or []
        if stale_slices:
            firing["watermark:stale_slice"] = {"slices": stale_slices}
        ckpt = self._ckpt_stale(view)
        if ckpt:
            firing["watermark:ckpt_age"] = {"ranks": ckpt}
        recompile = self._recompile_steady(series)
        if recompile:
            firing["watermark:recompile_steady"] = recompile
        return firing

    @staticmethod
    def _ckpt_stale(view: dict) -> List[dict]:
        """kftop's CKPT STALE condition, rule-ified: manifest age > 3x
        the persist period on any rank (one condition, two consumers —
        the dashboard alarm and this alert must agree)."""
        out = []
        for row in field(view, "ranks") or []:
            gauges = field(row, "gauges") or {}
            period = sum_metric(gauges, "kf_ckpt_period_seconds")
            age = sum_metric(gauges, "kf_ckpt_age_seconds")
            if period > 0 and age > 3 * period:
                out.append({"rank": field(row, "rank"),
                            "age_s": age, "period_s": period})
        return out

    def _recompile_steady(self, series: Dict[str, float]) -> Optional[dict]:
        """XLA recompiles AFTER warmup: the baseline compile count is
        pinned the first sample past ``warmup_steps``; any growth beyond
        it means a shape leak / cache bust mid-run (docs/sentinel.md)."""
        step = series.get("step")
        compiles = series.get("jit_compiles")
        if step is None or compiles is None or step <= self.warmup_steps:
            return None
        if self._compile_baseline is None:
            self._compile_baseline = compiles
            return None
        if compiles > self._compile_baseline:
            return {"compiles": compiles,
                    "baseline": self._compile_baseline,
                    "after_step": self.warmup_steps}
        return None

    # -- alert fan-out ----------------------------------------------------
    def _fire(self, alert: dict, view: dict, events: List[dict]) -> None:
        rule = alert["rule"]
        self._alerts.append(alert)
        del self._alerts[:-self._max_alerts]
        # counted kind: ticks kf_alerts_total{rule=...} even with
        # tracing off; force=True lands it in the flight recorder ring
        # regardless, so the dump of a broken run shows its alerts
        timeline.event("alert", rule, force=True, wall=alert["wall"])
        try:
            alert["incident"] = self._dump_incident(alert, view, events)
        except OSError:
            # an unwritable incident dir must not take the plane down;
            # the alert itself (counter, timeline, /alerts) still fired
            alert["incident"] = None

    def _dump_incident(self, alert: dict, view: dict,
                       events: List[dict]) -> str:
        """The incident flight record: bounded evidence, atomically
        written (a crash mid-dump leaves no torn bundle)."""
        self._incident_seq += 1
        safe_rule = alert["rule"].replace(":", "-").replace("/", "-")
        strategies = {str(field(r, "rank")): field(r, "strategy") or ""
                      for r in field(view, "ranks") or []}
        bundle = {
            "kfincident": 1,
            "wall": alert["wall"],
            "alert": {k: alert[k] for k in ("rule", "wall", "evidence")},
            # history_n lets the offline replay select the SAME record
            # prefix this verdict was computed over: kfhist --verdict
            # --upto <history_n> must reproduce `verdicts` exactly
            "history_n": self._records,
            "history": list(self._recent),
            "timeline_tail": events[-INCIDENT_EVENT_TAIL:],
            "xray": field(view, "xray"),
            "verdicts": self.verdicts(),
            "config": {
                "cluster": field(view, "cluster"),
                "strategies": strategies,
                "serving": field(view, "serving"),
                "stale": field(view, "stale") or [],
                "active_alerts": sorted(self._active | {alert["rule"]}),
            },
        }
        inc_dir = os.path.join(self.root, "incidents")
        os.makedirs(inc_dir, exist_ok=True)
        path = os.path.join(
            inc_dir, f"incident-{self._incident_seq:06d}-{safe_rule}.json")
        history._atomic_write(
            path, json.dumps(bundle, sort_keys=True).encode("utf-8"))
        return path

    # -- read side --------------------------------------------------------
    def alerts_view(self) -> dict:
        """The ``/alerts`` JSON: active rules, the fired-alert log, and
        the live detector verdicts."""
        with self._lock:
            return {
                "kfsentinel": 1,
                "active": sorted(self._active),
                "alerts": [
                    {k: a.get(k) for k in
                     ("rule", "wall", "evidence", "incident")}
                    for a in self._alerts
                ],
                "verdicts": self.verdicts(),
                "records": self._records,
                "window": self.window,
                "threshold": self.threshold,
                "decisions": self.ledger.summary(),
            }
