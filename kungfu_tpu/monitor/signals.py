"""Worker-side heartbeat signal senders.

Parity with reference ``kungfu/cmd/__init__.py:11-29`` (monitor_batch_begin
/ monitor_batch_end / monitor_epoch_end / monitor_train_end) →
``libkungfu-comm/send.go:32-57`` (POST to the rank-0 host's detector at
:7756).  The detector address comes from ``KF_MONITOR_ADDR`` (set by the
monitored runner); with it unset these are no-ops, so instrumented training
scripts run unchanged under plain ``kfrun``.

Failures to deliver are swallowed by design: a dying detector must not
take the training job down with it.
"""

from __future__ import annotations

import os
from typing import Optional

from kungfu_tpu.monitor.detector import DEFAULT_DETECTOR_PORT, post_signal
from kungfu_tpu.utils.log import get_logger

_log = get_logger("signals")

MONITOR_ADDR_ENV = "KF_MONITOR_ADDR"


def _target() -> Optional[tuple]:
    addr = os.environ.get(MONITOR_ADDR_ENV)
    if not addr:
        return None
    if ":" in addr:
        host, port = addr.rsplit(":", 1)
        return host, int(port)
    return addr, DEFAULT_DETECTOR_PORT


def _send(sig: dict) -> None:
    target = _target()
    if target is None:
        return
    try:
        post_signal(target[0], target[1], sig, timeout=3)
    except OSError as e:
        _log.debug("signal %s not delivered: %s", sig.get("kind"), e)


def monitor_batch_begin(rank: int) -> None:
    _send({"kind": "begin", "rank": rank})


def monitor_batch_end(rank: int) -> None:
    _send({"kind": "end", "rank": rank})


def monitor_epoch_end(rank: int, epoch: int) -> None:
    _send({"kind": "epoch", "rank": rank, "epoch": epoch})


def monitor_train_end(rank: int) -> None:
    _send({"kind": "trainend", "rank": rank})
