"""Worker-side heartbeat signal senders.

Parity with reference ``kungfu/cmd/__init__.py:11-29`` (monitor_batch_begin
/ monitor_batch_end / monitor_epoch_end / monitor_train_end) →
``libkungfu-comm/send.go:32-57`` (POST to the rank-0 host's detector at
:7756).  The detector address comes from ``KF_MONITOR_ADDR`` (set by the
monitored runner); with it unset these are no-ops, so instrumented training
scripts run unchanged under plain ``kfrun``.

Failures to deliver are swallowed by design: a dying detector must not
take the training job down with it.  Per-batch begin/end heartbeats are
fire-and-forget (the next batch re-sends fresher liveness anyway), but
``epoch``/``trainend`` are *bookkeeping* — a dropped epoch signal makes
the post-failure restart resume from an older epoch (observed on a
loaded box: the detector's accept backlog ate an epoch POST and the job
re-trained an epoch it had finished) — so those retry a few times
before giving up.
"""

from __future__ import annotations

import http.client
import os
import time
from typing import Optional

from kungfu_tpu.monitor.detector import DEFAULT_DETECTOR_PORT, post_signal
from kungfu_tpu.utils.log import get_logger

_log = get_logger("signals")

MONITOR_ADDR_ENV = "KF_MONITOR_ADDR"


def _target() -> Optional[tuple]:
    addr = os.environ.get(MONITOR_ADDR_ENV)
    if not addr:
        return None
    if ":" in addr:
        host, port = addr.rsplit(":", 1)
        return host, int(port)
    return addr, DEFAULT_DETECTOR_PORT


def _send(sig: dict, attempts: int = 1) -> None:
    target = _target()
    if target is None:
        return
    for i in range(attempts):
        try:
            post_signal(target[0], target[1], sig, timeout=3)
            return
        # HTTPException is NOT an OSError (e.g. BadStatusLine from a
        # half-dead detector); both must be swallowed or the monitoring
        # sidecar's death takes the training job down with it
        except (OSError, http.client.HTTPException) as e:
            if i + 1 < attempts:
                time.sleep(0.2 * (i + 1))
            else:
                _log.debug("signal %s not delivered: %s", sig.get("kind"), e)


def monitor_batch_begin(rank: int) -> None:
    _send({"kind": "begin", "rank": rank})


def monitor_batch_end(rank: int) -> None:
    _send({"kind": "end", "rank": rank})


def monitor_epoch_end(rank: int, epoch: int) -> None:
    _send({"kind": "epoch", "rank": rank, "epoch": epoch}, attempts=3)


def monitor_compile_grace(rank: int) -> None:
    """Announce an upcoming known-long stall (resize re-jit): the
    detector extends this rank's allowance to its compile-grace window
    instead of the batch-stall timeout.  Retried — a dropped grace signal
    turns a healthy recompile into a spurious cluster restart."""
    _send({"kind": "grace", "rank": rank}, attempts=3)


def monitor_train_end(rank: int) -> None:
    _send({"kind": "trainend", "rank": rank}, attempts=3)


def monitor_report_down(epoch: int = -1) -> None:
    """Worker-side escalation to the detector-driven full restart — the
    last resort when in-flight shrink recovery loses quorum
    (``elastic/shrink.py``).  ``epoch=-1`` = "sender has no epoch
    accounting": the detector falls back to its own records instead of
    restarting from epoch 0.  Retried: this IS the recovery path, a
    dropped signal strands the job."""
    _send({"kind": "otherdown", "epoch": epoch}, attempts=3)
