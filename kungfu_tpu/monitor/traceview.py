"""``kftrace``: merge per-rank flight-recorder dumps, find stragglers.

Consumes the JSONL dumps written by :mod:`kungfu_tpu.monitor.timeline`
(one file per rank/process) and produces:

* ``kftrace merge -o trace.json r0.jsonl r1.jsonl ...`` — one
  Chrome-trace/Perfetto JSON: every span becomes a complete (``ph: X``)
  event on the emitting rank's track, every mark an instant (``ph: i``),
  so ``chrome://tracing`` / https://ui.perfetto.dev render the
  cross-rank timeline directly;
* ``kftrace report ...`` — the straggler report: per-collective
  cross-rank skew (same rendezvous tag compared across ranks — duration
  comparison, immune to wall-clock skew between hosts), the slowest rank
  per step window, and the overlap of fault events (chaos injections,
  peer deadlines, down verdicts) with latency spikes — "was a fault in
  flight when this collective stalled?" answered mechanically;
* ``kftrace --critical-path dumps...`` — the kf-xray report: per-step
  critical-path attribution (compute / comm_exposed / comm_hidden /
  input_stall / straggler_wait), the culprit rank and edge, and the
  longest dependency chain of the widest step
  (:mod:`kungfu_tpu.monitor.xray` — the SAME implementation the live
  aggregator serves under ``/cluster``, docs/xray.md);
* ``kftrace --self-check [dumps...]`` — dump schema validation (with no
  arguments it synthesizes a dump via the live timeline module —
  covering the collective/chaos/mark kinds AND the serving-plane
  ``serve``/``request`` kinds — and round-trips it), wired into
  ``scripts/check.sh``.

Deliberately stdlib-only so the CLI runs in bare CI images (the
``scripts/kftrace`` launcher stubs the package like ``scripts/kflint``).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence, Tuple

# the straggler math lives in monitor/skew.py — ONE implementation shared
# with the live cluster aggregator, so the online /cluster skew section
# and this offline report cannot disagree about the same events.  The
# re-exports keep the PR 4 public surface of this module intact.
from kungfu_tpu.monitor.skew import (  # noqa: F401  (re-exported API)
    FAULT_KINDS,
    FAULT_SLACK_S,
    SPIKE_FACTOR,
    fault_overlaps,
    skew_rows,
    slowest_rank_per_step,
    straggler_verdict,
)

#: required keys of one event line (see timeline.snapshot())
EVENT_KEYS = ("ts", "rank", "step", "kind", "name", "dur", "attrs")


class DumpError(ValueError):
    """A dump file failed schema validation."""


def _check_event(obj: dict, lineno: int, kinds: Optional[frozenset]) -> None:
    missing = [k for k in EVENT_KEYS if k not in obj]
    if missing:
        raise DumpError(f"line {lineno}: missing key(s) {missing}")
    if not isinstance(obj["kind"], str) or not isinstance(obj["name"], str):
        raise DumpError(f"line {lineno}: kind/name must be strings")
    if kinds is not None and obj["kind"] not in kinds:
        raise DumpError(
            f"line {lineno}: unknown event kind {obj['kind']!r}")
    for k in ("ts", "dur"):
        if not isinstance(obj[k], (int, float)):
            raise DumpError(f"line {lineno}: {k} must be a number")
    if obj["rank"] is not None and not isinstance(obj["rank"], int):
        raise DumpError(f"line {lineno}: rank must be int or null")
    if not isinstance(obj["attrs"], dict):
        raise DumpError(f"line {lineno}: attrs must be an object")


def load_dump(path: str,
              kinds: Optional[frozenset] = None
              ) -> Tuple[Optional[dict], List[dict]]:
    """``(header, events)`` from one JSONL dump, schema-validated.
    ``kinds`` (default: the live vocabulary when importable) restricts
    event kinds; pass ``None``-able explicitly to skip that check."""
    if kinds is None:
        try:
            from kungfu_tpu.monitor.timeline import EVENT_KINDS

            kinds = EVENT_KINDS
        except ImportError:
            kinds = None
    header: Optional[dict] = None
    events: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise DumpError(f"line {lineno}: not JSON ({e})") from None
            if lineno == 1 and "kftrace" in obj:
                header = obj
                continue
            _check_event(obj, lineno, kinds)
            events.append(obj)
    return header, events


def _event_rank(ev: dict, header: Optional[dict]) -> int:
    r = ev.get("rank")
    if r is None and header is not None:
        r = header.get("rank")
    return -1 if r is None else int(r)


def load_all(paths: Sequence[str]) -> List[dict]:
    """All events from all dumps, rank-resolved (header rank filled in
    where the event carries none), time-sorted."""
    out: List[dict] = []
    for p in paths:
        header, events = load_dump(p)
        for ev in events:
            ev = dict(ev)
            ev["rank"] = _event_rank(ev, header)
            out.append(ev)
    out.sort(key=lambda e: e["ts"])
    return out


# -- Chrome trace ----------------------------------------------------------
def chrome_trace(events: List[dict]) -> dict:
    """Chrome-trace JSON object: one process track per rank, spans as
    complete events, marks as instants, all timestamps rebased to the
    earliest event (µs)."""
    if events:
        t0 = min(e["ts"] for e in events)
    else:
        t0 = 0.0
    ranks = sorted({e["rank"] for e in events})
    trace_events: List[dict] = []
    for r in ranks:
        trace_events.append({
            "name": "process_name", "ph": "M", "pid": r, "tid": 0,
            "args": {"name": f"rank {r}" if r >= 0 else "rankless"},
        })
    for e in events:
        base = {
            "name": e["name"],
            "cat": e["kind"],
            "pid": e["rank"],
            "tid": 0,
            "ts": (e["ts"] - t0) * 1e6,
            "args": dict(e["attrs"], step=e["step"]),
        }
        if e["dur"] > 0:
            base["ph"] = "X"
            base["dur"] = e["dur"] * 1e6
        else:
            base["ph"] = "i"
            base["s"] = "p"
        trace_events.append(base)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


# -- straggler report (analysis itself: monitor/skew.py) -------------------
def render_report(events: List[dict], top: int = 10) -> str:
    lines: List[str] = []
    rows = skew_rows(events)
    verdict = straggler_verdict(events)
    n_faults = sum(1 for e in events if e["kind"] in FAULT_KINDS)
    lines.append(f"kftrace: {len(events)} event(s), "
                 f"{len(rows)} cross-rank collective group(s), "
                 f"{n_faults} fault event(s)")
    if verdict is not None:
        lines.append(f"straggler verdict: rank {verdict} "
                     f"(slowest in {sum(1 for r in rows if r['slowest_rank'] == verdict)}"
                     f"/{len(rows)} groups)")
    lines.append("")
    lines.append("== per-collective cross-rank skew (widest first)")
    if not rows:
        lines.append("  (no collective seen on more than one rank)")
    for r in rows[:top]:
        lines.append(
            f"  {r['op']}/{r['tag']}: skew {r['skew_s'] * 1e3:.1f}ms — "
            f"rank {r['slowest_rank']} {r['slowest_s'] * 1e3:.1f}ms vs "
            f"rank {r['fastest_rank']} {r['fastest_s'] * 1e3:.1f}ms "
            f"({r['ranks']} ranks)"
        )
    lines.append("")
    lines.append("== slowest rank per step window")
    steps = slowest_rank_per_step(events)
    if not steps:
        lines.append("  (no stepped collective spans)")
    for s in steps[:top]:
        lines.append(
            f"  step {s['step']}: rank {s['slowest_rank']} "
            f"({s['total_s'] * 1e3:.1f}ms total collective time, "
            f"{s['ranks']} ranks)"
        )
    lines.append("")
    lines.append("== fault overlap with latency spikes "
                 f"(> {SPIKE_FACTOR:g}x group median)")
    overlaps = fault_overlaps(events)
    if not overlaps:
        lines.append("  (none)")
    for o in overlaps[:top]:
        faults = ", ".join(
            f"{f['kind']}:{f['name']}@rank{f['rank']}" for f in o["faults"]
        )
        lines.append(
            f"  {o['op']}/{o['tag']} rank {o['rank']} step {o['step']}: "
            f"{o['dur_s'] * 1e3:.1f}ms ({o['x_median']:.1f}x median) "
            f"overlaps [{faults}]"
        )
    return "\n".join(lines) + "\n"


# -- self-check ------------------------------------------------------------
def self_check(paths: Sequence[str]) -> int:
    """Validate dump schemas; with no paths, synthesize a dump via the
    live timeline module and round-trip it (proves recorder and reader
    agree byte-for-byte on the schema)."""
    if not paths:
        import os
        import tempfile

        from kungfu_tpu.monitor import timeline

        timeline.reset(cap=64)
        with timeline.span("collective", "engine.all_reduce[64B]",
                           rank=0, force=True, op="all_reduce",
                           tag="selfcheck", nbytes=64):
            pass
        timeline.event("chaos", "delay", rank=0, force=True, ms=1)
        timeline.event("mark", "selfcheck", rank=0, force=True)
        # serving-plane kinds (kf-serve, PR 13) must round-trip too —
        # with the explicit trace context a served request carries, so
        # the recorder/reader agreement covers the causal triple
        with timeline.trace_ctx("srv.selfcheck", "s0.router"):
            with timeline.span("serve", "prefill", rank=0, force=True,
                               tokens=4, reused=0):
                pass
            timeline.event("request", "accept", rank=0, force=True,
                           rid="selfcheck")
        with timeline.span("input", "prefetch.next", rank=0, force=True):
            pass
        fd, tmp = tempfile.mkstemp(suffix=".jsonl", prefix="kftrace-")
        os.close(fd)
        try:
            timeline.dump(tmp)
            header, events = load_dump(tmp)
        finally:
            os.unlink(tmp)
            timeline.reset()
        srv = [e for e in events if e["kind"] in ("serve", "request")]
        ok = (header is not None and len(events) == 6
              and len(srv) == 2
              and all(e["attrs"].get("trace") == "srv.selfcheck"
                      for e in srv))
        if not ok:
            print("kftrace: self-check FAILED (round-trip mismatch)",
                  file=sys.stderr)
            return 1
        print("kftrace: self-check ok (synthetic round-trip incl. "
              "serve/request kinds + trace context)")
        return 0
    rc = 0
    for p in paths:
        try:
            header, events = load_dump(p)
        except (OSError, DumpError) as e:
            print(f"kftrace: {p}: INVALID — {e}", file=sys.stderr)
            rc = 1
            continue
        dropped = (header or {}).get("dropped", 0)
        print(f"kftrace: {p}: ok ({len(events)} event(s), "
              f"{dropped} dropped)")
    return rc


# -- CLI -------------------------------------------------------------------
def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-check" in argv:
        argv.remove("--self-check")
        return self_check(argv)
    if "--critical-path" in argv:
        # the kf-xray offline report: same implementation as the live
        # /cluster xray section (monitor/xray.py), fed from merged dumps
        from kungfu_tpu.monitor import xray as xraylib

        argv.remove("--critical-path")
        if not argv:
            print("kftrace: --critical-path needs at least one dump",
                  file=sys.stderr)
            return 2
        try:
            events = load_all(argv)
        except (OSError, DumpError) as e:
            print(f"kftrace: {e}", file=sys.stderr)
            return 1
        sys.stdout.write(xraylib.render_report(events))
        return 0
    p = argparse.ArgumentParser(
        prog="kftrace",
        description="merge kungfu-tpu flight-recorder dumps; find stragglers",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    pm = sub.add_parser("merge", help="merge dumps into a Chrome-trace JSON")
    pm.add_argument("-o", "--out", required=True, help="output trace.json")
    pm.add_argument("dumps", nargs="+", help="per-rank JSONL dumps")
    pr = sub.add_parser("report", help="print the straggler report")
    pr.add_argument("--top", type=int, default=10,
                    help="rows per section (default 10)")
    pr.add_argument("dumps", nargs="+", help="per-rank JSONL dumps")
    args = p.parse_args(argv)
    try:
        events = load_all(args.dumps)
    except (OSError, DumpError) as e:
        print(f"kftrace: {e}", file=sys.stderr)
        return 1
    if args.cmd == "merge":
        trace = chrome_trace(events)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        ranks = sorted({e['rank'] for e in events})
        print(f"kftrace: wrote {len(trace['traceEvents'])} trace event(s) "
              f"from {len(args.dumps)} dump(s) (ranks {ranks}) to {args.out}")
        return 0
    sys.stdout.write(render_report(events, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
