"""kfmon: the live cluster observability plane.

After PR 4 every rank can tell its own story (``/metrics``,
flight-recorder dumps) — but only *post mortem*, and only one rank at a
time.  At pod scale the operating question is always "**which rank,
right now**": this module gives every rank a :class:`RankReporter`
thread that periodically pushes a compact :func:`make_snapshot` to a
:class:`ClusterAggregator` co-hosted with the elastic
:class:`~kungfu_tpu.elastic.configserver.ConfigServer` — the one process
every peer already knows the address of, and that survives a shrink.

The aggregator maintains a rolling cluster view served by the config
server as ``/cluster`` (JSON, rendered live by ``scripts/kftop``) and
merged into its ``/metrics`` (Prometheus text):

* **freshness** — a rank whose snapshots stop arriving is flagged
  *stale* after ``KF_CONFIG_MONITOR_STALE_AFTER`` seconds (default 3
  push periods ≈ 3 s), well before the failure detector's 10 s ``down``
  verdict — the first cross-rank signal that something is wrong;
* **online skew** — each snapshot carries the collective spans the
  flight recorder captured since the last push; the aggregator feeds
  them to the SAME :mod:`kungfu_tpu.monitor.skew` math ``kftrace`` uses
  offline, so the live straggler verdict and the post-mortem report
  cannot disagree;
* **cluster health** — peer set + config version (from the co-hosted
  config server), per-rank strategy, the last shrink/resize control
  events (pushed by the elastic layer via :func:`post_control`), and the
  quorum margin (how many more deaths until shrink-to-survivors must
  give up).

Wire contract: everything is plain JSON over the config server's
existing HTTP endpoint (``POST /push``).  Snapshot field names are
**literals from the declared schema constants below** — enforced by the
``agg-schema`` kflint rule, because a typo'd field would not error, it
would silently vanish from every ``kftop`` column (the same failure mode
the ``trace-vocab`` rule exists to prevent).

Cost contract: the whole plane is off unless
``KF_CONFIG_ENABLE_CLUSTER_MONITOR`` is truthy (``kfrun -monitor``); on,
it is one daemon thread per rank doing O(new events) work per push.
Online skew additionally needs the flight recorder enabled
(``KF_CONFIG_ENABLE_TRACE`` — ``-monitor`` implies it); without it the
snapshots still carry step/counter/net freshness.

Stdlib-only by design, like :mod:`~kungfu_tpu.monitor.registry` and
:mod:`~kungfu_tpu.monitor.skew`: ``scripts/kftop`` must run in bare CI
images and on operator laptops without jax.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional

from kungfu_tpu.monitor import skew as skewlib
from kungfu_tpu.monitor import xray as xraylib
from kungfu_tpu.monitor.registry import REGISTRY, _escape_label_value
from kungfu_tpu.utils.log import get_logger

_log = get_logger("kfmon")

# env mirror constants, defined next to their reader like timeline.py's
# DUMP_ENV/CAP_ENV; utils/envs.py registers the same tokens for the
# env-contract scan
ENABLE_ENV = "KF_CONFIG_ENABLE_CLUSTER_MONITOR"
PUSH_PERIOD_ENV = "KF_CONFIG_MONITOR_PUSH_PERIOD"
STALE_AFTER_ENV = "KF_CONFIG_MONITOR_STALE_AFTER"

DEFAULT_PUSH_PERIOD_S = 1.0
#: stale = this many push periods without a snapshot (when
#: KF_CONFIG_MONITOR_STALE_AFTER does not pin an absolute value)
STALE_PERIODS = 3.0

#: wire-format version stamped on every snapshot/control message
WIRE_VERSION = 1

#: one snapshot = one JSON object with EXACTLY these fields.  Producers
#: go through :func:`make_snapshot`, consumers through :func:`field` —
#: both enforced to literal members of this set by the ``agg-schema``
#: kflint rule (and revalidated at runtime, for payloads built by hand).
SNAPSHOT_FIELDS = frozenset({
    "kfmon",         # wire version (int)
    "rank",          # stable process identity (bootstrap rank)
    "slice",         # TPU slice id (None on single-slice jobs)
    "pid",           # sender pid
    "wall",          # sender wall-clock at build time
    "step",          # current training step (-1 before the first)
    "step_time_s",   # EMA seconds per step (None until measurable)
    "counters",      # {metric-key: int} cumulative registry counters
    "gauges",        # {metric-key: float} registry gauges (GNS et al.)
    "latency",       # {metric-key: {count, sum}} histogram DELTAS
    "events",        # recent flight-recorder events (skew feedstock)
    "net",           # {egress_bytes, ingress_bytes} cumulative totals
    "strategy",      # active allreduce strategy name ("" = default)
})

#: fields of the ``/cluster`` view (and its per-rank rows / control
#: entries) — the read-side vocabulary ``kftop`` renders from.
VIEW_FIELDS = frozenset({
    "kfmon", "wall", "stale_after_s", "cluster", "ranks", "stale",
    "skew", "slowest_per_step", "straggler", "controls",
    # slice grouping (multislice jobs; empty on single-slice)
    "slices", "stale_slices",
    # cluster-health subfields
    "version", "size", "workers", "quorum_margin", "last_control",
    # per-rank row subfields (snapshot fields age_s/stale are computed)
    "rank", "slice", "pid", "step", "step_time_s", "age_s", "counters",
    "gauges", "latency", "net", "strategy",
    # per-slice group subfields ("slice"/"ranks"/"stale" shared above)
    "all_stale",
    # control-event subfields
    "kind", "attrs",
    # skew-row subfields (monitor/skew.py row dicts)
    "op", "tag", "slowest_rank", "slowest_s", "fastest_rank",
    "fastest_s", "skew_s", "total_s",
    # kf-sentinel section (present ONLY when a Sentinel is attached —
    # the disabled plane is byte-identical to the pre-sentinel view):
    # active rules + fired-alert log + live detector verdicts, plus the
    # kf-ledger decision summary a policy steers by
    "alerts", "active", "rule", "evidence", "incident", "verdicts",
    "decisions",
    # kf-pulse section (None when no rank exports the gradient-signal
    # gauges): cluster means of the kf_gns / kf_grad_variance gauges and
    # the per-group kf_grad_norm{group=} rollup
    "pulse", "gns", "grad_variance", "groups",
    # serving summary (kf-serve; None on deployments with no serve
    # metrics): cluster-wide sums of the per-rank serve gauges/counters
    # plus window-mean latencies from the pushed histogram deltas
    "serving", "active", "queued", "kv_bytes", "completed", "rejected",
    "replayed", "ttft_ms", "e2e_ms",
    # kf-xray section (None when the window holds nothing attributable):
    # the step-time attribution + verdict computed by monitor/xray.py —
    # the SAME implementation `kftrace --critical-path` runs offline —
    # plus the MFU / model-FLOPs rollup from the pushed gauges
    "xray", "verdict", "phases", "steps", "culprit", "critical_rank",
    "dominant", "steps_seen", "wall_s", "mfu", "model_flops_s",
    "phase_seconds", "dropped_events",
})


def _esc_label(v) -> str:
    """Prometheus exposition-format label-value escaping (one rule set
    for the whole package — registry.py owns it)."""
    return _escape_label_value(str(v))


def _parse_float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


#: floor on the push period: a 0/negative env value must not turn every
#: rank into a busy-loop of HTTP POSTs (disable via the ENABLE env, not
#: a zero period)
MIN_PUSH_PERIOD_S = 0.05


def push_period_from_env() -> float:
    v = _parse_float_env(PUSH_PERIOD_ENV, DEFAULT_PUSH_PERIOD_S)
    if v <= 0:
        return DEFAULT_PUSH_PERIOD_S
    # clamp a too-small positive value UP rather than ignoring it, so
    # every consumer of the knob (reporter period, staleness default,
    # the launcher's aggregator) lands on the same effective period
    return max(v, MIN_PUSH_PERIOD_S)


def stale_after_from_env(period: Optional[float] = None) -> float:
    period = push_period_from_env() if period is None else period
    return _parse_float_env(STALE_AFTER_ENV, STALE_PERIODS * period)


def make_snapshot(**fields) -> dict:
    """Build one wire snapshot; unknown field names raise — the runtime
    backstop behind the static ``agg-schema`` rule."""
    unknown = set(fields) - SNAPSHOT_FIELDS
    if unknown:
        raise ValueError(
            f"unknown snapshot field(s) {sorted(unknown)}; the schema is "
            f"SNAPSHOT_FIELDS in kungfu_tpu/monitor/aggregator.py"
        )
    snap = {"kfmon": WIRE_VERSION}
    snap.update(fields)
    return snap


def field(obj: dict, name: str, default=None):
    """Schema-checked read of one snapshot/view field.  Call sites must
    pass a string literal from the declared schema (``agg-schema``
    kflint rule) — so a typo'd field fails lint instead of silently
    rendering an empty ``kftop`` column."""
    return obj.get(name, default)


def sum_metric(mapping: Optional[dict], name: str) -> float:
    """Sum of a pushed counter/gauge over its label variants (the
    registry renders ``kf_x_total{what="y"}`` per label set).  The ONE
    implementation of the label-key match — the serving rollup here and
    kftop's per-rank columns must never disagree on it."""
    return sum(v for k, v in (mapping or {}).items()
               if k == name or k.startswith(name + "{"))


def control_event(kind: str, rank: Optional[int] = None, **attrs) -> dict:
    """A control-plane event (shrink/resize/...) for :func:`post_control`."""
    return {
        "kfmon_control": WIRE_VERSION,
        "kind": kind,
        "rank": rank,
        "wall": time.time(),
        "attrs": attrs,
    }


def server_base(config_server_url: str) -> str:
    """The aggregator's HTTP base from any config-server URL: scheme +
    authority, path dropped (``http://h:9100/get`` → ``http://h:9100``)."""
    from urllib.parse import urlsplit

    url = config_server_url.strip().rstrip("/")
    if "://" not in url:
        # a bare host:port would parse its host as a scheme
        url = "http://" + url
    parts = urlsplit(url)
    return f"{parts.scheme}://{parts.netloc}"


def _post_json(url: str, obj: dict, timeout: float) -> None:
    req = urllib.request.Request(
        url,
        data=json.dumps(obj).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        resp.read()


def post_control(config_server_url: str, kind: str,
                 rank: Optional[int] = None, timeout: float = 2.0,
                 **attrs) -> bool:
    """Best-effort control-event push (elastic layer → aggregator).
    Never raises: the monitoring plane must not take a recovery path
    down with it.  Returns delivery success for tests."""
    if not config_server_url:
        return False
    try:
        _post_json(server_base(config_server_url) + "/push",
                   control_event(kind, rank=rank, **attrs), timeout)
        return True
    except (OSError, http.client.HTTPException) as e:
        _log.debug("control event %r not delivered: %s", kind, e)
        return False


# -- aggregator (config-server side) ---------------------------------------
class ClusterAggregator:
    """Rolling cluster view over pushed rank snapshots + control events.

    Thread-safe; mounted into the ConfigServer's HTTP handler (`/push`,
    `/cluster`, `/metrics`).  Per-rank event windows are bounded: skew
    is an *online* signal over the recent past, not an archive — the
    archive is the flight-recorder dump."""

    def __init__(self, stale_after: Optional[float] = None,
                 max_events_per_rank: int = 4096,
                 max_controls: int = 64,
                 time_fn: Callable[[], float] = time.time):
        self.stale_after = (stale_after if stale_after is not None
                            else stale_after_from_env())
        self._lock = threading.Lock()
        self._time = time_fn
        self._ranks: Dict[int, dict] = {}        # rank -> last snapshot
        self._seen: Dict[int, float] = {}        # rank -> arrival time
        self._events: Dict[int, deque] = {}      # rank -> recent events
        self._max_events = max_events_per_rank
        self._controls: deque = deque(maxlen=max_controls)
        # kf-sentinel judging plane (attach_sentinel); None = off, and
        # every sentinel touch point below is a None check so the
        # disabled aggregator is byte-identical to the pre-sentinel one
        self._sentinel = None

    def attach_sentinel(self, sentinel) -> None:
        """Attach the kf-sentinel judging plane (duck-typed — this
        module must not import :mod:`~kungfu_tpu.monitor.sentinel`,
        which imports it back).  The sentinel samples after ingests and
        contributes the ``alerts`` section of ``/cluster``."""
        self._sentinel = sentinel

    def _notify_sentinel(self) -> None:
        """Post-ingest sentinel hook, OUTSIDE the aggregator lock (the
        sentinel calls back into ``cluster_view``) and guarded — the
        judging plane must never take the ingest path down."""
        s = self._sentinel
        if s is None:
            return
        try:
            s.on_ingest(self)
        except Exception as e:  # noqa: BLE001 - monitoring must not raise
            _log.debug("sentinel sample failed: %s", e)

    # -- ingest ----------------------------------------------------------
    def ingest(self, obj: dict) -> None:
        """One pushed JSON object: a rank snapshot or a control event."""
        if not isinstance(obj, dict):
            raise ValueError("push payload must be a JSON object")
        if obj.get("kfmon_control"):
            dead = []
            if obj.get("kind") == "shrink":
                dead = [r for r in (obj.get("attrs") or {}).get("dead", [])
                        if isinstance(r, int)]
            with self._lock:
                self._controls.append(dict(obj))
                # a shrink evicts the dead ranks' state: their last spans
                # would otherwise feed the skew verdict forever (no new
                # pushes rotate a dead rank's window), leaving /cluster
                # naming a rank that no longer exists
                for r in dead:
                    self._events.pop(r, None)
                    self._ranks.pop(r, None)
                    self._seen.pop(r, None)
            REGISTRY.counter("kf_cluster_control_events_total",
                             what=str(obj.get("kind"))).inc()
            self._notify_sentinel()
            return
        if not obj.get("kfmon"):
            raise ValueError("push payload is neither snapshot nor control")
        unknown = set(obj) - SNAPSHOT_FIELDS
        if unknown:
            raise ValueError(f"unknown snapshot field(s) {sorted(unknown)}")
        rank = obj.get("rank")
        if not isinstance(rank, int):
            raise ValueError("snapshot carries no integer rank")
        events = obj.get("events") or []
        with self._lock:
            self._ranks[rank] = obj
            self._seen[rank] = self._time()
            win = self._events.get(rank)
            if win is None:
                win = self._events[rank] = deque(maxlen=self._max_events)
            for ev in events:
                # the skew math keys on the emitting rank; a reporter
                # forwarding ring events recorded before Peer.start
                # installed the default stamps them itself
                if ev.get("rank") is None:
                    ev = dict(ev, rank=rank)
                win.append(ev)
        self._notify_sentinel()

    # -- views -----------------------------------------------------------
    @staticmethod
    def _serving_summary(rows: List[dict]) -> Optional[dict]:
        """Cluster-wide serving rollup from per-rank rows (the kf-serve
        gauges/counters/histogram-deltas every snapshot already
        carries); ``None`` when no rank serves, so a training-only
        deployment renders no serving section."""

        def gauge_sum(name: str) -> float:
            return sum(sum_metric(row.get("gauges"), name) for row in rows)

        def counter_sum(name: str, what: str) -> int:
            sel = f'{name}{{what="{what}"}}'
            return sum((row.get("counters") or {}).get(sel, 0)
                       for row in rows)

        def window_ms(hist: str) -> Optional[float]:
            count = total = 0.0
            for row in rows:
                for k, d in (row.get("latency") or {}).items():
                    if k == hist or k.startswith(hist + "{"):
                        count += d.get("count", 0)
                        total += d.get("sum", 0.0)
            return (total / count * 1e3) if count else None

        serving = any(
            k.startswith(("kf_serve_", "kf_kv_cache_bytes"))
            for row in rows
            for k in list(row.get("gauges") or {})
            + list(row.get("counters") or {}))
        if not serving:
            return None
        return {
            "active": int(gauge_sum("kf_serve_active_requests")),
            "queued": int(gauge_sum("kf_serve_queue_depth")),
            "kv_bytes": int(gauge_sum("kf_kv_cache_bytes")),
            "completed": counter_sum("kf_serve_requests_total", "complete"),
            "rejected": counter_sum("kf_serve_requests_total", "reject"),
            "replayed": counter_sum("kf_serve_requests_total", "replay"),
            "ttft_ms": window_ms("kf_serve_ttft_seconds"),
            "e2e_ms": window_ms("kf_serve_e2e_seconds"),
        }

    @staticmethod
    def _pulse_summary(rows: List[dict]) -> Optional[dict]:
        """Cluster-wide gradient-signal rollup (kf-pulse): means of the
        per-rank ``kf_gns`` / ``kf_grad_variance`` gauges (every rank
        publishes the SAME collective estimate, so the mean passes
        identical values through) plus the per-group
        ``kf_grad_norm{group=}`` rollup.  ``None`` when no rank exports
        pulse gauges, so an uninstrumented deployment renders no PULSE
        section."""
        gns: List[float] = []
        gvar: List[float] = []
        groups: Dict[str, List[float]] = {}
        prefix = 'kf_grad_norm{group="'
        for row in rows:
            gauges = row.get("gauges") or {}
            v = gauges.get("kf_gns")
            if v is not None:
                gns.append(float(v))
            v = gauges.get("kf_grad_variance")
            if v is not None:
                gvar.append(float(v))
            for key, val in gauges.items():
                if key.startswith(prefix) and key.endswith('"}'):
                    groups.setdefault(key[len(prefix):-2],
                                      []).append(float(val))
        if not gns and not gvar and not groups:
            return None
        return {
            "gns": (sum(gns) / len(gns)) if gns else None,
            "grad_variance": (sum(gvar) / len(gvar)) if gvar else None,
            "groups": {g: sum(vs) / len(vs)
                       for g, vs in sorted(groups.items())},
        }

    @staticmethod
    def _xray_summary(rows: List[dict],
                      events: List[dict]) -> Optional[dict]:
        """The ``/cluster`` ``xray`` section: step-time attribution +
        verdict from the pushed event windows (:func:`kungfu_tpu.monitor.
        xray.online_view` — the same implementation ``kftrace
        --critical-path`` runs offline, so the two cannot disagree) plus
        the MFU / model-FLOPs / per-phase-gauge / trace-loss rollup from
        the per-rank snapshots.  ``None`` when nothing is attributable
        and no rank exports xray gauges."""
        body = xraylib.online_view(events)
        mfu: Dict[int, float] = {}
        flops_s = 0.0
        phase_sums: Dict[str, List[float]] = {}
        dropped: Dict[int, int] = {}
        for row in rows:
            gauges = row.get("gauges") or {}
            m = gauges.get("kf_mfu")
            if m is not None:
                mfu[row["rank"]] = float(m)
            flops_s += sum_metric(gauges, "kf_model_flops_s")
            prefix = 'kf_step_phase_seconds{phase="'
            for key, val in gauges.items():
                if key.startswith(prefix) and key.endswith('"}'):
                    phase = key[len(prefix):-2]
                    phase_sums.setdefault(phase, []).append(float(val))
            drops = sum_metric(row.get("counters"),
                               "kf_timeline_dropped_total")
            if drops:
                dropped[row["rank"]] = int(drops)
        # MEAN over the ranks exporting each phase, never the rank-sum:
        # kftop renders this under a per-step label, and an N-rank sum
        # would read as an N-fold-inflated step (FLOP/s sums honestly —
        # rates add across ranks; per-step seconds do not)
        phase_seconds = {ph: sum(vs) / len(vs)
                         for ph, vs in phase_sums.items()}
        # a lossy ring alone still warrants the section: the TRACE LOSS
        # signal must not vanish just because the surviving window holds
        # nothing attributable (that is exactly when drops matter most)
        if (body is None and not mfu and not flops_s and not phase_seconds
                and not dropped):
            return None
        out = dict(body or {"verdict": None, "steps": []})
        out["mfu"] = mfu or None
        out["model_flops_s"] = flops_s or None
        out["phase_seconds"] = phase_seconds or None
        out["dropped_events"] = dropped or None
        return out

    def _all_events(self) -> List[dict]:
        with self._lock:
            return [e for win in self._events.values() for e in win]

    def stale_ranks(self) -> List[int]:
        now = self._time()
        with self._lock:
            return sorted(r for r, t in self._seen.items()
                          if now - t > self.stale_after)

    def cluster_view(self, cluster_info: Optional[dict] = None,
                     top: int = 20) -> dict:
        """The ``/cluster`` JSON: cluster health + per-rank freshness +
        online skew.  ``cluster_info`` is the co-hosted config server's
        ``{version, size, workers}`` (None when it holds no cluster)."""
        now = self._time()
        with self._lock:
            ranks = dict(self._ranks)
            seen = dict(self._seen)
            controls = list(self._controls)
        events = self._all_events()
        rows = []
        stale = []
        for rank in sorted(ranks):
            snap = ranks[rank]
            age = now - seen[rank]
            is_stale = age > self.stale_after
            if is_stale:
                stale.append(rank)
            rows.append({
                "rank": rank,
                "slice": snap.get("slice"),
                "pid": snap.get("pid"),
                "step": snap.get("step"),
                "step_time_s": snap.get("step_time_s"),
                "age_s": age,
                "stale": is_stale,
                "counters": snap.get("counters") or {},
                "gauges": snap.get("gauges") or {},
                "latency": snap.get("latency") or {},
                "net": snap.get("net") or {},
                "strategy": snap.get("strategy") or "",
            })
        # slice grouping (multislice jobs): a WHOLE-stale slice is a
        # different animal than a stale rank — it is the slice-loss
        # signature (DCN partition / power), the event the slice-shrink
        # protocol exists for, so /cluster and kftop flag it distinctly
        by_slice: Dict[int, dict] = {}
        for row in rows:
            s = row["slice"]
            if s is None:
                continue
            g = by_slice.setdefault(
                int(s), {"slice": int(s), "ranks": [], "stale": []})
            g["ranks"].append(row["rank"])
            if row["stale"]:
                g["stale"].append(row["rank"])
        slice_groups = []
        stale_slices = []
        for s in sorted(by_slice):
            g = by_slice[s]
            g["all_stale"] = bool(g["ranks"]) and g["stale"] == g["ranks"]
            if g["all_stale"]:
                stale_slices.append(s)
            slice_groups.append(g)
        health = dict(cluster_info or {})
        size = health.get("size")
        if isinstance(size, int) and size > 0:
            # deaths survivable before strict majority is lost: the
            # shrink path needs 2*survivors > size
            health["quorum_margin"] = size - (size // 2 + 1)
        if controls:
            health["last_control"] = controls[-1]
        view = {
            "kfmon": WIRE_VERSION,
            "wall": now,
            "stale_after_s": self.stale_after,
            "cluster": health,
            "ranks": rows,
            "stale": stale,
            "slices": slice_groups,
            "stale_slices": stale_slices,
            "serving": self._serving_summary(rows),
            "pulse": self._pulse_summary(rows),
            "xray": self._xray_summary(rows, events),
            "skew": skewlib.skew_rows(events)[:top],
            "slowest_per_step": skewlib.slowest_rank_per_step(events)[-top:],
            "straggler": skewlib.straggler_verdict(events),
            "controls": controls[-top:],
        }
        # the alerts section exists ONLY when a sentinel is attached:
        # with the plane off, /cluster is byte-identical to the
        # pre-sentinel view (asserted in tests — the cost contract)
        s = self._sentinel
        if s is not None:
            view["alerts"] = s.alerts_view()
        return view

    def render_prometheus(self, cluster_info: Optional[dict] = None,
                          top: int = 20) -> str:
        """Cluster-plane series merged into the config server's
        ``/metrics`` so one stock-Prometheus scrape of the control
        process covers the whole job."""
        view = self.cluster_view(cluster_info, top=top)
        lines = [
            "# HELP kf_cluster_ranks ranks that have pushed a snapshot",
            "# TYPE kf_cluster_ranks gauge",
            f"kf_cluster_ranks {len(view['ranks'])}",
            "# HELP kf_cluster_stale_ranks ranks past the staleness threshold",
            "# TYPE kf_cluster_stale_ranks gauge",
            f"kf_cluster_stale_ranks {len(view['stale'])}",
        ]
        if view["slices"]:
            lines += [
                "# HELP kf_cluster_stale_slices slices whose EVERY rank "
                "is stale (slice-loss signature)",
                "# TYPE kf_cluster_stale_slices gauge",
                f"kf_cluster_stale_slices {len(view['stale_slices'])}",
            ]
        if view["serving"]:
            srv = view["serving"]
            lines += [
                "# HELP kf_cluster_serve_active decode slots occupied "
                "across the serving deployment",
                "# TYPE kf_cluster_serve_active gauge",
                f"kf_cluster_serve_active {srv['active']}",
                "# HELP kf_cluster_serve_queued accepted-but-unfinished "
                "requests across routers",
                "# TYPE kf_cluster_serve_queued gauge",
                f"kf_cluster_serve_queued {srv['queued']}",
                "# HELP kf_cluster_kv_cache_bytes paged KV-cache "
                "footprint summed over serving ranks",
                "# TYPE kf_cluster_kv_cache_bytes gauge",
                f"kf_cluster_kv_cache_bytes {srv['kv_bytes']}",
            ]
        if view["pulse"]:
            pl = view["pulse"]
            if pl.get("gns") is not None:
                lines += [
                    "# HELP kf_cluster_gns gradient noise scale, mean "
                    "over reporting ranks (kf-pulse)",
                    "# TYPE kf_cluster_gns gauge",
                    f"kf_cluster_gns {pl['gns']:.6g}",
                ]
            if pl.get("grad_variance") is not None:
                lines += [
                    "# HELP kf_cluster_grad_variance cross-peer gradient "
                    "variance, mean over reporting ranks (kf-pulse)",
                    "# TYPE kf_cluster_grad_variance gauge",
                    f"kf_cluster_grad_variance {pl['grad_variance']:.6g}",
                ]
        if view["xray"]:
            xr = view["xray"]
            if xr.get("mfu"):
                lines += [
                    "# HELP kf_cluster_mfu model-FLOPs utilization per "
                    "rank (analytic FLOPs / detected chip peak)",
                    "# TYPE kf_cluster_mfu gauge",
                ]
                for r in sorted(xr["mfu"]):
                    lines.append(
                        f'kf_cluster_mfu{{rank="{r}"}} {xr["mfu"][r]:.6g}')
            if xr.get("model_flops_s"):
                lines += [
                    "# HELP kf_cluster_model_flops_s analytic model "
                    "FLOP/s summed over reporting ranks",
                    "# TYPE kf_cluster_model_flops_s gauge",
                    f"kf_cluster_model_flops_s {xr['model_flops_s']:.6g}",
                ]
            if xr.get("phase_seconds"):
                lines += [
                    "# HELP kf_cluster_step_phase_seconds per-phase step-"
                    "time decomposition, mean over reporting ranks "
                    "(kf-xray taxonomy)",
                    "# TYPE kf_cluster_step_phase_seconds gauge",
                ]
                for ph in sorted(xr["phase_seconds"]):
                    lines.append(
                        f'kf_cluster_step_phase_seconds'
                        f'{{phase="{_esc_label(ph)}"}} '
                        f'{xr["phase_seconds"][ph]:.6g}')
        if view.get("alerts"):
            lines += [
                "# HELP kf_cluster_alerts_active kf-sentinel rules "
                "currently firing",
                "# TYPE kf_cluster_alerts_active gauge",
                f"kf_cluster_alerts_active "
                f"{len(view['alerts']['active'])}",
            ]
        version = (view["cluster"] or {}).get("version")
        if version is not None:
            lines += [
                "# HELP kf_cluster_config_version current cluster config version",
                "# TYPE kf_cluster_config_version gauge",
                f"kf_cluster_config_version {version}",
            ]
        if view["ranks"]:
            lines += [
                "# HELP kf_cluster_rank_age_seconds seconds since a rank's last snapshot",
                "# TYPE kf_cluster_rank_age_seconds gauge",
            ]
            for row in view["ranks"]:
                lines.append(
                    f'kf_cluster_rank_age_seconds{{rank="{row["rank"]}"}} '
                    f'{row["age_s"]:.6g}')
            lines += [
                "# HELP kf_cluster_rank_step a rank's last reported training step",
                "# TYPE kf_cluster_rank_step gauge",
            ]
            for row in view["ranks"]:
                if row["step"] is not None:
                    lines.append(
                        f'kf_cluster_rank_step{{rank="{row["rank"]}"}} '
                        f'{row["step"]}')
            st_rows = [r for r in view["ranks"]
                       if r["step_time_s"] is not None]
            if st_rows:
                lines += [
                    "# HELP kf_cluster_rank_step_time_seconds EMA step time per rank",
                    "# TYPE kf_cluster_rank_step_time_seconds gauge",
                ]
                for row in st_rows:
                    lines.append(
                        f'kf_cluster_rank_step_time_seconds'
                        f'{{rank="{row["rank"]}"}} {row["step_time_s"]:.6g}')
        if view["skew"]:
            lines += [
                "# HELP kf_cluster_skew_seconds cross-rank duration skew per collective tag",
                "# TYPE kf_cluster_skew_seconds gauge",
            ]
            for row in view["skew"]:
                # op/tag are user-supplied collective names — escape per
                # the exposition format or one odd name (quote, newline)
                # invalidates the entire cluster-plane scrape
                lines.append(
                    f'kf_cluster_skew_seconds{{op="{_esc_label(row["op"])}",'
                    f'tag="{_esc_label(row["tag"])}"}} {row["skew_s"]:.6g}')
        return "\n".join(lines) + "\n"


# -- reporter (rank side) --------------------------------------------------
#: event kinds a snapshot forwards to the aggregator: the skew feedstock
#: plus the fault kinds (so `/cluster` can correlate them online)
REPORT_KINDS = (frozenset(skewlib.COLLECTIVE_KINDS)
                | frozenset(skewlib.FAULT_KINDS)
                # kf-adapt swap events ride the same push so kftop's
                # control/event surfaces see lockstep strategy changes
                | frozenset({"swap"})
                # kf-xray attribution feedstock: REPORT_KINDS must stay
                # a superset of xray.XRAY_KINDS (asserted in tests) or
                # the online verdict would compute from fewer kinds than
                # the offline report and the two could disagree
                | xraylib.XRAY_KINDS | frozenset({"xray"}))

#: EMA weight for the step-time estimate (~5-push memory)
_STEP_EMA_ALPHA = 0.2


#: RankReporter slice_id default: "derive from the MEGASCALE env" —
#: distinct from an explicit None ("no slice", authoritative)
_SLICE_FROM_ENV = object()


class RankReporter:
    """Per-rank snapshot pusher: one daemon thread, one HTTP POST per
    ``KF_CONFIG_MONITOR_PUSH_PERIOD``.  Delivery failures are swallowed
    (a dead aggregator must not take training down); the aggregator's
    staleness clock is the receiving side of the same contract."""

    def __init__(self, rank: int, server_url: str,
                 period: Optional[float] = None,
                 strategy_fn: Optional[Callable[[], str]] = None,
                 net_totals_fn: Optional[Callable[[], Dict[str, int]]] = None,
                 events_fn: Optional[Callable[[], List[dict]]] = None,
                 slice_id=_SLICE_FROM_ENV,
                 pre_snapshot_fn: Optional[Callable[[], None]] = None):
        self.rank = rank
        # slice identity, like the rank, is the STABLE bootstrap value
        # (a slice-shrink renumbers live topologies but must not alias
        # this process's row onto another slice's).  An explicit
        # slice_id — int or None — is authoritative: a Peer that
        # REJECTED an incoherent MEGASCALE contract and fell back to
        # flat passes None, and the env must not resurrect slice rows
        # (a false kftop SLICE LOSS alarm on a job that will never
        # slice-shrink).  Default (standalone reporters): the
        # per-process MEGASCALE_SLICE_ID the launcher stamped; env read
        # is direct — this module stays importable in the stubbed
        # kftop/CI context where kungfu_tpu.utils.envs cannot load —
        # and malformed values mean no slice, not a crash.
        if slice_id is _SLICE_FROM_ENV:
            sid = (os.environ.get("MEGASCALE_SLICE_ID", "") or "").strip()
            num = (os.environ.get("MEGASCALE_NUM_SLICES", "") or "").strip()
            slice_id = None
            if sid and num:
                try:
                    slice_id = int(sid) if int(num) > 1 else None
                except ValueError:
                    slice_id = None
        self.slice_id = slice_id
        self.period = max(MIN_PUSH_PERIOD_S,
                          push_period_from_env() if period is None else period)
        self._push_url = server_base(server_url) + "/push"
        self._strategy_fn = strategy_fn
        self._net_totals_fn = net_totals_fn
        self._events_fn = events_fn
        # refresh hook run before each snapshot build: gauges whose
        # source is a query, not an instrumented code path (device
        # memory stats, ...) get one cheap poll per push
        self._pre_snapshot_fn = pre_snapshot_fn
        self._cursor = 0           # timeline.events_tail cursor
        self._hist_prev: Dict[str, tuple] = {}
        # a failed push must not eat its window: the cursor and delta
        # baselines advance at COLLECTION time, so the undelivered
        # events/deltas are carried here and merged into the next
        # snapshot — otherwise a config-server blip during the very
        # incident being diagnosed would hole the online skew window and
        # break the online==offline agreement.  Bounded like the
        # aggregator's own windows (a long outage keeps the newest).
        self._pending_events: List[dict] = []
        self._pending_latency: Dict[str, dict] = {}
        self._max_pending = 4096
        self._last_step: Optional[int] = None
        self._last_step_wall = 0.0
        self._step_ema: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes pushes: stop()'s final push can otherwise run while
        # the loop thread is still blocked inside a slow HTTP POST (the
        # join below times out) — two threads advancing the cursor and
        # pending buffers concurrently would duplicate or drop events
        self._push_lock = threading.Lock()

    # -- snapshot assembly ----------------------------------------------
    def _collect_events(self) -> List[dict]:
        if self._events_fn is not None:
            return list(self._events_fn())
        from kungfu_tpu.monitor import timeline

        self._cursor, events = timeline.events_tail(
            self._cursor, kinds=REPORT_KINDS)
        return events

    def _split_registry(self):
        counters: Dict[str, int] = {}
        gauges: Dict[str, float] = {}
        latency: Dict[str, dict] = {}
        for key, val in REGISTRY.snapshot().items():
            if isinstance(val, dict):  # histogram summary
                prev_count, prev_sum = self._hist_prev.get(key, (0, 0.0))
                self._hist_prev[key] = (val["count"], val["sum"])
                if val["count"] > prev_count:
                    latency[key] = {
                        "count": val["count"] - prev_count,
                        "sum": val["sum"] - prev_sum,
                    }
            elif isinstance(val, bool):
                continue
            elif isinstance(val, int):
                counters[key] = val
            else:
                gauges[key] = float(val)
        return counters, gauges, latency

    def _step_time(self, step: int, now: float) -> Optional[float]:
        if step is None or step < 0:
            return self._step_ema
        if self._last_step is None or step < self._last_step:
            # first sight — or the step went BACKWARD (shrink replay from
            # the leader-agreed boundary): rebase the rate baseline so
            # the first post-replay advance cannot smear the whole
            # stall+replay wall time over a few steps as one bogus sample
            self._last_step, self._last_step_wall = step, now
            return self._step_ema
        if step > self._last_step:
            x = (now - self._last_step_wall) / (step - self._last_step)
            self._step_ema = (
                x if self._step_ema is None
                else (1 - _STEP_EMA_ALPHA) * self._step_ema
                + _STEP_EMA_ALPHA * x
            )
            self._last_step, self._last_step_wall = step, now
        return self._step_ema

    def snapshot_once(self) -> dict:
        """Build (but do not send) one snapshot — also the test surface."""
        from kungfu_tpu.monitor import timeline

        if self._pre_snapshot_fn is not None:
            # guarded like the other user callbacks: a raising gauge
            # poll must not cost this window its events/deltas
            try:
                self._pre_snapshot_fn()
            except Exception as e:  # noqa: BLE001 - monitoring must not raise
                _log.debug("pre-snapshot hook failed: %s", e)
        now = time.time()
        step = timeline.current_step()
        counters, gauges, latency = self._split_registry()
        net = {"egress_bytes": 0, "ingress_bytes": 0}
        if self._net_totals_fn is not None:
            try:
                net.update(self._net_totals_fn())
            except Exception as e:  # noqa: BLE001 - monitoring must not raise
                _log.debug("net totals unavailable: %s", e)
        else:
            net["egress_bytes"] = int(gauges.get("kf_net_egress_bytes", 0))
            net["ingress_bytes"] = int(gauges.get("kf_net_ingress_bytes", 0))
        for key, delta in self._pending_latency.items():
            cur = latency.get(key)
            if cur is None:
                latency[key] = delta
            else:
                latency[key] = {"count": cur["count"] + delta["count"],
                                "sum": cur["sum"] + delta["sum"]}
        events = self._pending_events + self._collect_events()
        strategy = ""
        if self._strategy_fn is not None:
            # guarded like net_totals_fn: a raising user callback after
            # the cursor/delta baselines advanced would otherwise drop
            # this window's events on the push_once build-failure path
            try:
                strategy = self._strategy_fn()
            except Exception as e:  # noqa: BLE001 - monitoring must not raise
                _log.debug("strategy_fn unavailable: %s", e)
        return make_snapshot(
            rank=self.rank,
            slice=self.slice_id,
            pid=os.getpid(),
            wall=now,
            step=step,
            step_time_s=self._step_time(step, now),
            counters=counters,
            gauges=gauges,
            latency=latency,
            events=events[-self._max_pending:],
            net=net,
            strategy=strategy,
        )

    # -- lifecycle -------------------------------------------------------
    def push_once(self) -> bool:
        with self._push_lock:
            try:
                snap = self.snapshot_once()
            except Exception as e:  # noqa: BLE001 - monitoring must not raise
                _log.warning("snapshot build failed: %s", e)
                return False
            try:
                _post_json(self._push_url, snap,
                           timeout=max(1.0, min(self.period, 5.0)))
                self._pending_events = []
                self._pending_latency = {}
                return True
            except (OSError, http.client.HTTPException) as e:
                # the snapshot already merged any earlier pending window,
                # so carrying IT forward carries everything undelivered
                self._pending_events = (snap.get("events")
                                        or [])[-self._max_pending:]
                self._pending_latency = dict(snap.get("latency") or {})
                _log.debug("snapshot push failed: %s", e)
                return False

    def _loop(self) -> None:
        while not self._stop.wait(self.period):
            self.push_once()

    def start(self) -> "RankReporter":
        self._thread = threading.Thread(
            target=self._loop, name=f"kfmon-r{self.rank}", daemon=True)
        self._thread.start()
        return self

    def stop(self, final_push: bool = False) -> None:
        """Stop the loop; ``final_push`` sends one last snapshot so a
        clean shutdown leaves fresh numbers rather than a stale flag."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.period + 1.0)
            self._thread = None
        if final_push:
            self.push_once()


def post_control_if_enabled(peer, kind: str, **attrs) -> bool:
    """The elastic layer's one-line control post: applies the shared
    gate (plane enabled + config server known) and stamps the peer's
    stable chaos-rank identity.  Callers keep only their own leader
    check — which rank announces differs per protocol.  Imports lazily:
    this module must stay importable from the stubbed ``kftop``/CI
    context where :mod:`kungfu_tpu.utils.envs`'s plan imports are
    unavailable."""
    from kungfu_tpu.utils import envs

    if not envs.parse_bool_env(envs.ENABLE_CLUSTER_MONITOR):
        return False
    if not peer.config.config_server:
        return False
    return post_control(peer.config.config_server, kind,
                        rank=peer.chaos_rank(), **attrs)


def publish_stat(name: str, value: float) -> None:
    """Publish a training statistic (GNS, gradient variance, ...) into
    the unified registry so the next snapshot carries it to ``kftop``:
    ``publish_stat("gns", v)`` → gauge ``kf_stat_gns``."""
    REGISTRY.gauge(f"kf_stat_{name}").set(float(value))
