"""``kftop``: live terminal view of the cluster observability plane.

Fetches the config server's ``/cluster`` JSON (the rolling view the
:class:`~kungfu_tpu.monitor.aggregator.ClusterAggregator` maintains from
per-rank snapshot pushes) and renders it as a refreshing terminal table:
per-rank freshness/step/step-time/fault counters, the online cross-rank
skew section (same :mod:`~kungfu_tpu.monitor.skew` math as the offline
``kftrace`` report), and cluster health (membership version, quorum
margin, last shrink/resize control event).

Modes::

    kftop                         # live view, refresh every 2 s
    kftop --server http://h:9100  # point at the config server
    kftop --once                  # render one frame and exit
    kftop --json                  # one-shot raw /cluster JSON (scripts)
    kftop --self-check            # schema round-trip on a canned payload

Stdlib-only and launched through ``scripts/kftop`` with the same package
stubs as ``kftrace``: it must run on an operator laptop or bare CI image
with no jax installed.

Every read of a snapshot/view field goes through
:func:`~kungfu_tpu.monitor.aggregator.field` with a literal name — the
``agg-schema`` kflint rule fails a typo'd field at lint time instead of
letting a column silently render empty.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import List, Optional, Sequence

from kungfu_tpu.monitor.aggregator import (
    ClusterAggregator,
    VIEW_FIELDS,
    control_event,
    field,
    make_snapshot,
    server_base,
    sum_metric,
)

DEFAULT_SERVER = "http://127.0.0.1:9100"


def fetch_view(server: str, timeout: float = 5.0) -> dict:
    url = server_base(server) + "/cluster"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


# -- rendering -------------------------------------------------------------
def _fmt_s(v: Optional[float], unit: str = "s") -> str:
    if v is None:
        return "-"
    if unit == "ms":
        return f"{v * 1e3:.1f}ms"
    return f"{v:.1f}s"


def _fmt_bytes(n) -> str:
    try:
        n = int(n)
    except (TypeError, ValueError):
        return "-"
    for suffix, scale in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= scale:
            return f"{n / scale:.1f}{suffix}"
    return f"{n}B"


def _counter(row: dict, name: str) -> int:
    """Sum of a pushed counter over its label variants (the shared
    aggregator ``sum_metric`` match — one implementation)."""
    return int(sum_metric(field(row, "counters"), name))


def _window_latency_s(row: dict) -> Optional[float]:
    """Mean collective latency over the rank's last push window, from
    the histogram count/sum deltas the snapshot carries."""
    lat = field(row, "latency") or {}
    count = sum(d.get("count", 0) for d in lat.values())
    total = sum(d.get("sum", 0.0) for d in lat.values())
    return (total / count) if count else None


def _gauge(row: dict, name: str) -> float:
    """Sum of a pushed gauge over its label variants (like _counter)."""
    return sum_metric(field(row, "gauges"), name)


def _serving_lines(view: dict) -> List[str]:
    """The serving section (kf-serve deployments): the cluster rollup
    the aggregator computes plus per-rank serve columns from the same
    gauges/counters every snapshot carries (docs/serving.md)."""
    srv = field(view, "serving")
    if not srv:
        return []
    ttft = field(srv, "ttft_ms")
    e2e = field(srv, "e2e_ms")
    lines = [
        "",
        "== serving (continuous batching; docs/serving.md)",
        f"  active {field(srv, 'active')} | queued {field(srv, 'queued')} | "
        f"kv-cache {_fmt_bytes(field(srv, 'kv_bytes'))} | "
        f"completed {field(srv, 'completed')} | "
        f"rejected {field(srv, 'rejected')} | "
        f"replayed {field(srv, 'replayed')} | "
        f"window ttft {_fmt_s(ttft / 1e3, 'ms') if ttft is not None else '-'}"
        f" e2e {_fmt_s(e2e / 1e3, 'ms') if e2e is not None else '-'}",
        f"  {'rank':>4} {'active':>7} {'kv-cache':>9} {'done':>6} "
        f"{'replay':>7} {'reuse-tok':>10}",
    ]
    done_key = 'kf_serve_requests_total{what="complete"}'
    replay_key = 'kf_serve_requests_total{what="replay"}'
    reuse_key = 'kf_serve_prefill_tokens_total{what="reused"}'
    for row in field(view, "ranks") or []:
        if not (_gauge(row, "kf_serve_active_requests")
                or _gauge(row, "kf_kv_cache_bytes")
                or _counter(row, "kf_serve_requests_total")
                or _counter(row, "kf_serve_prefill_tokens_total")):
            continue
        counters = field(row, "counters") or {}
        lines.append(
            f"  {field(row, 'rank'):>4} "
            f"{int(_gauge(row, 'kf_serve_active_requests')):>7} "
            f"{_fmt_bytes(int(_gauge(row, 'kf_kv_cache_bytes'))):>9} "
            f"{counters.get(done_key, 0):>6} "
            f"{counters.get(replay_key, 0):>7} "
            f"{counters.get(reuse_key, 0):>10}")
    return lines


def _device_mem(row: dict):
    """A rank's ``(in_use, limit)`` device-memory bytes from the labeled
    gauge pair (direct key reads — ``sum_metric`` would add the two
    variants together, which is exactly wrong here)."""
    gauges = field(row, "gauges") or {}
    return (gauges.get('kf_device_memory_bytes{kind="in_use"}', 0.0),
            gauges.get('kf_device_memory_bytes{kind="limit"}', 0.0))


def _pulse_lines(view: dict) -> List[str]:
    """The PULSE section (kf-pulse gradient-signal monitoring; present
    only when some rank exports the pulse gauges — docs/pulse.md):
    cluster GNS/variance means plus per-rank and per-group gradient
    norms from the same pushed gauges."""
    pl = field(view, "pulse")
    if not pl:
        return []
    gns = field(pl, "gns")
    gvar = field(pl, "grad_variance")
    lines = ["", "== PULSE (gradient noise scale / variance; "
                 "docs/pulse.md)"]
    head = (f"  gns {gns:.4g}" if gns is not None else "  gns -")
    head += (f" | grad-var {gvar:.4g}" if gvar is not None
             else " | grad-var -")
    groups = field(pl, "groups") or {}
    if groups:
        head += " | " + " ".join(
            f"|g|[{g}] {v:.4g}" for g, v in sorted(groups.items()))
    lines.append(head)
    per_rank = []
    for row in field(view, "ranks") or []:
        gauges = field(row, "gauges") or {}
        v = gauges.get("kf_gns")
        if v is not None:
            per_rank.append(f"r{field(row, 'rank')}:{float(v):.4g}")
    if per_rank:
        lines.append("  per-rank gns: " + " ".join(per_rank))
    return lines


def _decision_lines(view: dict) -> List[str]:
    """The DECISIONS tail of the ALERTS section (kf-ledger): how many
    adaptive-actor decisions the run has made, how they measured out,
    and the newest effect verdict (docs/pulse.md)."""
    al = field(view, "alerts")
    if not al:
        return []
    dec = field(al, "decisions")
    if not dec or not dec.get("total"):
        return []
    by_verdict = dec.get("by_verdict") or {}
    line = (f"  decisions: {dec.get('total')} made, "
            f"{dec.get('judged')} judged, {dec.get('pending')} pending")
    if by_verdict:
        line += " (" + " ".join(
            f"{k}:{v}" for k, v in sorted(by_verdict.items())) + ")"
    lines = [line]
    last = dec.get("last")
    if last:
        lines.append(
            f"  last effect: {last.get('actor')}/{last.get('knob')} "
            f"-> {last.get('verdict')} "
            f"({last.get('series')} {last.get('before_median')} -> "
            f"{last.get('after_median')}, score {last.get('score')})")
    return lines


def _alert_lines(view: dict) -> List[str]:
    """The ALERTS section (kf-sentinel; present only when a Sentinel is
    attached to the aggregator — docs/sentinel.md)."""
    al = field(view, "alerts")
    if not al:
        return []
    active = field(al, "active") or []
    fired = field(al, "alerts") or []
    lines = ["", "== ALERTS (kf-sentinel online detectors; "
                 "docs/sentinel.md)"]
    if active:
        lines.append("  !! ACTIVE: " + " | ".join(active))
    else:
        lines.append("  (no rule firing)")
    for a in fired[-5:]:
        inc = field(a, "incident")
        lines.append(
            f"  fired: {field(a, 'rule')}"
            + (f" -> {inc}" if inc else ""))
    return lines


def _fmt_flops(v) -> str:
    if not v:
        return "-"
    for suffix, scale in (("PFLOP/s", 1e15), ("TFLOP/s", 1e12),
                          ("GFLOP/s", 1e9), ("MFLOP/s", 1e6)):
        if v >= scale:
            return f"{v / scale:.2f}{suffix}"
    return f"{v:.0f}FLOP/s"


def _xray_lines(view: dict) -> List[str]:
    """The XRAY section: continuous step-time decomposition + MFU /
    model-FLOPs rate + the causal verdict (culprit rank and edge) the
    aggregator computes with the SAME monitor/xray.py implementation as
    the offline ``kftrace --critical-path`` report (docs/xray.md)."""
    xr = field(view, "xray")
    if not xr:
        return []
    lines = ["", "== XRAY (step-time decomposition; same math as "
                 "`kftrace --critical-path`)"]
    phases = field(xr, "phase_seconds")
    v = field(xr, "verdict")
    label = "phases/step"
    if not phases and v:
        # no rank exports the per-step gauges (no MFUMeter in the loop):
        # fall back to the verdict's WINDOW TOTALS, divided back to a
        # per-step mean — rendering a 32-step total under a per-step
        # label would read as a 32x-inflated step
        steps_seen = max(1, field(v, "steps_seen") or 1)
        totals = field(v, "phases") or {}
        phases = {ph: sec / steps_seen for ph, sec in totals.items()}
        label = "phases/step (window mean)"
    if phases:
        lines.append(f"  {label}: " + " | ".join(
            f"{ph} {_fmt_s(sec, 'ms')}" for ph, sec in sorted(
                phases.items(), key=lambda kv: -kv[1])))
    mfu = field(xr, "mfu")
    flops = field(xr, "model_flops_s")
    if mfu or flops:
        mfu_txt = ("-" if not mfu else " ".join(
            f"r{r}:{m:.3f}" for r, m in sorted(mfu.items())))
        lines.append(f"  mfu {mfu_txt} | model rate {_fmt_flops(flops)}")
    if v:
        verdict_bits = []
        if field(v, "straggler") is not None:
            verdict_bits.append(f"straggler rank {field(v, 'straggler')}")
        if field(v, "dominant") is not None:
            verdict_bits.append(f"dominant {field(v, 'dominant')}")
        c = field(v, "culprit")
        if c:
            verdict_bits.append(
                f"culprit {field(c, 'op')}/{field(c, 'tag')} "
                f"rank {field(c, 'slowest_rank')} -> "
                f"rank {field(c, 'fastest_rank')} "
                f"(skew {_fmt_s(field(c, 'skew_s'), 'ms')})")
        if verdict_bits:
            lines.append("  verdict: " + " | ".join(verdict_bits))
        lines.append(f"  window: {field(v, 'steps_seen')} step(s)")
    return lines


def render_view(view: dict, top: int = 10) -> str:
    lines: List[str] = []
    wall = field(view, "wall")
    clock = time.strftime("%H:%M:%S", time.localtime(wall)) if wall else "?"
    rows = field(view, "ranks") or []
    stale = field(view, "stale") or []
    straggler = field(view, "straggler")
    cluster = field(view, "cluster") or {}
    head = (f"kfmon @ {clock} — {len(rows)} rank(s), {len(stale)} stale "
            f"(threshold {_fmt_s(field(view, 'stale_after_s'))})")
    version = field(cluster, "version")
    if version is not None:
        head += (f" | cluster v{version} n={field(cluster, 'size')}"
                 f" quorum-margin {field(cluster, 'quorum_margin')}")
    if straggler is not None:
        head += f" | straggler: rank {straggler}"
    lines.append(head)
    slices = field(view, "slices") or []
    if slices:
        # a WHOLE-stale slice is the slice-loss signature (DCN/power),
        # not a straggling rank — render it as its own alarm line
        stale_slices = field(view, "stale_slices") or []
        parts = []
        for g in slices:
            n_ranks = len(field(g, "ranks") or [])
            n_stale = len(field(g, "stale") or [])
            mark = ("LOST" if field(g, "all_stale")
                    else f"{n_stale}/{n_ranks} stale" if n_stale else "ok")
            parts.append(f"slice {field(g, 'slice')}: {mark}")
        lines.append("slices: " + " | ".join(parts))
        if stale_slices:
            lines.append(
                f"!! SLICE LOSS: slice(s) {stale_slices} fully stale — "
                "expect slice-shrink (docs/multislice.md)")
    last = field(cluster, "last_control")
    if last:
        age = (wall or time.time()) - (field(last, "wall") or 0)
        lines.append(
            f"last control: {field(last, 'kind')} "
            f"({_fmt_s(age)} ago, rank {field(last, 'rank')}) "
            f"{field(last, 'attrs') or ''}")
    lines.append("")
    show_slice = any(field(r, "slice") is not None for r in rows)
    show_mem = any(_device_mem(r)[0] for r in rows)
    hdr = (f"{'rank':>4} " + (f"{'slice':>5} " if show_slice else "")
           + f"{'state':<6} {'age':>7} {'step':>7} "
           f"{'step-time':>10} {'coll-lat':>9} {'retries':>8} "
           f"{'faults':>7} {'chaos':>6} "
           + (f"{'dev-mem':>15} " if show_mem else "")
           + f"{'egress':>9} {'ingress':>9}  strategy")
    lines.append(hdr)
    for row in rows:
        state = "STALE" if field(row, "stale") else "ok"
        net = field(row, "net") or {}
        faults = (_counter(row, "kf_peer_faults_total")
                  + _counter(row, "kf_detector_down_total"))
        lat = _window_latency_s(row)
        sl = field(row, "slice")
        mem_txt = ""
        if show_mem:
            in_use, limit = _device_mem(row)
            cell = (f"{_fmt_bytes(int(in_use))}/{_fmt_bytes(int(limit))}"
                    if in_use else "-")
            mem_txt = f"{cell:>15} "
        lines.append(
            f"{field(row, 'rank'):>4} "
            + (f"{sl if sl is not None else '-':>5} " if show_slice else "")
            + f"{state:<6} "
            f"{_fmt_s(field(row, 'age_s')):>7} "
            f"{field(row, 'step') if field(row, 'step') is not None else '-':>7} "
            f"{_fmt_s(field(row, 'step_time_s')):>10} "
            f"{_fmt_s(lat, 'ms') if lat is not None else '-':>9} "
            f"{_counter(row, 'kf_engine_retries_total'):>8} "
            f"{faults:>7} "
            f"{_counter(row, 'kf_chaos_injections_total'):>6} "
            + mem_txt
            + f"{_fmt_bytes(net.get('egress_bytes')):>9} "
            f"{_fmt_bytes(net.get('ingress_bytes')):>9}  "
            f"{field(row, 'strategy') or '-'}")
    if not rows:
        lines.append("  (no snapshots yet — workers push once per "
                     "KF_CONFIG_MONITOR_PUSH_PERIOD)")
    lines.append("")
    lines.append("== cross-rank skew (widest first; online, same math as "
                 "`kftrace report`)")
    skew = field(view, "skew") or []
    for r in skew[:top]:
        lines.append(
            f"  {field(r, 'op')}/{field(r, 'tag')}: "
            f"skew {_fmt_s(field(r, 'skew_s'), 'ms')} — "
            f"rank {field(r, 'slowest_rank')} "
            f"{_fmt_s(field(r, 'slowest_s'), 'ms')} vs "
            f"rank {field(r, 'fastest_rank')} "
            f"{_fmt_s(field(r, 'fastest_s'), 'ms')}")
    if not skew:
        lines.append("  (no cross-rank collective spans in the window — "
                     "is KF_CONFIG_ENABLE_TRACE on?)")
    lines.extend(_xray_lines(view))
    # a silently-lossy flight recorder must not look complete: the
    # aggregator's ONE per-rank drop rollup (xray.dropped_events, from
    # kf_timeline_dropped_total) becomes an explicit alarm line
    lossy = field(field(view, "xray") or {}, "dropped_events") or {}
    if lossy:
        lines.append("")
        lines.append(
            "!! TRACE LOSS: flight-recorder ring evicted events — "
            + ", ".join(f"rank {r}: {n}" for r, n in sorted(lossy.items()))
            + " (raise KF_CONFIG_TIMELINE_CAP; skew/xray windows are "
              "incomplete)")
    # kf-persist: a rank whose manifest age exceeds 3 persist periods
    # has a wedged/starved durable plane — a preemption now would lose
    # that much progress (docs/persistence.md)
    ckpt_stale = []
    for row in rows:
        period = _gauge(row, "kf_ckpt_period_seconds")
        age = _gauge(row, "kf_ckpt_age_seconds")
        if period > 0 and age > 3 * period:
            ckpt_stale.append(
                f"rank {field(row, 'rank')}: {_fmt_s(age)} "
                f"(period {_fmt_s(period)})")
    if ckpt_stale:
        lines.append("")
        lines.append(
            "!! CKPT STALE: manifest age > 3x persist period — "
            + ", ".join(ckpt_stale)
            + " (durable plane wedged? a preemption now replays all of "
              "that; docs/persistence.md)")
    lines.extend(_pulse_lines(view))
    lines.extend(_serving_lines(view))
    lines.extend(_alert_lines(view))
    lines.extend(_decision_lines(view))
    return "\n".join(lines) + "\n"


# -- self-check ------------------------------------------------------------
def self_check() -> int:
    """Schema round-trip on a canned payload: build snapshots through
    :func:`make_snapshot`, ingest them into a live aggregator, serialize
    the view through JSON, and re-render — proving the push wire format,
    the view schema, and the renderer agree (wired into check.sh)."""
    import tempfile

    from kungfu_tpu.monitor.sentinel import Sentinel

    clock = [1000.0]
    agg = ClusterAggregator(stale_after=1.0, time_fn=lambda: clock[0])
    # a sentinel with a step-time ceiling the canned 0.25 s step busts:
    # proves ingest -> sample -> alert -> /cluster alerts section ->
    # ALERTS rendering, end to end on the same canned payload
    tmp = tempfile.TemporaryDirectory(prefix="kftop-selfcheck-")
    agg.attach_sentinel(Sentinel(tmp.name, period_s=0.0,
                                 step_ceiling_s=0.1))

    def span(rank, dur, tag):
        return {"ts": 999.0, "rank": rank, "step": 3, "kind": "collective",
                "name": "engine.all_reduce", "dur": dur,
                "attrs": {"op": "all_reduce", "tag": tag}}

    for rank in range(3):
        dur = 0.10 if rank == 2 else 0.01
        counters = {"kf_engine_retries_total": rank}
        gauges = {"kf_stat_gns": 1.5,
                  # kf-pulse gauges on every rank (the collective
                  # estimate is identical across peers by construction)
                  "kf_gns": 1.5,
                  "kf_grad_variance": 0.25,
                  'kf_grad_norm{group="flat"}': 2.0}
        latency = {"kf_collective_latency_seconds": {"count": 2, "sum": dur}}
        if rank == 0:  # one rank exporting the kf-xray gauges
            gauges["kf_mfu"] = 0.41
            gauges["kf_model_flops_s"] = 1.2e12
            gauges['kf_step_phase_seconds{phase="compute"}'] = 0.2
            gauges['kf_step_phase_seconds{phase="comm_exposed"}'] = 0.05
        if rank == 2:  # one lossy ring proves the TRACE LOSS alarm
            counters["kf_timeline_dropped_total"] = 5
        if rank == 2:  # and a wedged persist plane proves CKPT STALE
            gauges["kf_ckpt_last_step"] = 1.0
            gauges["kf_ckpt_age_seconds"] = 95.0
            gauges["kf_ckpt_period_seconds"] = 30.0
            gauges["kf_ckpt_bytes_total"] = 2048.0
        if rank == 1:  # device-memory gauges prove the dev-mem column
            gauges['kf_device_memory_bytes{kind="in_use"}'] = float(2 << 30)
            gauges['kf_device_memory_bytes{kind="limit"}'] = float(8 << 30)
        if rank == 1:  # one serving rank proves the serving rollup
            counters['kf_serve_requests_total{what="complete"}'] = 7
            counters['kf_serve_requests_total{what="replay"}'] = 2
            counters['kf_serve_prefill_tokens_total{what="reused"}'] = 64
            gauges["kf_serve_active_requests"] = 3.0
            gauges["kf_kv_cache_bytes"] = float(1 << 20)
            latency["kf_serve_e2e_seconds"] = {"count": 4, "sum": 2.0}
        agg.ingest(make_snapshot(
            rank=rank, pid=100 + rank, wall=999.5, step=3,
            step_time_s=0.25,
            slice=rank // 2,  # 2-rank slice 0 + 1-rank slice 1
            counters=counters,
            gauges=gauges,
            latency=latency,
            events=[span(rank, dur, "grad3")],
            net={"egress_bytes": 1 << 20, "ingress_bytes": 1 << 20},
            strategy="RING",
        ))
    agg.ingest(control_event("shrink", rank=0, dead=[4], version=2))
    clock[0] += 2.0  # every rank now past the 1 s staleness threshold
    view = json.loads(json.dumps(agg.cluster_view(
        {"version": 2, "size": 3, "workers": ["h:1", "h:2", "h:3"]})))
    bad = set(view) - VIEW_FIELDS
    ok = (
        not bad
        and field(view, "straggler") == 2
        and [field(r, "rank") for r in field(view, "ranks")] == [0, 1, 2]
        and field(view, "stale") == [0, 1, 2]
        and field(view, "skew")
        and abs(field(field(view, "skew")[0], "skew_s") - 0.09) < 1e-9
        and field(field(view, "cluster"), "quorum_margin") == 1
        and field(field(field(view, "cluster"), "last_control"), "kind")
        == "shrink"
    )
    ok = ok and bool(field(field(view, "ranks")[0], "latency"))
    # slice grouping: every rank is stale, so both canned slices must be
    # flagged as whole-stale (the slice-loss signature)
    ok = (ok
          and [field(g, "slice") for g in field(view, "slices")] == [0, 1]
          and field(field(view, "slices")[0], "all_stale")
          and field(view, "stale_slices") == [0, 1])
    # serving rollup: the one serving rank's gauges/counters/deltas must
    # surface as the cluster serving summary (docs/serving.md)
    srv = field(view, "serving")
    ok = (ok and srv is not None
          and field(srv, "active") == 3
          and field(srv, "kv_bytes") == (1 << 20)
          and field(srv, "completed") == 7
          and field(srv, "replayed") == 2
          and abs(field(srv, "e2e_ms") - 500.0) < 1e-9)
    # kf-xray section: the canned spans must attribute, the verdict must
    # name the slow rank's edge (same monitor/xray.py math as the
    # offline report), and the pushed gauges must roll up
    xr = field(view, "xray")
    xv = field(xr, "verdict") if xr else None
    ok = (ok and xr is not None and xv is not None
          and field(xv, "straggler") == 2
          and field(field(xv, "culprit"), "slowest_rank") == 2
          and abs(field(field(xv, "culprit"), "skew_s") - 0.09) < 1e-9
          and field(xv, "steps_seen") == 1
          and field(xr, "mfu") == {"0": 0.41}
          and abs(field(xr, "model_flops_s") - 1.2e12) < 1.0
          and field(xr, "phase_seconds") == {"compute": 0.2,
                                             "comm_exposed": 0.05}
          and field(xr, "dropped_events") == {"2": 5})
    # kf-pulse: the per-rank gauges must roll up to the cluster means
    # and the per-group norm table
    pl = field(view, "pulse")
    ok = (ok and pl is not None
          and abs(field(pl, "gns") - 1.5) < 1e-9
          and abs(field(pl, "grad_variance") - 0.25) < 1e-9
          and field(pl, "groups") == {"flat": 2.0})
    # kf-sentinel: the busted step-time ceiling must be an active alert
    # in the view, and the fired alert must carry its incident path —
    # and the alerts section must carry the kf-ledger decision summary
    al = field(view, "alerts")
    ok = (ok and al is not None
          and "watermark:step_time" in (field(al, "active") or [])
          and (field(al, "alerts") or [])
          and field(field(al, "alerts")[0], "incident")
          and isinstance(field(al, "decisions"), dict))
    text = render_view(view)
    ok = (ok and "STALE" in text and "all_reduce/grad3" in text
          and "coll-lat" in text and "SLICE LOSS" in text
          and "== serving" in text and "replay" in text
          and "== XRAY" in text and "TRACE LOSS" in text
          and "rank 2: 5" in text and "CKPT STALE" in text
          and "== ALERTS" in text and "watermark:step_time" in text
          and "== PULSE" in text and "gns 1.5" in text
          and "dev-mem" in text and "2.0GiB/8.0GiB" in text)
    tmp.cleanup()
    if not ok:
        print("kftop: self-check FAILED (view schema/round-trip mismatch)",
              file=sys.stderr)
        return 1
    print("kftop: self-check ok (canned /cluster round-trip)")
    return 0


# -- CLI -------------------------------------------------------------------
def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-check" in argv:
        return self_check()
    p = argparse.ArgumentParser(
        prog="kftop",
        description="live kungfu-tpu cluster view (config server /cluster)",
    )
    p.add_argument("-s", "--server", default=DEFAULT_SERVER,
                   help=f"config server URL (default {DEFAULT_SERVER})")
    p.add_argument("-n", "--interval", type=float, default=2.0,
                   help="refresh period seconds (default 2)")
    p.add_argument("--top", type=int, default=10,
                   help="skew rows shown (default 10)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit")
    p.add_argument("--json", action="store_true",
                   help="print the raw /cluster JSON once and exit")
    args = p.parse_args(argv)
    if args.json or args.once:
        try:
            view = fetch_view(args.server)
        except (OSError, ValueError) as e:
            print(f"kftop: {args.server}: {e}", file=sys.stderr)
            return 1
        if args.json:
            json.dump(view, sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(render_view(view, top=args.top))
        return 0
    try:
        while True:
            try:
                frame = render_view(fetch_view(args.server), top=args.top)
            except (OSError, ValueError) as e:
                frame = f"kftop: {args.server}: {e} (retrying)\n"
            # clear + home, then the frame — a live refreshing view
            sys.stdout.write("\x1b[2J\x1b[H" + frame)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
