"""Monitoring: failure detection, heartbeats, network/throughput metrics.

* :mod:`kungfu_tpu.monitor.detector` — the failure-detection server
  (reference fork's ``srcs/go/kungfu/runner/monitorserver/monitor.go``);
* :mod:`kungfu_tpu.monitor.signals` — worker-side heartbeat senders
  (reference ``kungfu/cmd/__init__.py`` monitor_* + ``libkungfu-comm/send.go``);
* :mod:`kungfu_tpu.monitor.metrics` — egress/ingress counters + HTTP
  ``/metrics`` endpoint (reference ``srcs/go/monitor``);
* :mod:`kungfu_tpu.monitor.timeline` — the flight recorder: bounded ring
  of cross-rank structured events, JSONL dumps for ``kftrace``;
* :mod:`kungfu_tpu.monitor.registry` — unified counters/gauges/latency
  histograms rendered through ``/metrics``;
* :mod:`kungfu_tpu.monitor.traceview` — ``kftrace``: merge per-rank
  dumps into a Chrome/Perfetto trace + straggler report;
* :mod:`kungfu_tpu.monitor.skew` — the straggler math itself, one pure
  module shared by the offline report and the live plane;
* :mod:`kungfu_tpu.monitor.aggregator` — kfmon: per-rank snapshot
  pushes to a cluster aggregator co-hosted with the config server
  (freshness/staleness, online skew, cluster health);
* :mod:`kungfu_tpu.monitor.kftop` — ``kftop``: live refreshing terminal
  view of the aggregator's ``/cluster`` endpoint;
* :mod:`kungfu_tpu.monitor.adapt_device` — kf-adapt: the UCB bandit
  drivers (host strategies + MST arm, per-bucket device schedules) with
  the consensus-fenced lockstep swap (docs/adaptation.md).
"""

from kungfu_tpu.monitor import timeline
from kungfu_tpu.monitor.detector import DetectorServer, DetectorResults, DEFAULT_DETECTOR_PORT
from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.monitor.adaptive import (
    AdaptiveStrategyDriver,
    DeviceStrategyDriver,
    monitored_all_reduce,
)
from kungfu_tpu.monitor.signals import (
    monitor_batch_begin,
    monitor_batch_end,
    monitor_compile_grace,
    monitor_epoch_end,
    monitor_train_end,
)

__all__ = [
    "DetectorServer",
    "DetectorResults",
    "DEFAULT_DETECTOR_PORT",
    "AdaptiveStrategyDriver",
    "DeviceStrategyDriver",
    "monitored_all_reduce",
    "monitor_batch_begin",
    "monitor_compile_grace",
    "monitor_batch_end",
    "monitor_epoch_end",
    "monitor_train_end",
]

#: kf-adapt bandit drivers, exported LAZILY (PEP 562): adapt_device
#: pulls in the policy package, whose runner imports elastic.hooks,
#: which imports kungfu_tpu.chaos — and chaos.inject imports THIS
#: package for the timeline.  An eager import here closes that loop
#: into a real circular-import crash whenever kungfu_tpu.chaos is the
#: first package imported (tests/test_chaos.py standalone).
_LAZY_BANDIT = ("DeviceBanditDriver", "HostBanditDriver")
__all__ += list(_LAZY_BANDIT)


def __getattr__(name):
    if name in _LAZY_BANDIT:
        from kungfu_tpu.monitor import adapt_device

        return getattr(adapt_device, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
