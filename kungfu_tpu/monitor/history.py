"""kf-sentinel durable history: bounded per-stream segmented JSONL rings.

The aggregator can *see* but not *remember*: it holds only the freshest
snapshot per rank, so a regression that started ten minutes ago is
invisible to anyone who was not watching kftop at the time.  This module
is the memory — the :class:`~kungfu_tpu.monitor.sentinel.Sentinel`
appends one compact record per sample to per-stream rings under
``KF_SENTINEL_DIR`` (stream ``cluster`` carries the rollup series the
detector judges; stream ``rank-<r>`` carries each rank's condensed
snapshot), and ``scripts/kfhist`` reads them back offline.

Write discipline (the PR-17 atomic tempfile+rename contract of
:mod:`kungfu_tpu.elastic.persist`): segments are whole files, each
append rewrites the small OPEN segment via ``mkstemp`` + ``os.replace``.
A crash at any instant leaves either the previous complete segment or
the new complete segment — never a half-written line — plus at worst an
orphan ``*.tmp`` the reader ignores.  At ``segment_records`` records the
open segment is *sealed* (never touched again) and the ring is GC'd
oldest-sealed-first down to ``KF_SENTINEL_KEEP_BYTES`` per stream.  A
restarted writer always opens a FRESH segment (next sequence number):
appending into a predecessor's file would re-serialize records this
process never saw.

The reader side is defensive the way the persist restore path is: a
torn or hand-edited line is *skipped and counted*, not raised — a
corrupt byte in the history must never take down the post-mortem tool
reading it.

Stdlib-only: ``scripts/kfhist`` runs through the same package stubs as
``kftop``, on operator laptops and bare CI images with no jax.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Dict, List, Optional, Tuple

# env mirror constants, defined next to their reader like timeline.py's
# DUMP_ENV/CAP_ENV; utils/envs.py registers the same tokens for the
# env-contract scan
DIR_ENV = "KF_SENTINEL_DIR"
KEEP_BYTES_ENV = "KF_SENTINEL_KEEP_BYTES"

#: per-stream ring byte budget (sealed + open segments)
DEFAULT_KEEP_BYTES = 8 << 20
#: records per segment before it seals; small on purpose — the open
#: segment is rewritten whole on every append, so this bounds the
#: rewrite cost at ~a few KiB of JSON per push
DEFAULT_SEGMENT_RECORDS = 64

_SEG_RE = re.compile(r"^(?P<stream>.+)-(?P<seq>\d{8})\.jsonl$")


def keep_bytes_from_env() -> int:
    try:
        v = int(os.environ.get(KEEP_BYTES_ENV, "") or DEFAULT_KEEP_BYTES)
    except ValueError:
        return DEFAULT_KEEP_BYTES
    return v if v > 0 else DEFAULT_KEEP_BYTES


def _atomic_write(path: str, data: bytes) -> None:
    """Atomic replace in the target directory (same-filesystem rename);
    a crash mid-write leaves only a ``*.tmp`` orphan, never a torn
    file — the persist plane's write discipline."""
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _segments(root: str, stream: str) -> List[Tuple[int, str]]:
    """Sorted ``(seq, path)`` of a stream's segments on disk (``*.tmp``
    orphans and foreign files ignored)."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _SEG_RE.match(name)
        if m and m.group("stream") == stream:
            out.append((int(m.group("seq")), os.path.join(root, name)))
    out.sort()
    return out


class HistoryRing:
    """One stream's bounded, durable, append-only record ring."""

    def __init__(self, root: str, stream: str,
                 keep_bytes: Optional[int] = None,
                 segment_records: int = DEFAULT_SEGMENT_RECORDS):
        if not stream or "/" in stream or stream.startswith("."):
            raise ValueError(f"bad stream name {stream!r}")
        self.root = root
        self.stream = stream
        self.keep_bytes = (keep_bytes if keep_bytes is not None
                           else keep_bytes_from_env())
        self.segment_records = max(1, int(segment_records))
        os.makedirs(root, exist_ok=True)
        # always start a FRESH segment past anything on disk (crash or
        # restart): sealed history is immutable
        existing = _segments(root, stream)
        self._seq = (existing[-1][0] + 1) if existing else 0
        self._open_lines: List[str] = []

    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.root, f"{self.stream}-{seq:08d}.jsonl")

    def append(self, record: dict) -> None:
        """Append one record durably: the open segment is rewritten
        whole and atomically renamed into place."""
        self._open_lines.append(json.dumps(record, sort_keys=True))
        data = ("\n".join(self._open_lines) + "\n").encode("utf-8")
        _atomic_write(self._seg_path(self._seq), data)
        if len(self._open_lines) >= self.segment_records:
            self._seq += 1
            self._open_lines = []
            self.gc()

    def gc(self) -> int:
        """Drop oldest SEALED segments until the stream fits
        ``keep_bytes``; the open segment is never a candidate.  Returns
        segments removed."""
        segs = _segments(self.root, self.stream)
        sizes = {}
        for seq, path in segs:
            try:
                sizes[seq] = os.path.getsize(path)
            except OSError:
                sizes[seq] = 0
        total = sum(sizes.values())
        removed = 0
        for seq, path in segs:
            if total <= self.keep_bytes:
                break
            if seq >= self._seq:  # the open segment
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= sizes[seq]
            removed += 1
        return removed


# -- reader side (kfhist; incident bundles) ---------------------------------
def streams(root: str) -> List[str]:
    """Stream names present under ``root``, sorted."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    found = {m.group("stream")
             for m in (_SEG_RE.match(n) for n in names) if m}
    return sorted(found)


def scan_stream(root: str, stream: str) -> Tuple[List[dict], int]:
    """``(records, skipped)`` oldest-first across the stream's segments.
    A torn/garbled line (or a whole unreadable segment) is counted in
    ``skipped`` and passed over — corrupt history must not crash the
    reader."""
    records: List[dict] = []
    skipped = 0
    for _seq, path in _segments(root, stream):
        try:
            with open(path, "r", encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            skipped += 1
            continue
        for line in lines:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                skipped += 1
    return records, skipped


def read_stream(root: str, stream: str,
                last: Optional[int] = None) -> List[dict]:
    """The stream's records oldest-first (``last`` keeps only the newest
    N), torn lines silently skipped — the common-case read."""
    records, _ = scan_stream(root, stream)
    if last is not None and last >= 0:
        records = records[-last:]
    return records


def series_from_records(records: List[dict]) -> Dict[str, List[float]]:
    """Per-series sample lists from cluster-rollup records (each record
    carries a ``series`` dict) — the detector feedstock ``kfhist
    --verdict`` rebuilds from disk.  Samples keep record order; a record
    missing a series contributes no sample to it (exactly how the online
    plane accumulates)."""
    out: Dict[str, List[float]] = {}
    for rec in records:
        series = rec.get("series")
        if not isinstance(series, dict):
            continue
        for name, value in series.items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                out.setdefault(name, []).append(float(value))
    return out
