"""kf-pulse: gradient-signal monitoring — noise scale and variance.

The reference framework's signature online statistic is the **gradient
noise scale** (OpenAI GNS estimator, ``tensorflow/ops/cpu/collective.
cpp`` ``NoiseScale``): from ONE training step it estimates the batch
size past which data parallelism stops buying convergence, by comparing
the gradient computed on a small batch (one rank's) against the same
step's large-batch gradient (the allreduced mean).  This module is the
host-plane half of that wire-up:

* :func:`noise_scale` — the ONE scalar implementation of the estimator,
  shared by the host collective plane (:func:`kungfu_tpu.ops.monitor.
  host_noise_scale`) and by tests pinning the in-graph
  :func:`~kungfu_tpu.ops.monitor.global_noise_scale` equal to it.
  Returns ``None`` on a single worker — with ``b_small == b_big`` the
  two-batch estimator is undefined, and 0.0 would read as a (wrong)
  measurement;
* :class:`PulseMonitor` — EMA smoothing + period gating + gauge export.
  The train-step factories (``dp_train_step`` / ``zero_train_step`` /
  ``ShardedTrainer``) compile ONE extra jit program that additionally
  returns the already-reduced square-norm pair; the monitor decides
  per step which program runs (``KF_PULSE_EVERY``), so on off steps the
  bare step's jit program is byte-identical to an uninstrumented build.
  On sample steps it publishes ``kf_gns``, ``kf_grad_variance`` and the
  per-group ``kf_grad_norm{group=...}`` gauges into the unified
  registry, where the :class:`~kungfu_tpu.monitor.aggregator.
  RankReporter` snapshot carries them to the aggregator's ``/cluster``
  rollup, kftop's PULSE section, and the sentinel's ``regress:gns``
  detect stream.

No second gradient all-reduce: the small-batch/large-batch pair comes
from the per-rank flat gradient vs the post-reduce gradient the step
already holds; the only extra collective is the cross-peer MEAN of the
local square norms — one scalar, so the estimate is symmetric across
peers (every rank publishes the same number).

Cost contract: ``KF_PULSE_EVERY=0`` disables the plane —
:func:`PulseMonitor.from_env` returns ``None`` and the step factories
return the bare program untouched.

Env reads are direct ``os.environ`` via the mirror constants below
(defaults pinned equal to :func:`kungfu_tpu.utils.envs.pulse_knobs` by
tests), like every monitor/ module: stdlib-only, importable from the
stubbed ``kftop``/``kfhist`` context.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from kungfu_tpu.monitor import timeline
from kungfu_tpu.monitor.registry import REGISTRY

# env mirror constants (utils/envs.py registers the same tokens;
# pulse_knobs() pins the defaults both sides must agree on)
EVERY_ENV = "KF_PULSE_EVERY"
EMA_ENV = "KF_PULSE_EMA"

#: sample every N steps; 0 disables the plane entirely
DEFAULT_EVERY = 10
#: EMA weight for the published estimates (~5-sample memory, the same
#: alpha as the reporter's step-time EMA)
DEFAULT_EMA_ALPHA = 0.2

#: the epsilon guarding the |G|^2 denominator (reference
#: ``grad_noise_scale.py``; also used by ops/monitor.py in-graph)
GNS_EPS = 1e-30


def _i(env: str, default: int) -> int:
    try:
        return int(os.environ.get(env, "") or default)
    except ValueError:
        return default


def _f(env: str, default: float) -> float:
    try:
        return float(os.environ.get(env, "") or default)
    except ValueError:
        return default


def noise_scale(g_local_sq: float, g_global_sq: float,
                b_small: float, n: int) -> Optional[float]:
    """The OpenAI two-batch GNS estimate ``S / |G|^2`` from one step.

    ``g_local_sq``: cross-peer MEAN of the per-rank (small-batch)
    gradient square norms; ``g_global_sq``: square norm of the
    allreduced (large-batch) mean gradient; ``b_small``: per-rank batch
    size, ``n``: peers (``b_big = n * b_small``).

    The ONE host-side implementation: :func:`kungfu_tpu.ops.monitor.
    host_noise_scale` delegates here, and the in-graph estimator is
    pinned equal by tests across world sizes.  ``None`` when ``n <= 1``
    — the estimator needs two distinct batch sizes to exist."""
    n = int(n)
    if n <= 1:
        return None
    b_small = float(b_small)
    b_big = b_small * n
    g_local_sq = float(g_local_sq)
    g_global_sq = float(g_global_sq)
    g2 = (b_big * g_global_sq - b_small * g_local_sq) / (b_big - b_small)
    s = (g_local_sq - g_global_sq) / (1.0 / b_small - 1.0 / b_big)
    return s / (abs(g2) + GNS_EPS)


def grad_variance(g_local_sq: float, g_global_sq: float) -> float:
    """Cross-peer gradient variance ``E_i |g_i|^2 - |g_avg|^2`` from the
    same square-norm pair the GNS estimate consumes (clamped at 0 — a
    float cancellation must not report negative variance)."""
    return max(0.0, float(g_local_sq) - float(g_global_sq))


class PulseMonitor:
    """EMA smoothing + period gating + gauge export for the pulse pair.

    Host-side and stdlib-only: the jit programs hand over plain floats
    (the square-norm pair and optional per-group norms); this object
    owns every remaining decision — when to sample
    (:meth:`should_sample`), how to smooth (EMA), and what to publish
    (the ``kf_gns`` / ``kf_grad_variance`` / ``kf_grad_norm{group=}``
    gauges plus a ``pulse`` timeline mark when tracing is on)."""

    def __init__(self, every: Optional[int] = None,
                 ema_alpha: Optional[float] = None):
        self.every = max(1, int(every if every is not None
                                else _i(EVERY_ENV, DEFAULT_EVERY)))
        self.ema_alpha = float(ema_alpha if ema_alpha is not None
                               else _f(EMA_ENV, DEFAULT_EMA_ALPHA))
        self.gns: Optional[float] = None            # EMA-smoothed
        self.variance: Optional[float] = None       # EMA-smoothed
        self.samples = 0
        self._count = 0

    @classmethod
    def from_env(cls) -> Optional["PulseMonitor"]:
        """The production constructor: ``None`` (no pulse, no cost) when
        ``KF_PULSE_EVERY`` is 0 or negative."""
        every = _i(EVERY_ENV, DEFAULT_EVERY)
        if every <= 0:
            return None
        return cls(every=every)

    def should_sample(self, step: Optional[int] = None) -> bool:
        """True on pulse steps.  With an explicit ``step`` the gate is
        ``step % every == 0`` (deterministic across restarts from a
        checkpointed step); without one an internal call counter gates
        — the step factories use the counter so caller numbering
        schemes cannot skew the period.  The counter's FIRST sample is
        the ``every``-th call, not the first: step 0 is the compile
        transient, and short runs (most tests) never pay the
        instrumented program's compile at all."""
        if step is not None:
            return int(step) % self.every == 0
        self._count += 1
        return self._count % self.every == 0

    def _ema(self, prev: Optional[float], x: float) -> float:
        if prev is None:
            return x
        a = self.ema_alpha
        return (1.0 - a) * prev + a * x

    def publish_norms(self, group_norms: Dict[str, float],
                      step: Optional[int] = None) -> None:
        """Per-group norm gauges only — for meshes where the two-batch
        GNS pair is undefined (tp/pp/sp/expert sharding mixes what "one
        rank's gradient" means) but the per-kind ``|g|`` is still an
        exact, free readout of the already-reduced gradients."""
        for group, norm in (group_norms or {}).items():
            REGISTRY.gauge("kf_grad_norm", group=str(group)).set(float(norm))
        timeline.event("pulse", "norms",
                       **({} if step is None else {"pulse_step": int(step)}))

    def update(self, g_local_sq: float, g_global_sq: float,
               b_small: float, n: int,
               group_norms: Optional[Dict[str, float]] = None,
               step: Optional[int] = None) -> dict:
        """One pulse sample: smooth, publish, return the sample dict.

        ``gns`` is ``None`` (and its gauge untouched) on a single
        worker; the variance is still defined (it is 0 there) and
        publishes regardless, so a world-size change mid-run cannot
        leave a stale noise-scale gauge lying about the new world."""
        raw = noise_scale(g_local_sq, g_global_sq, b_small, n)
        var = grad_variance(g_local_sq, g_global_sq)
        self.samples += 1
        if raw is not None:
            self.gns = self._ema(self.gns, raw)
            REGISTRY.gauge("kf_gns").set(self.gns)
        self.variance = self._ema(self.variance, var)
        REGISTRY.gauge("kf_grad_variance").set(self.variance)
        for group, norm in (group_norms or {}).items():
            REGISTRY.gauge("kf_grad_norm", group=str(group)).set(float(norm))
        out = {
            "gns": self.gns,
            "gns_raw": raw,
            "grad_variance": self.variance,
            "grad_variance_raw": var,
            "n": int(n),
            "b_small": float(b_small),
        }
        # hot-ish kind (every `every` steps): ring-recorded only when
        # tracing is on; the always-on surfaces are the gauges above
        timeline.event("pulse", "sample",
                       gns=raw, var=var,
                       **({} if step is None else {"pulse_step": int(step)}))
        return out
