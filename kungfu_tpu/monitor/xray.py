"""kf-xray: causal critical-path analysis + step-time attribution.

The flight recorder (PR 4) and live plane (PR 5) say *what happened on
each rank*; :mod:`kungfu_tpu.monitor.skew` says *who was slowest*.  This
module answers the operating question behind ROADMAP items 4 and 5:
**where did the step's wall clock go, and which rank/edge put it
there** — the MLPerf-on-TPU-pods decomposition (compute / exposed comm /
input stall, 1909.09756) extended with the straggler excess the skew
math already isolates.

One pure, stdlib-only implementation with two consumers, exactly like
:mod:`~kungfu_tpu.monitor.skew` (and reusing it for every cross-rank
comparison, so the offline and online verdicts cannot diverge):

* **offline** — ``kftrace --critical-path`` over merged per-rank JSONL
  dumps (:mod:`~kungfu_tpu.monitor.traceview`);
* **online** — the cluster aggregator's ``/cluster`` ``xray`` section
  over the event windows ranks push with their snapshots
  (:mod:`~kungfu_tpu.monitor.aggregator`), rendered by ``kftop``.

Attribution taxonomy (:data:`PHASES`, per step, decomposing the
*critical rank's* wall):

* ``compute``        — wall not covered by any recorded span (the
  residual: model math, optimizer math, host glue);
* ``comm_exposed``   — union of synchronous collective/device span
  intervals, minus the straggler excess below (the irreducible wire +
  algorithm time a skew-free step would still pay);
* ``comm_hidden``    — interval time covered ONLY by async collective
  spans (tags seen in kf-overlap ``issue`` marks): wire time that ran
  concurrently with something else.  A late ``wait()`` that actually
  blocked still counts hidden here — the corrective signal is the
  ``kf_overlap_efficiency`` histogram, which measures blocking at the
  handle;
* ``input_stall``    — union of ``input`` span intervals (the
  consumer-side wait for the next batch, datasets/prefetch.py);
* ``straggler_wait`` — the cross-rank skew excess: per collective group,
  slowest minus fastest duration (``skew.skew_rows``), clamped into the
  critical rank's comm time.  The *culprit edge* is the widest group —
  ``(op, tag, slowest_rank, fastest_rank)``.

Determinism contract: every selection inherits the tie-breaks of
:mod:`~kungfu_tpu.monitor.skew` (lowest rank / ``(op, tag)`` order), and
all analysis is restricted to :data:`XRAY_KINDS` — the kinds BOTH
consumers see (``aggregator.REPORT_KINDS`` forwards a superset), so the
offline report and the live view compute from the same feedstock.
"""

from __future__ import annotations

import os
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from kungfu_tpu.monitor import skew as skewlib

#: the attribution taxonomy, in render order.  ``pp_bubble`` is the
#: pipeline-parallel fill/drain wait (kf-pipeline "bubble" spans): time
#: a stage spent blocked on a cross-DCN activation/gradient dependency
#: — distinct from comm_exposed (the wire itself) because a prefetched
#: hop's wire can be fully hidden while the stage STILL idles waiting
#: for work (the schedule's bubble, not the network's).  Bubble time is
#: EXCLUSIVE: comm intervals inside a bubble span are charged to the
#: bubble (the wait), not double-counted as exposed wire — the phases
#: keep tiling the step wall
PHASES = ("compute", "comm_exposed", "comm_hidden", "input_stall",
          "pp_bubble", "straggler_wait")

#: event kinds the attribution consumes.  Restricting BOTH consumers to
#: this set is what makes "offline == online" assertable: a dump also
#: carries send/recv/chaos marks the live plane never forwards, and wall
#: windows computed over different kind sets would disagree.
XRAY_KINDS = frozenset(skewlib.COLLECTIVE_KINDS) | frozenset(
    {"input", "overlap", "pp"})

#: online attribution window (steps) — mirror constant next to its
#: reader like timeline.py's CAP_ENV; utils/envs.py registers the token
WINDOW_ENV = "KF_XRAY_WINDOW_STEPS"
DEFAULT_WINDOW_STEPS = 32


def window_steps_from_env() -> int:
    try:
        v = int(os.environ.get(WINDOW_ENV, "") or DEFAULT_WINDOW_STEPS)
    except ValueError:
        v = DEFAULT_WINDOW_STEPS
    return max(1, v)


# -- interval math ----------------------------------------------------------
def _union_len(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of ``(start, end)`` intervals —
    concurrent spans (async pool threads) must count wall time once."""
    if not intervals:
        return 0.0
    total = 0.0
    lo = hi = None
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if hi is None or s > hi:
            if hi is not None:
                total += hi - lo
            lo, hi = s, e
        elif e > hi:
            hi = e
    if hi is not None:
        total += hi - lo
    return total


def _xray_events(events: List[dict]) -> List[dict]:
    return [e for e in events if e.get("kind") in XRAY_KINDS]


def _async_tags(events: List[dict]) -> set:
    """Tags issued through the kf-overlap async window (their collective
    spans ran on the pool, concurrently with the issuer)."""
    return {
        (e.get("attrs") or {}).get("tag")
        for e in events
        if e.get("kind") == "overlap" and e.get("name") == "issue"
    } - {None}


def rank_phase_split(events: List[dict],
                     async_tags: Optional[set] = None) -> Dict[str, float]:
    """Single-rank wall decomposition over one window of events (all
    :data:`XRAY_KINDS`; cross-rank ``straggler_wait`` is 0 here — that
    phase only exists against other ranks).  ``wall_s`` spans the first
    event start to the last event end."""
    events = _xray_events(events)
    if async_tags is None:
        async_tags = _async_tags(events)
    spans = [e for e in events if e.get("dur", 0) > 0]
    marks = [e for e in events if not e.get("dur", 0)]
    if not spans and not marks:
        return {"wall_s": 0.0, **{p: 0.0 for p in PHASES}}
    t_lo = min(e["ts"] for e in spans + marks)
    t_hi = max(e["ts"] + e.get("dur", 0.0) for e in spans + marks)
    wall = max(0.0, t_hi - t_lo)
    sync_comm, async_comm, inputs, bubbles = [], [], [], []
    for e in spans:
        iv = (e["ts"], e["ts"] + e["dur"])
        if e["kind"] in skewlib.COLLECTIVE_KINDS:
            tag = (e.get("attrs") or {}).get("tag") or e["name"]
            (async_comm if tag in async_tags else sync_comm).append(iv)
        elif e["kind"] == "input":
            inputs.append(iv)
        elif e["kind"] == "pp" and e.get("name") == "bubble":
            # the dependency wait itself; pp "fwd"/"bwd" spans are
            # stage COMPUTE and deliberately fall through (subtracting
            # them would hollow the compute phase out)
            bubbles.append(iv)
    # bubble owns its wall time: a blocking pipeline recv records BOTH
    # a bubble span (the owner-thread wait) and a sync collective span
    # (the wire) over the same interval — counting that interval in
    # comm_exposed too would make the phases sum past the wall.  The
    # comm phases therefore measure comm time OUTSIDE bubbles; with no
    # bubble spans in the window every value below is byte-identical to
    # the pre-pp math.
    pp_bubble = _union_len(bubbles)
    comm_exposed = max(0.0, _union_len(sync_comm + bubbles) - pp_bubble)
    comm_hidden = max(0.0, _union_len(sync_comm + async_comm + bubbles)
                      - _union_len(sync_comm + bubbles))
    input_stall = _union_len(inputs)
    spanned = _union_len(sync_comm + async_comm + inputs + bubbles)
    compute = max(0.0, wall - spanned)
    return {
        "wall_s": wall,
        "compute": compute,
        "comm_exposed": comm_exposed,
        "comm_hidden": comm_hidden,
        "input_stall": input_stall,
        "pp_bubble": pp_bubble,
        "straggler_wait": 0.0,
    }


# -- per-step cluster attribution ------------------------------------------
def _by_step(events: List[dict]) -> Dict[int, List[dict]]:
    out: Dict[int, List[dict]] = defaultdict(list)
    for e in _xray_events(events):
        step = e.get("step")
        if isinstance(step, int):
            out[step].append(e)
    return out


def _culprit(rows: List[dict]) -> Optional[dict]:
    """The widest skew row, reduced to the edge fields — the dependency
    edge ``slowest_rank → fastest_rank`` of collective ``op/tag`` is
    where the straggler excess enters the critical path."""
    if not rows:
        return None
    r = rows[0]
    return {k: r[k] for k in ("op", "tag", "slowest_rank", "slowest_s",
                              "fastest_rank", "fastest_s", "skew_s")}


def step_attribution(events: List[dict]) -> List[dict]:
    """Per-step cluster attribution rows, step-ordered.  Each row
    decomposes the step wall of the *critical rank* (largest per-rank
    wall window; duration ties → lowest rank, the skew.py contract) into
    :data:`PHASES`, and names the culprit edge from the step's widest
    cross-rank skew group."""
    rows: List[dict] = []
    async_tags = _async_tags(events)
    stepped = _by_step(events)
    for step in sorted(stepped):
        evs = stepped[step]
        by_rank: Dict[int, List[dict]] = defaultdict(list)
        for e in evs:
            r = e.get("rank")
            if isinstance(r, int):
                by_rank[r].append(e)
        if not by_rank:
            continue
        splits = {r: rank_phase_split(res, async_tags)
                  for r, res in by_rank.items()}
        crit = max(sorted(splits), key=lambda r: splits[r]["wall_s"])
        phases = dict(splits[crit])
        wall = phases.pop("wall_s")
        skew_rows = skewlib.skew_rows(evs)
        # the straggler excess cannot exceed the critical rank's comm
        # time — it is the skew PORTION of those very spans
        excess = min(sum(r["skew_s"] for r in skew_rows),
                     phases["comm_exposed"])
        phases["comm_exposed"] -= excess
        phases["straggler_wait"] = excess
        rows.append({
            "step": step,
            "wall_s": wall,
            "critical_rank": crit,
            "ranks": len(by_rank),
            "phases": phases,
            "culprit": _culprit(skew_rows),
        })
    return rows


def verdict(events: List[dict], rows: Optional[List[dict]] = None) -> dict:
    """THE shared offline/online verdict: straggler rank (skew.py's
    vote), culprit edge (widest skew group over the whole window),
    dominant phase, and the phase totals.  ``kftrace --critical-path``
    prints exactly this object; the aggregator serves exactly this
    object under ``/cluster → xray → verdict`` — asserted identical in
    the chaos tests.  ``rows`` passes precomputed
    :func:`step_attribution` output for the same events (the live
    ``/cluster`` path computes it once per scrape, not twice)."""
    events = _xray_events(events)
    if rows is None:
        rows = step_attribution(events)
    totals = {p: sum(r["phases"][p] for r in rows) for p in PHASES}
    dominant = max(PHASES, key=lambda p: totals[p]) if rows else None
    crit_votes: Dict[int, int] = defaultdict(int)
    for r in rows:
        crit_votes[r["critical_rank"]] += 1
    # ONE whole-window skew pass: the culprit edge is the widest row and
    # the straggler vote is derived from the same rows (identical math
    # to skewlib.straggler_verdict, which would re-group internally)
    sk = skewlib.skew_rows(events)
    votes: Dict[int, int] = defaultdict(int)
    for row in sk:
        votes[row["slowest_rank"]] += 1
    return {
        "straggler": (max(sorted(votes), key=votes.get)
                      if votes else None),
        "culprit": _culprit(sk),
        "dominant": dominant,
        "phases": totals,
        "steps_seen": len(rows),
        "critical_rank": (max(sorted(crit_votes), key=crit_votes.get)
                          if crit_votes else None),
    }


# -- critical path ----------------------------------------------------------
def critical_path(events: List[dict],
                  step: Optional[int] = None) -> List[dict]:
    """The longest dependency chain through one step's causal graph.

    Nodes are collective groups (same ``(op, tag)`` — and, when stamped,
    the same derived ``trace`` id — on every rank); each group is a
    barrier that completes with its slowest participant.  The chain
    walks groups in completion order; between barriers it follows the
    NEXT group's slowest rank, whose gap (compute/input on that rank) is
    what the step actually waited on.  Returns hops::

        {"kind": "collective", "rank", "op", "tag", "trace",
         "dur_s", "skew_s"}          # the barrier, at its slowest rank
        {"kind": "gap", "rank", "dur_s"}   # inter-barrier time on the
                                           # rank owning the next hop
    """
    evs = _xray_events(events)
    if step is not None:
        evs = [e for e in evs if e.get("step") == step]
    groups: Dict[Tuple[str, str], Dict[int, dict]] = defaultdict(dict)
    for e in evs:
        if e["kind"] not in skewlib.COLLECTIVE_KINDS or e.get("dur", 0) <= 0:
            continue
        attrs = e.get("attrs") or {}
        op = attrs.get("op") or e["name"]
        tag = attrs.get("tag") or e["name"]
        r = e.get("rank")
        cur = groups[(op, tag)].get(r)
        if cur is None or e["dur"] > cur["dur"]:
            groups[(op, tag)][r] = e
    if not groups:
        return []
    nodes = []
    for (op, tag), per_rank in groups.items():
        ranks = sorted(per_rank)
        slowest = max(ranks, key=lambda r: per_rank[r]["dur"])
        fastest = min(ranks, key=lambda r: per_rank[r]["dur"])
        ev = per_rank[slowest]
        nodes.append({
            "op": op, "tag": tag, "rank": slowest,
            "trace": (ev.get("attrs") or {}).get("trace"),
            "ts": ev["ts"], "end": ev["ts"] + ev["dur"],
            "dur_s": ev["dur"],
            "skew_s": per_rank[slowest]["dur"] - per_rank[fastest]["dur"],
        })
    nodes.sort(key=lambda n: (n["end"], n["op"], n["tag"]))
    hops: List[dict] = []
    prev_end = None
    for n in nodes:
        if prev_end is not None and n["ts"] > prev_end:
            hops.append({"kind": "gap", "rank": n["rank"],
                         "dur_s": n["ts"] - prev_end})
        hops.append({"kind": "collective", "rank": n["rank"], "op": n["op"],
                     "tag": n["tag"], "trace": n["trace"],
                     "dur_s": n["dur_s"], "skew_s": n["skew_s"]})
        prev_end = max(prev_end, n["end"]) if prev_end is not None else n["end"]
    return hops


# -- online view (aggregator glue) -----------------------------------------
def online_view(events: List[dict],
                window_steps: Optional[int] = None) -> Optional[dict]:
    """The ``/cluster`` ``xray`` section body: the verdict plus the last
    ``window_steps`` attribution rows.  ``None`` when the window holds
    nothing attributable — a job without collective spans renders no
    XRAY section rather than a table of zeros."""
    window = window_steps if window_steps is not None else window_steps_from_env()
    rows = step_attribution(events)
    if not rows:
        return None
    rows = rows[-window:]
    keep = {r["step"] for r in rows}
    win_events = [e for e in _xray_events(events) if e.get("step") in keep]
    # the sliced rows ARE step_attribution(win_events) (per-step rows
    # depend only on their own step's events; async tags come from the
    # full window on both the offline and online paths) — pass them so
    # a /cluster scrape attributes once, not twice
    return {"verdict": verdict(win_events, rows=rows), "steps": rows}


# -- rendering (kftrace --critical-path) -----------------------------------
def _fmt_ms(v: float) -> str:
    return f"{v * 1e3:.1f}ms"


def render_report(events: List[dict], top: int = 10) -> str:
    """The offline ``kftrace --critical-path`` text: verdict, per-step
    attribution, and the longest chain of the widest step."""
    evs = _xray_events(events)
    v = verdict(evs)
    rows = step_attribution(evs)
    lines = [f"kf-xray: {len(evs)} attributable event(s), "
             f"{v['steps_seen']} step(s)"]
    if v["straggler"] is not None:
        lines.append(f"straggler verdict: rank {v['straggler']}")
    c = v["culprit"]
    if c is not None:
        lines.append(
            f"culprit edge: {c['op']}/{c['tag']} "
            f"rank {c['slowest_rank']} ({_fmt_ms(c['slowest_s'])}) -> "
            f"rank {c['fastest_rank']} ({_fmt_ms(c['fastest_s'])}), "
            f"skew {_fmt_ms(c['skew_s'])}")
    if v["dominant"] is not None:
        total = sum(v["phases"].values()) or 1.0
        lines.append(
            f"dominant phase: {v['dominant']} "
            f"({v['phases'][v['dominant']] / total:.0%} of attributed time)")
    lines.append("")
    lines.append("== per-step attribution "
                 "(compute / comm_exposed / comm_hidden / input_stall / "
                 "straggler_wait)")
    if not rows:
        lines.append("  (no stepped collective spans)")
    for r in rows[-top:]:
        ph = r["phases"]
        cu = r["culprit"]
        lines.append(
            f"  step {r['step']}: wall {_fmt_ms(r['wall_s'])} = "
            + " + ".join(f"{p}:{_fmt_ms(ph[p])}" for p in PHASES)
            + f" | critical rank {r['critical_rank']}"
            + (f" | culprit {cu['op']}/{cu['tag']}@rank{cu['slowest_rank']}"
               if cu else ""))
    lines.append("")
    widest = None
    for r in rows:
        if r["culprit"] and (widest is None
                             or r["culprit"]["skew_s"]
                             > widest["culprit"]["skew_s"]):
            widest = r
    if widest is not None:
        step = widest["step"]
        lines.append(f"== critical path (step {step}, longest chain)")
        for hop in critical_path(evs, step)[:top * 2]:
            if hop["kind"] == "gap":
                lines.append(f"  rank {hop['rank']}: "
                             f"[compute/input {_fmt_ms(hop['dur_s'])}]")
            else:
                lines.append(
                    f"  rank {hop['rank']}: {hop['op']}/{hop['tag']} "
                    f"{_fmt_ms(hop['dur_s'])}"
                    + (f" (skew {_fmt_ms(hop['skew_s'])})"
                       if hop["skew_s"] > 0 else ""))
    return "\n".join(lines) + "\n"
