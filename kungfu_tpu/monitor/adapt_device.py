"""kf-adapt drivers: measured online collective adaptation, both planes.

The decision core is the UCB bandit (:mod:`kungfu_tpu.policy.bandit`);
this module is the *plumbing that makes it collective-safe*:

* :class:`HostBanditDriver` — arms are host-plane strategies
  (:class:`~kungfu_tpu.plan.strategy.Strategy` graph sets) plus the
  measured-latency MST tree (``"mst"``).  The observable is the measured
  per-step engine collective seconds the caller feeds to :meth:`step`;
  the engine's own throughput windows
  (:meth:`~kungfu_tpu.comm.engine.CollectiveEngine.window_peek`) and
  swap-eligibility epochs gate the hysteresis.

* :class:`DeviceBanditDriver` — arms are the compiled allreduce
  schedules ``psum``/``two_stage``/``ring``/``pallas_ring`` (the last
  lowering BELOW XLA into the in-kernel-overlap ring kernels of
  :mod:`kungfu_tpu.ops.pallas.collectives`), learned **per payload-size
  bucket** (small control tensors and large fused gradient buckets get
  independent winners — :data:`kungfu_tpu.ops.schedules.SIZE_BUCKETS`)
  and installed into the communicator's per-``nbytes`` dispatch
  (:meth:`~kungfu_tpu.comm.device.Communicator.set_bucket_strategy`).
  Observations come from the communicator's latency hook (every eager
  collective reports ``(nbytes, schedule, seconds)``) or, opt-in, from
  the flight recorder's device-plane collective spans
  (``feed="timeline"``: the per-schedule EMA ring is fed from
  ``timeline.events_tail`` device spans, which now carry ``nbytes`` and
  ``sched`` attrs).

The swap fence — identical to
:class:`~kungfu_tpu.monitor.adaptive.AdaptiveStrategyDriver`'s
discipline (reference ``adaptation.go:8-28``) — makes mid-training
switching safe on a live cluster:

1. **the window exchange is an allreduce**: each rank contributes its
   local window's per-arm ``(count, sum)`` deltas plus its straggler
   vote; the agreed sums are identical everywhere, so every rank folds
   the same numbers into its bandit table;
2. **the decision is pure** (:meth:`ArmStats.select`, ties break by arm
   order) — identical tables ⇒ identical proposal, no leader;
3. **digest-agree**: ``consensus_bytes`` over the proposed arm (a
   diverged rank is a bug surfaced loudly, not a deadlock later);
4. **barrier, then swap in lockstep**, stamping a ``swap`` timeline
   event on every rank and marking the engine's swap epoch so the next
   windows are attributed to the new arm only.

Straggler verdicts (:mod:`kungfu_tpu.monitor.skew`) feed in as the vote:
when a cluster-wide majority sees a consistent straggler rank, the
window is *not* charged to the active arm — strategy switching cannot
fix a sick rank — and the host driver prefers the MST re-carve (the
topology fix that routes around it) when that arm is available.
Scope note: the local suspicion reads the process-local flight-recorder
ring, whose cross-rank collective groups exist in in-process clusters
(bench, tests, kfrun emulation, co-located multi-rank runs); a
one-rank-per-process deployment records only its own spans, so its
votes are conservatively 0 and adaptation rides the arm measurements
alone — wiring the vote to the aggregator's merged ``/cluster`` skew
view is the natural extension.

Bandit state does NOT survive membership changes: a 4-rank winner says
nothing about the 2-rank regime, so both drivers reset and re-explore
when the cluster version moves (wired through ``elastic_step``'s
``bandit=`` hook and self-detected from ``peer.cluster_version``).
"""

from __future__ import annotations

import math
import statistics
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kungfu_tpu.monitor import ledger, timeline
from kungfu_tpu.monitor.skew import (COLLECTIVE_KINDS, SPIKE_FACTOR,
                                     skew_rows, straggler_verdict)
from kungfu_tpu.policy.bandit import ArmStats, ScheduleTable
from kungfu_tpu.utils.log import get_logger

_log = get_logger("kf-adapt")


def _spiky_straggler(events: Sequence[dict]) -> bool:
    """True when the skew analysis names a straggler AND at least one
    group shows a real spike (slowest >= SPIKE_FACTOR x fastest).
    ``straggler_verdict`` alone votes a rank for ANY >=2-rank group —
    including perfectly healthy ones with microsecond skew — and a
    bandit that believed it would discard every window and never learn;
    the spike threshold keeps the verdict for genuinely sick ranks."""
    rows = skew_rows(list(events))
    spiky = any(
        r["fastest_s"] > 0 and r["slowest_s"] >= SPIKE_FACTOR * r["fastest_s"]
        for r in rows
    )
    return spiky and straggler_verdict(list(events)) is not None

#: the measured-latency MST arm of the host bandit: installing it
#: re-carves the broadcast topology over the ping-latency MST
#: (``peer.set_tree``), the reference's ``adaptation.cpp`` move
MST_ARM = "mst"

#: default host-plane arm set; the incumbent engine strategy is
#: prepended when it is not already a member
DEFAULT_HOST_ARMS = ("STAR", "RING", "BINARY_TREE_STAR", MST_ARM)

_DEVICE_SPAN_KINDS = frozenset({"device"})


def _median(xs: Sequence[float]) -> Optional[float]:
    finite = [x for x in xs if math.isfinite(x) and x > 0]
    return statistics.median(finite) if finite else None


class HostBanditDriver:
    """Per-rank driver over the host collective engine.  Every rank must
    construct one with the SAME arguments and call :meth:`step` at the
    same loop points (the window exchange and the fence are collective).

    Typical loop::

        driver = HostBanditDriver(peer, check_every=4)
        for batch in data:
            t0 = time.perf_counter()
            grads = peer.engine().all_reduce(grads, op="mean")
            driver.step(time.perf_counter() - t0)  # may lockstep-swap
    """

    def __init__(self, peer, arms: Optional[Sequence[str]] = None,
                 check_every: int = 8, c: float = 0.5, min_pulls: int = 1,
                 decay: float = 1.0, min_swap_collectives: int = 2,
                 mst_samples: int = 3):
        self.peer = peer
        self.check_every = max(1, check_every)
        self.min_swap_collectives = max(0, min_swap_collectives)
        self.mst_samples = max(1, mst_samples)
        arm_list = list(arms) if arms is not None else list(DEFAULT_HOST_ARMS)
        incumbent = self._engine_arm_name()
        if incumbent is not None and incumbent not in arm_list:
            arm_list.insert(0, incumbent)
        self.table = ArmStats(arm_list, c=c, min_pulls=min_pulls, decay=decay)
        self.active = incumbent if incumbent in arm_list else arm_list[0]
        self._window: List[float] = []
        self._step_n = 0
        self._seq = 0            # check-boundary sequence (lockstep)
        self._settling = False   # discard the first window after a swap
        self._skew_cursor = 0
        self._seen_version = getattr(peer, "cluster_version", 0)
        self.swaps = 0

    # -- helpers ---------------------------------------------------------
    def _engine_arm_name(self) -> Optional[str]:
        if self.peer is None or getattr(self.peer, "detached", False):
            return None  # a detached peer has no engine in the new world
        engine = self.peer.engine()
        if engine is None:
            return None
        s = engine.strategy
        if s is None:  # an explicit tree is installed
            return MST_ARM
        return getattr(s, "name", str(s))

    def _rank(self) -> Optional[int]:
        r = self.peer.chaos_rank()
        return r if r is not None else self.peer.rank()

    def _straggler_suspected(self) -> bool:
        """Local suspicion from the flight recorder's recent collective
        spans — cheap (cursor read), spike-thresholded
        (:func:`_spiky_straggler`), and only ever *advisory*: the
        cluster-wide majority vote in the window exchange is what makes
        the verdict identical on every rank."""
        self._skew_cursor, events = timeline.events_tail(
            self._skew_cursor, kinds=frozenset(COLLECTIVE_KINDS))
        return _spiky_straggler(events)

    # -- membership ------------------------------------------------------
    def on_membership_change(self, version: Optional[int] = None) -> None:
        """Reset and re-explore: called by ``elastic_step(bandit=...)``
        after a resize, and self-detected from ``peer.cluster_version``.
        The rebuilt engine runs the configured default strategy, so the
        active arm re-anchors on what is actually installed."""
        self.table.reset()
        self._window = []
        self._settling = True
        # re-anchor the check fence at the resize boundary: a joiner
        # constructs a FRESH driver (counters 0), so survivors carrying
        # pre-resize _step_n/_seq would hit check boundaries at loop
        # iterations the joiner does not (mismatched collective streams)
        # and stamp different seqs into the swap digest (false
        # "tables diverged" consensus failures)
        self._step_n = 0
        self._seq = 0
        self._seen_version = (version if version is not None
                              else getattr(self.peer, "cluster_version", 0))
        incumbent = self._engine_arm_name()
        if incumbent is not None and incumbent in self.table.arms:
            self.active = incumbent
        _log.info("membership changed: bandit state reset (re-exploring "
                  "from %s)", self.active)

    # -- the per-step driver ---------------------------------------------
    def step(self, collective_seconds: Optional[float] = None) -> bool:
        """Feed one step's measured collective seconds; returns True when
        a fenced swap happened (collectively, on every rank)."""
        if getattr(self.peer, "cluster_version", 0) != self._seen_version:
            self.on_membership_change()
        if (collective_seconds is not None
                and math.isfinite(collective_seconds)
                and collective_seconds > 0):
            self._window.append(collective_seconds)
        self._step_n += 1
        if self._step_n % self.check_every:
            return False
        return self._check()

    def _check(self) -> bool:
        med = _median(self._window)
        self._window = []  # cleared even when there is no engine — a
        # single-process loop feeding step() forever must not grow an
        # unbounded list of measurements nobody will read
        engine = self.peer.engine()
        if engine is None:
            return False  # single-process: no host collectives to adapt
        suspected = self._straggler_suspected()
        # ONE fused window-exchange allreduce (record=False keeps the
        # 24-byte vote out of the throughput window it is judging):
        # [n_obs, sum_of_window_medians, straggler_votes]
        row = np.array(
            [0.0 if med is None else 1.0,
             0.0 if med is None else med,
             1.0 if suspected else 0.0],
            np.float64,
        )
        agreed = engine.all_reduce(row, op="sum", record=False)
        n_obs, obs_sum = float(agreed[0]), float(agreed[1])
        straggler = float(agreed[2]) * 2 > self.peer.size()
        self._seq += 1
        if self._settling:
            # the first window after a swap measures the swap transient
            # (connection churn, fresh graphs) — a clean window seeds the
            # new arm's own baseline instead
            self._settling = False
            return False
        if n_obs > 0 and not straggler and self.active in self.table.arms:
            # agreed observation: the mean of the ranks' window medians.
            # A straggler-voted window is NOT charged to the arm — a sick
            # rank slows every strategy; swapping cannot fix it
            self.table.observe(self.active, obs_sum / n_obs)
        proposal = self.table.select()
        if straggler and MST_ARM in self.table.arms:
            # agreed straggler: prefer the topology fix that routes
            # around the slow rank/link over strategy roulette
            proposal = MST_ARM
        if proposal == self.active:
            return False
        if not engine.swap_eligible(self.min_swap_collectives):
            return False  # the incumbent has not been measured yet
        self._install(engine, proposal)
        return True

    # -- the fenced swap --------------------------------------------------
    def _install(self, engine, proposal: str) -> None:
        """Digest-agree → barrier → swap in lockstep → ``swap`` event on
        every rank (the reference ``SetGlobalStrategy`` fence).

        The proposal digest runs for EVERY arm, the MST included: a
        diverged rank must be surfaced by this loud RuntimeError, not by
        the deadlock of one rank entering the latency allgather while
        another enters a consensus round (the exact failure the fence
        exists to catch)."""
        prev = self.active
        digest = f"kf-bandit:{self._seq}:{proposal}".encode()
        if not self.peer.consensus_bytes(digest, name="bandit-swap"):
            raise RuntimeError(
                f"ranks disagree on the bandit swap target {proposal!r}"
                " — bandit tables diverged (non-collective step calls?)"
            )
        if proposal == MST_ARM:
            from kungfu_tpu.monitor.adapt import \
                minimum_spanning_tree_from_latencies

            # the latency matrix is allgathered → identical on all ranks
            # → identical MST; peer.set_tree runs its own digest
            # consensus + barrier around the engine swap
            forest = minimum_spanning_tree_from_latencies(
                self.peer, samples=self.mst_samples)
            self.peer.set_tree(forest)
        else:
            from kungfu_tpu.plan.strategy import parse_strategy

            self.peer.barrier()
            engine.set_strategy(parse_strategy(proposal))
        engine.mark_swap()
        timeline.event(
            "swap", proposal, rank=self._rank(), plane="host",
            seq=self._seq, prev=prev, step=timeline.current_step(),
        )
        # kf-ledger: the durable accountability record — the swap digest
        # seq is the consensus round that agreed on this change
        ledger.record_decision(
            "bandit-host", "strategy", prev, proposal,
            consensus_seq=self._seq, evidence={"plane": "host"})
        self.active = proposal
        self._settling = True
        self.swaps += 1
        _log.info("bandit swap (host): %s -> %s at seq %d",
                  prev, proposal, self._seq)


class DeviceBanditDriver:
    """Per-controller driver over the device communicator's size-bucketed
    schedule table.  Arms are the compiled allreduce schedules; each
    payload bucket learns its own winner and installs it via
    ``comm.set_bucket_strategy`` (re-jit happens lazily on next use —
    compiled programs are cached per ``(op, shape, schedule)``).

    Single-controller meshes decide locally (the decision is
    deterministic anyway); multi-controller worlds fence through the
    peer's host plane exactly like :class:`HostBanditDriver`.
    """

    def __init__(self, comm, peer=None,
                 arms: Optional[Sequence[str]] = None,
                 check_every: int = 16, c: float = 0.5, min_pulls: int = 1,
                 decay: float = 1.0, feed: str = "hook"):
        from kungfu_tpu.ops.schedules import ALLREDUCE_SCHEDULES, SIZE_BUCKETS

        if feed not in ("hook", "timeline"):
            raise ValueError(f"feed must be hook|timeline, got {feed!r}")
        self.peer = peer
        self.check_every = max(1, check_every)
        self._buckets = len(SIZE_BUCKETS)
        self._bucket_names = SIZE_BUCKETS
        arm_list = list(arms) if arms is not None else list(ALLREDUCE_SCHEDULES)
        self.table = ScheduleTable(arm_list, self._buckets, c=c,
                                   min_pulls=min_pulls, decay=decay)
        self._feed = feed
        self._tl_cursor = 0
        self._skew_cursor = 0
        #: local window accumulators: [bucket][arm] -> [count, sum]
        self._pending = [
            {a: [0.0, 0.0] for a in self.table.arms}
            for _ in range(self._buckets)
        ]
        self._settling = [False] * self._buckets
        self._step_n = 0
        self._seq = 0
        self.swaps = 0
        self.comm = None
        self._seen_version = None
        self.rebind(comm)

    # -- binding / membership --------------------------------------------
    def rebind(self, comm) -> None:
        """Bind to a (new) mesh-epoch communicator: install the latency
        hook, seed the active arms from its current strategy, and reset
        the table — a new epoch is a new regime (re-explore)."""
        if self.comm is not None and self.comm is not comm:
            self.comm.set_latency_hook(None)
        self.comm = comm
        self._seen_version = comm.version
        if self._feed == "hook":
            comm.set_latency_hook(self._on_collective)
        self.table.reset()
        for b in range(self._buckets):
            self.table.active[b] = comm.strategy_for_bucket(b)
            self._pending[b] = {a: [0.0, 0.0] for a in self.table.arms}
        self._settling = [False] * self._buckets
        # re-anchor the check fence (see HostBanditDriver
        # .on_membership_change): a new epoch's joiners start fresh
        # drivers at 0, and the swap digest embeds _seq
        self._step_n = 0
        self._seq = 0

    def on_membership_change(self, version: Optional[int] = None) -> None:
        """Re-explore after a resize (``elastic_step(bandit=...)``): the
        next ``step`` rebinds to the new epoch's communicator."""
        self._seen_version = None

    # -- feeding ---------------------------------------------------------
    def _on_collective(self, nbytes: int, sched: str, seconds: float) -> None:
        from kungfu_tpu.ops.schedules import size_bucket

        if not math.isfinite(seconds) or seconds <= 0:
            return
        acc = self._pending[size_bucket(nbytes)].get(sched)
        if acc is not None:
            acc[0] += 1.0
            acc[1] += seconds

    def feed_from_timeline(self) -> int:
        """Drain device-plane collective spans from the flight recorder
        into the per-schedule rings (``feed="timeline"`` mode — for loops
        whose collectives are observed by tracing rather than the eager
        hook).  Returns the number of spans consumed."""
        self._tl_cursor, events = timeline.events_tail(
            self._tl_cursor, kinds=_DEVICE_SPAN_KINDS)
        used = 0
        for e in events:
            attrs = e.get("attrs") or {}
            nbytes, sched = attrs.get("nbytes"), attrs.get("sched")
            if nbytes is None or sched is None or e["dur"] <= 0:
                continue
            self._on_collective(int(nbytes), sched, float(e["dur"]))
            used += 1
        return used

    def _straggler_suspected(self) -> bool:
        self._skew_cursor, events = timeline.events_tail(
            self._skew_cursor, kinds=frozenset(COLLECTIVE_KINDS))
        return _spiky_straggler(events)

    # -- the per-step driver ---------------------------------------------
    def step(self) -> bool:
        """Call once per training step on every controller; returns True
        when at least one bucket's schedule was swapped (in lockstep)."""
        if self.peer is not None and (
                self._seen_version is None
                or self.peer.cluster_version != self._seen_version):
            comm = self.peer.communicator()
            if comm is not self.comm or comm.version != self._seen_version:
                self.rebind(comm)
        if self._feed == "timeline":
            self.feed_from_timeline()
        self._step_n += 1
        if self._step_n % self.check_every:
            return False
        return self._check()

    def _agree(self, row: np.ndarray) -> Tuple[np.ndarray, int]:
        """Sum the window vector across ranks; returns (agreed, world)."""
        engine = self.peer.engine() if self.peer is not None else None
        if engine is None:
            return row, 1
        return (np.asarray(engine.all_reduce(row, op="sum", record=False)),
                self.peer.size())

    def _check(self) -> bool:
        suspected = self._straggler_suspected()
        arms = self.table.arms
        # fused exchange: per (bucket, arm) [count, sum] + straggler vote
        flat: List[float] = []
        for b in range(self._buckets):
            for a in arms:
                flat.extend(self._pending[b][a])
            self._pending[b] = {a: [0.0, 0.0] for a in arms}
        flat.append(1.0 if suspected else 0.0)
        agreed, world = self._agree(np.asarray(flat, np.float64))
        straggler = float(agreed[-1]) * 2 > world
        self._seq += 1
        swapped = False
        proposals: List[Tuple[int, str, str]] = []
        off = 0
        for b in range(self._buckets):
            settle, self._settling[b] = self._settling[b], False
            for i, a in enumerate(arms):
                cnt, tot = float(agreed[off + 2 * i]), float(agreed[off + 2 * i + 1])
                if cnt > 0 and not straggler and not settle:
                    # one window observation per (bucket, arm): the mean
                    # collective latency across ranks and repeats.
                    # Straggler-voted and post-swap (compile) windows are
                    # discarded, not charged
                    self.table.observe(b, a, tot / cnt)
            off += 2 * len(arms)
            proposal = self.table.select(b)
            if proposal != self.table.active[b]:
                proposals.append((b, self.table.active[b], proposal))
        if not proposals:
            return False
        self._fence(proposals)
        for b, prev, arm in proposals:
            self.comm.set_bucket_strategy(b, arm)
            self.table.install(b, arm)
            self._settling[b] = True
            timeline.event(
                "swap", arm, rank=self._rank(), plane="device",
                bucket=self._bucket_names[b], seq=self._seq, prev=prev,
                step=timeline.current_step(),
            )
            ledger.record_decision(
                "bandit-device", "schedule", prev, arm,
                consensus_seq=self._seq,
                evidence={"plane": "device",
                          "bucket": self._bucket_names[b]})
            self.swaps += 1
            swapped = True
            _log.info("bandit swap (device, %s bucket): %s -> %s at seq %d",
                      self._bucket_names[b], prev, arm, self._seq)
        return swapped

    def _rank(self) -> Optional[int]:
        if self.peer is None:
            return timeline.current_rank()
        r = self.peer.chaos_rank()
        return r if r is not None else self.peer.rank()

    def _fence(self, proposals: List[Tuple[int, str, str]]) -> None:
        """Digest-agree + barrier across controllers before any bucket
        installs — a survivor compiling ring collectives while a peer
        compiles psum is two different programs on one mesh."""
        if self.peer is None or self.peer.size() <= 1:
            return
        digest = ";".join(
            f"{self._bucket_names[b]}:{prev}->{arm}"
            for b, prev, arm in proposals
        )
        payload = f"kf-bandit-dev:{self._seq}:{digest}".encode()
        if not self.peer.consensus_bytes(payload, name="bandit-dev-swap"):
            raise RuntimeError(
                "controllers disagree on the device bucket swap "
                f"{digest!r} — bandit tables diverged"
            )
        self.peer.barrier()

    def summary(self) -> Dict:
        """Per-bucket active arm + arm stats (observability surface)."""
        return self.table.summary()
