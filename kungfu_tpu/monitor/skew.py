"""Cross-rank straggler math shared by ``kftrace`` and the live plane.

One pure, stdlib-only module holding the skew/straggler analysis that
PR 4 shipped inside :mod:`kungfu_tpu.monitor.traceview`: per-collective
cross-rank skew, slowest-rank-per-step windows, fault/latency-spike
overlap, and the straggler verdict.  Both consumers feed it the same
event dicts (``{ts, rank, step, kind, name, dur, attrs}``):

* **offline** — ``kftrace report`` over merged per-rank JSONL dumps;
* **online** — the cluster aggregator (:mod:`kungfu_tpu.monitor.
  aggregator`) over the collective spans each rank pushes with its
  snapshot.

Sharing the implementation is the point, not a convenience: the live
``/cluster`` skew section and the post-mortem ``kftrace`` report must
name the same straggler from the same events, or the operator reading
``kftop`` during the incident and the engineer reading the dump after it
are debugging two different clusters.

All analyses compare **durations** of the same rendezvous tag across
ranks, never wall-clock timestamps across hosts — skew numbers are
immune to NTP-level clock skew by construction.

Every selection is **deterministic under ties** (equal durations pick
the lowest rank; equal skews order by ``(op, tag)``): the offline reader
sees events time-sorted, the online aggregator sees them in push-arrival
order, and the shared-math guarantee would be vacuous if dict insertion
order could change the verdict.
"""

from __future__ import annotations

import statistics
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

#: event kinds that count as faults for the overlap analysis
FAULT_KINDS = ("chaos", "deadline", "down", "retry")

#: event kinds whose spans are collective work (host + device planes)
COLLECTIVE_KINDS = ("collective", "device")

#: how far above the per-collective median a duration must sit to be
#: called a spike in the fault-overlap section
SPIKE_FACTOR = 3.0

#: how far BEFORE a spiking span's start a fault still counts as
#: overlapping: a peer that dies an instant before the survivors enter
#: the collective is the cause of their stall, not a coincidence
FAULT_SLACK_S = 1.0


def collective_groups(events: List[dict]) -> Dict[Tuple[str, str], Dict[int, float]]:
    """``{(op, tag): {rank: duration}}`` over collective/device spans;
    a rank reporting the same tag more than once keeps its max (chunked
    collectives re-enter per chunk — the slowest chunk IS the stall)."""
    groups: Dict[Tuple[str, str], Dict[int, float]] = defaultdict(dict)
    for e in events:
        if e["kind"] not in COLLECTIVE_KINDS or e["dur"] <= 0:
            continue
        attrs = e["attrs"]
        op = attrs.get("op") or e["name"]
        tag = attrs.get("tag") or e["name"]
        cur = groups[(op, tag)].get(e["rank"])
        if cur is None or e["dur"] > cur:
            groups[(op, tag)][e["rank"]] = e["dur"]
    return groups


def skew_rows(events: List[dict]) -> List[dict]:
    """Per-collective cross-rank skew, widest first.  Only tags seen on
    ≥2 ranks qualify (a single-rank duration has no skew to measure)."""
    rows = []
    for (op, tag), per_rank in collective_groups(events).items():
        if len(per_rank) < 2:
            continue
        # iterate ranks sorted so duration ties resolve to the LOWEST
        # rank on both sides, independent of event arrival order
        ranks = sorted(per_rank)
        slowest = max(ranks, key=per_rank.get)
        fastest = min(ranks, key=per_rank.get)
        rows.append({
            "op": op, "tag": tag,
            "slowest_rank": slowest, "slowest_s": per_rank[slowest],
            "fastest_rank": fastest, "fastest_s": per_rank[fastest],
            "skew_s": per_rank[slowest] - per_rank[fastest],
            "ranks": len(per_rank),
        })
    rows.sort(key=lambda r: (-r["skew_s"], r["op"], r["tag"]))
    return rows


def slowest_rank_per_step(events: List[dict]) -> List[dict]:
    """Per step window: the rank with the largest total collective time."""
    by_step: Dict[int, Dict[int, float]] = defaultdict(lambda: defaultdict(float))
    for e in events:
        if e["kind"] in COLLECTIVE_KINDS and e["dur"] > 0:
            by_step[e["step"]][e["rank"]] += e["dur"]
    out = []
    for step in sorted(by_step):
        per_rank = by_step[step]
        slowest = max(sorted(per_rank), key=per_rank.get)  # tie → lowest rank
        out.append({"step": step, "slowest_rank": slowest,
                    "total_s": per_rank[slowest],
                    "ranks": len(per_rank)})
    return out


def fault_overlaps(events: List[dict]) -> List[dict]:
    """Latency spikes (span > SPIKE_FACTOR x its group median, groups of
    ≥2) paired with the fault events that fall inside their window —
    any rank's fault counts: an injected delay on rank 1 stalls rank 0's
    recv just as surely as its own send."""
    faults = [e for e in events if e["kind"] in FAULT_KINDS]
    # the spike baseline is the median over ALL spans of an op (every
    # tag, every rank): a per-tag median would be the stall itself when
    # the majority of ranks block on one dead peer
    by_op: Dict[str, List[dict]] = defaultdict(list)
    for e in events:
        if e["kind"] in COLLECTIVE_KINDS and e["dur"] > 0:
            by_op[e["attrs"].get("op") or e["name"]].append(e)
    out = []
    for op, spans in by_op.items():
        if len(spans) < 2:
            continue
        med = statistics.median(s["dur"] for s in spans)
        if med <= 0:
            continue
        for s in spans:
            if s["dur"] < SPIKE_FACTOR * med:
                continue
            lo, hi = s["ts"] - FAULT_SLACK_S, s["ts"] + s["dur"]
            inside = [
                f for f in faults
                if lo <= f["ts"] <= hi
            ]
            if inside:
                out.append({
                    "op": op,
                    "tag": s["attrs"].get("tag") or s["name"],
                    "rank": s["rank"],
                    "step": s["step"], "dur_s": s["dur"],
                    "x_median": s["dur"] / med,
                    "faults": [
                        {"kind": f["kind"], "name": f["name"],
                         "rank": f["rank"], "attrs": f["attrs"]}
                        for f in inside
                    ],
                })
    out.sort(key=lambda r: r["dur_s"], reverse=True)
    return out


def straggler_verdict(events: List[dict]) -> Optional[int]:
    """The rank most often slowest across the skew groups, or None when
    no group spans ≥2 ranks."""
    votes: Dict[int, int] = defaultdict(int)
    for row in skew_rows(events):
        votes[row["slowest_rank"]] += 1
    if not votes:
        return None
    return max(sorted(votes), key=votes.get)  # vote tie → lowest rank
