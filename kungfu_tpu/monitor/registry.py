"""Unified metrics registry: counters, gauges, fixed-bucket histograms.

One process-global :data:`REGISTRY` that every subsystem publishes into —
the flight recorder (:mod:`kungfu_tpu.monitor.timeline`) counts drops and
fault events here, the collective engine's spans feed per-op latency
histograms, :class:`~kungfu_tpu.monitor.metrics.NetMonitor` mirrors its
byte totals, and :class:`~kungfu_tpu.monitor.metrics.MetricsServer`
renders everything through the existing ``/metrics`` endpoint.  Before
this module each subsystem kept private aggregates (``utils/trace.py``
(count, total) pairs, ``NetMonitor`` rate counters) that no one surface
could render together.

Deliberately dependency-free (stdlib only): ``utils/trace.py`` borrows
:class:`Histogram` for its percentile report and ``scripts/kftrace``
imports the package without jax.

Histograms use **fixed** bucket boundaries (seconds, latency-shaped by
default): observation is O(#buckets) worst case with no allocation, and
p50/p95/p99 are estimated by linear interpolation inside the bucket the
requested rank falls in — the standard Prometheus-style estimate, exact
at bucket edges, never off by more than one bucket width inside.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

#: default latency buckets (seconds): 100 µs .. 60 s, roughly log-spaced.
#: The top is open-ended (+Inf bucket) — a collective stuck behind a dead
#: peer lands there and the max tracks the true value.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Counter:
    """Monotonic counter."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with min/max/percentile summaries."""

    __slots__ = ("buckets", "_counts", "_lock", "count", "sum", "min", "max")

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        # one slot per finite bucket + the +Inf overflow slot
        self._counts = [0] * (len(self.buckets) + 1)
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def percentile(self, q: float) -> float:
        """Estimated ``q``-quantile (``0 < q <= 1``) from the buckets:
        linear interpolation inside the bucket holding the target rank;
        the open +Inf bucket reports the observed max (the only honest
        bound available there)."""
        with self._lock:
            total = self.count
            if total == 0:
                return 0.0
            target = q * total
            cum = 0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                prev_cum = cum
                cum += c
                if cum < target:
                    continue
                if i == len(self.buckets):  # +Inf bucket
                    return self.max
                lo = self.buckets[i - 1] if i > 0 else min(self.min, self.buckets[i])
                hi = self.buckets[i]
                frac = (target - prev_cum) / c
                est = lo + (hi - lo) * frac
                # the interpolation assumes mass spread across the whole
                # bucket; clamp to the observed range so a sparse bucket
                # cannot report a quantile outside [min, max]
                return min(max(est, self.min), self.max)
            return self.max

    def summary(self) -> Dict[str, float]:
        with self._lock:
            if self.count == 0:
                return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0}
            base = {"count": self.count, "sum": self.sum,
                    "min": self.min, "max": self.max}
        base["p50"] = self.percentile(0.50)
        base["p95"] = self.percentile(0.95)
        base["p99"] = self.percentile(0.99)
        return base

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs, Prometheus-style; the final
        entry is ``(inf, total)``."""
        with self._lock:
            out = []
            cum = 0
            for le, c in zip(self.buckets, self._counts):
                cum += c
                out.append((le, cum))
            out.append((float("inf"), cum + self._counts[-1]))
            return out


def _escape_label_value(v: str) -> str:
    """Prometheus exposition-format escaping: one odd label value (a
    quote or newline in a user-supplied op name) must not invalidate
    the whole scrape.  Well-formed values render byte-identically."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(str(labels[k]))}"'
                     for k in sorted(labels))
    return "{" + inner + "}"


#: ``# HELP`` text per metric family.  Stock Prometheus scrapers accept
#: samples without metadata, but exposition-format validators (and every
#: dashboard's tooltip) want the HELP/TYPE header — new metrics get a
#: generic line until someone writes a better one.
METRIC_HELP: Dict[str, str] = {
    "kf_collective_latency_seconds":
        "collective duration by plane (host engine / device) and op",
    "kf_engine_collectives_total":
        "engine collectives started (any op)",
    "kf_engine_retries_total":
        "engine send retries after transient wire faults",
    "kf_peer_faults_total":
        "per-peer deadline exhaustions raised as PeerFailureError",
    "kf_chaos_injections_total": "chaos faults injected, by clause kind",
    "kf_detector_down_total": "failure-detector down verdicts",
    "kf_shrink_events_total": "shrink-to-survivors phase events, by phase",
    "kf_strategy_swaps_total":
        "consensus-fenced strategy/schedule swaps (kf-adapt), by arm",
    "kf_host_pool_size":
        "host-plane responder/sender pool size (scaled with peer count)",
    "kf_slice_events_total":
        "slice-granular recovery phase events (multislice), by phase",
    "kf_timeline_dropped_total":
        "flight-recorder ring evictions (a nonzero value means the "
        "skew/xray windows are incomplete — kftop raises TRACE LOSS)",
    "kf_mfu":
        "model-FLOPs utilization: analytic model FLOP/s over the "
        "detected (or KF_XRAY_PEAK_FLOPS-pinned) chip peak (kf-xray)",
    "kf_model_flops_s":
        "analytic model FLOP/s actually sustained (EMA; the MFU "
        "numerator — reported alone on CPU meshes with no honest peak)",
    "kf_step_phase_seconds":
        "per-step wall decomposition by kf-xray phase (compute / "
        "comm_exposed / comm_hidden / input_stall / straggler_wait)",
    "kf_opt_state_bytes":
        "per-rank optimizer-state footprint (worst device; ZeRO shards "
        "count one chunk, replicated state counts fully)",
    "kf_overlap_inflight":
        "async collective handles issued and not yet complete "
        "(kf-overlap in-flight window; 0 = fully drained)",
    "kf_overlap_efficiency":
        "per-handle hidden-wire fraction observed at wait(): 1.0 = the "
        "collective finished before the caller needed it (fully hidden), "
        "0.0 = the caller blocked for the whole wire time",
    "kf_kv_cache_bytes":
        "per-rank paged KV-cache footprint (allocated pages x page "
        "bytes; the serving analog of kf_opt_state_bytes)",
    "kf_serve_requests_total":
        "serving request lifecycle events (kf-serve router), by outcome "
        "(accept / reject / complete / replay / lost)",
    "kf_serve_prefill_tokens_total":
        "prefill tokens by source: computed ran the forward, reused "
        "came from the paged KV cache's prefix chain",
    "kf_serve_ttft_seconds":
        "time to first token (admission to first decode), worker-side",
    "kf_serve_token_seconds":
        "decode-step latency per generated token, worker-side",
    "kf_serve_e2e_seconds":
        "end-to-end request latency (submit to completion incl. "
        "routing, queueing, and any post-failure replay), router-side",
    "kf_serve_queue_depth":
        "router accepted-but-unfinished requests (admission bound: "
        "KF_SERVE_QUEUE_DEPTH)",
    "kf_serve_active_requests":
        "decode slots occupied on this engine (continuous batching)",
    "kf_ckpt_last_step":
        "newest step this rank's persist plane made durable "
        "(kf-persist; -1-ish float 0.0 before the first write)",
    "kf_ckpt_age_seconds":
        "seconds since this rank's last durable manifest write — grows "
        "while the writer is wedged; kftop raises CKPT STALE past 3 "
        "persist periods",
    "kf_ckpt_bytes_total":
        "cumulative bytes this rank streamed into durable manifests "
        "(gauge-typed: the plane owns the accumulation)",
    "kf_ckpt_period_seconds":
        "configured persist period (KF_PERSIST_PERIOD; 0 = persist at "
        "every commit) — the denominator of the CKPT STALE alarm",
    "kf_net_egress_bytes":
        "aggregate egress bytes (mirrored from NetMonitor)",
    "kf_net_ingress_bytes":
        "aggregate ingress bytes (mirrored from NetMonitor)",
    "kf_cluster_control_events_total":
        "control events (shrink/resize/...) received by the aggregator",
    "kf_alerts_total":
        "kf-sentinel rule firings by rule name (changepoint regressions, "
        "SLO burn rates, watermarks); each firing cuts an incident "
        "flight record under KF_SENTINEL_DIR",
    "kf_jit_compiles_total":
        "XLA compilations observed through the jax monitoring hook — a "
        "nonzero steady-state rate means a shape/dtype is retriggering "
        "jit (the dynamic twin of the static recompile-hazard rule)",
    "kf_jit_compile_seconds":
        "wall seconds per observed XLA compilation (jax monitoring "
        "hook; absent on jax versions without it)",
    "kf_device_memory_bytes":
        "accelerator memory by kind (in_use / limit) from "
        "device.memory_stats(); absent on backends without stats (CPU)",
    "kf_gns":
        "EMA-smoothed gradient-noise-scale estimate (OpenAI GNS; "
        "kf-pulse) — piggybacks on already-reduced gradient buckets, "
        "sampled every KF_PULSE_EVERY steps; absent on a single worker "
        "where the two-batch estimator is undefined",
    "kf_grad_variance":
        "EMA-smoothed cross-peer gradient variance E_i|g_i - g_avg|^2 "
        "from the same reduced buckets as kf_gns (kf-pulse)",
    "kf_grad_norm":
        "per-parameter-group gradient L2 norm, group= label keyed by "
        "the sharding kind (kf-pulse)",
    "kf_decisions_total":
        "adaptive-control decisions recorded in the kf-ledger, by "
        "actor= label — each one carries a durable (knob, old, new, "
        "evidence) record joined to its measured effect",
}


class MetricsRegistry:
    """Name+labels → metric instance, with one Prometheus rendering."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}

    def _get(self, cls, name: str, labels: Dict[str, str], **kwargs):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(**kwargs)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, **labels: str) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels: str) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Optional[Tuple[float, ...]] = None,
                  **labels: str) -> Histogram:
        return self._get(Histogram, name, labels,
                         buckets=buckets or DEFAULT_LATENCY_BUCKETS)

    def snapshot(self) -> Dict[str, object]:
        """``{rendered-name: value-or-summary}`` for tests/tools."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, object] = {}
        for (name, labels), m in items:
            key = name + _label_str(dict(labels))
            if isinstance(m, Histogram):
                out[key] = m.summary()
            else:
                out[key] = m.value
        return out

    def render_prometheus(self) -> str:
        """Prometheus exposition text: per metric *family* one ``# HELP``
        + ``# TYPE`` header (label variants sort together, so the header
        lands once), then the samples — whose names and label encoding
        are byte-identical to the pre-HELP/TYPE rendering, so existing
        scrape configs and dashboards keep matching."""
        with self._lock:
            items = sorted(self._metrics.items(), key=lambda kv: kv[0])
        lines: List[str] = []
        last_family = None
        for (name, labels), m in items:
            ld = dict(labels)
            if name != last_family:
                kind = ("counter" if isinstance(m, Counter)
                        else "gauge" if isinstance(m, Gauge)
                        else "histogram")
                lines.append(f"# HELP {name} "
                             f"{METRIC_HELP.get(name, 'kungfu-tpu metric')}")
                lines.append(f"# TYPE {name} {kind}")
                last_family = name
            if isinstance(m, Counter):
                lines.append(f"{name}{_label_str(ld)} {m.value}")
            elif isinstance(m, Gauge):
                lines.append(f"{name}{_label_str(ld)} {m.value:.6g}")
            else:  # Histogram: the _bucket/_sum/_count encoding
                for le, cum in m.bucket_counts():
                    le_s = "+Inf" if le == float("inf") else f"{le:g}"
                    bl = dict(ld, le=le_s)
                    lines.append(f"{name}_bucket{_label_str(bl)} {cum}")
                lines.append(f"{name}_sum{_label_str(ld)} {m.sum:.6g}")
                lines.append(f"{name}_count{_label_str(ld)} {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric (tests; a process-global registry otherwise
        accumulates across unrelated scenarios)."""
        with self._lock:
            self._metrics.clear()


#: the process-global registry rendered by ``/metrics``
REGISTRY = MetricsRegistry()
