"""Flight recorder: bounded in-process ring of structured events.

``utils/trace.py`` answers "how much, on average"; this module answers
"*which rank* stalled *which collective* at *which step*, and was a
chaos fault or a shrink in flight at the time".  Every event is
``(ts, rank, step, kind, name, dur, attrs)``:

* ``ts`` — wall-clock start time (``time.time()``, so cross-rank merges
  align without a clock-sync protocol; NTP-level skew is visible but the
  per-collective *skew analysis* in ``kftrace`` compares durations, which
  are immune to it);
* ``rank`` — the emitting rank (``None`` for rank-less subsystems like
  the detector; the module-level default set by :func:`set_rank` fills
  in when the call site passes nothing);
* ``step`` — the current training step (:func:`set_step`), ``-1`` before
  the first step;
* ``kind`` — one of :data:`EVENT_KINDS` (enforced by the ``trace-vocab``
  kflint rule: a typo'd kind would silently vanish from every ``kftrace``
  filter);
* ``dur`` — seconds for :func:`span` regions, ``0`` for one-shot
  :func:`event` marks.

Cost contract: gated by the same ``KF_CONFIG_ENABLE_TRACE`` switch as
``trace_scope``.  Disabled, :func:`span` returns a shared no-op context
manager (zero allocation) and :func:`event` returns after one env check
— except for the rare *counted* kinds (retry/deadline/chaos/down/
shrink), whose registry counters tick regardless so ``/metrics`` stays
truthful without paying for the ring on the hot path.

Dump: one JSONL file per process (= per rank under the runner) written
by :func:`maybe_dump` (``Peer.close``) and an ``atexit`` hook when
``KF_CONFIG_TRACE_DUMP`` names a directory (or a ``*.jsonl`` file).
``scripts/kftrace`` merges N ranks' dumps into one Chrome-trace JSON and
prints the straggler report (:mod:`kungfu_tpu.monitor.traceview`).
"""

from __future__ import annotations

import atexit
import collections
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.utils.log import get_logger
from kungfu_tpu.utils.trace import record_duration, trace_enabled

_log = get_logger("timeline")

#: JSONL dump location: a directory (one ``trace-*.jsonl`` per process)
#: or an exact ``*.jsonl`` path (single-process runs)
DUMP_ENV = "KF_CONFIG_TRACE_DUMP"
#: ring capacity override (events); default 65536
CAP_ENV = "KF_CONFIG_TIMELINE_CAP"

DEFAULT_CAP = 65536

#: the event vocabulary.  The ``trace-vocab`` kflint rule rejects any
#: ``span()``/``event()`` call site whose kind is not listed here — add
#: the kind FIRST, then the instrumentation.
EVENT_KINDS = frozenset({
    "collective",  # host-engine collective span (comm/engine.py)
    "device",      # device-plane collective span (comm/device.py)
    "send",        # host-channel frame egress mark, byte-counted
    "recv",        # host-channel frame ingress mark, byte-counted
    "retry",       # engine send retry after a transient wire fault
    "deadline",    # per-peer deadline exhausted -> PeerFailureError
    "signal",      # detector heartbeat intake (begin/end/epoch/...)
    "down",        # detector down verdict / local down report
    "shrink",      # shrink-to-survivors phase boundary
    "slice",       # slice-granular recovery phase (elastic/shrink.py:
                   # verdict / self-excluded / leader-consensus /
                   # propose / quorum-lost at the multislice grain)
    "chaos",       # fault injection fired (chaos/inject.py)
    "swap",        # consensus-fenced strategy/schedule swap (kf-adapt:
                   # monitor/adapt_device.py — host arm or device
                   # per-bucket schedule installed in lockstep)
    "overlap",     # async collective handle lifecycle (kf-overlap,
                   # comm/engine.py: "issue" / "complete" marks carrying
                   # tag, nbytes, and the in-flight queue depth).  A hot
                   # kind: recorded only when tracing is on — the
                   # always-on surfaces are the kf_overlap_inflight
                   # gauge and the kf_overlap_efficiency histogram.
                   # Since kf-xray, recorded marks also ride the monitor
                   # pushes (aggregator.REPORT_KINDS ⊇ xray.XRAY_KINDS:
                   # the online attribution needs the async-tag set)
    "serve",       # serving-plane engine/router lifecycle (kf-serve,
                   # serve/engine.py + serve/router.py: prefill/decode
                   # spans — hot, ring-only — plus the rare worker-dead/
                   # slice-dead/readmit marks of the serving fault
                   # ladder)
    "pp",          # pipeline-parallel lifecycle (kf-pipeline,
                   # parallel/pp.py): "fwd"/"bwd" stage-compute spans
                   # and the "bubble" span — the time a stage blocks on
                   # a cross-DCN activation/gradient hop — plus the
                   # rare "buddy-replicate"/"stage-recarve" marks of
                   # the elastic stage re-carve.  A hot kind, recorded
                   # only when tracing is on; recorded spans ride the
                   # monitor pushes (REPORT_KINDS) so kf-xray's online
                   # step decomposition attributes bubble time as its
                   # own phase (monitor/xray.py::PHASES pp_bubble)
    "input",       # input-pipeline wait span (kf-xray: the consumer-side
                   # block for the next batch — datasets/prefetch.py and
                   # any loader that wants its stall attributed.  A hot
                   # kind, one span per consumed batch, recorded only
                   # when tracing is on; recorded spans also ride the
                   # monitor pushes (REPORT_KINDS) so the online
                   # input_stall attribution sees them)
    "xray",        # kf-xray attribution mark (monitor/xray.py /
                   # ops/costmodel.py: the rank-local per-step phase
                   # split and MFU sample, so a dump carries the same
                   # decomposition the live gauges export)
    "request",     # serving request lifecycle mark (kf-serve router:
                   # "accept" / "reject" / "complete" / "replay" /
                   # "lost").  A counted kind: every mark ticks
                   # kf_serve_requests_total{what=<name>} even with
                   # tracing off, like the chaos/shrink counters
    "ckpt",        # durable persist plane (kf-persist,
                   # elastic/persist.py): "persist-issue" /
                   # "persist-done" marks around each async manifest
                   # write and the "restore" mark of a cold restart —
                   # rare boundary events, so always recordable; the
                   # always-on surfaces are the kf_ckpt_* gauges
    "alert",       # kf-sentinel rule firing (monitor/sentinel.py): a
                   # detector/burn-rate/watermark rule crossed its
                   # threshold and an incident flight record was cut.
                   # A counted kind labeled by RULE name: every firing
                   # ticks kf_alerts_total{rule=...} even with tracing
                   # off — an alert that /metrics cannot count did not
                   # happen
    "decision",    # adaptive-actor knob change (kf-ledger,
                   # monitor/ledger.py: a bandit swap, a batch-width
                   # move, an autoscale resize, a shrink — any actor
                   # writing a durable decision record).  A counted
                   # kind labeled by ACTOR name: every decision ticks
                   # kf_decisions_total{actor=...} even with tracing
                   # off — a knob change /metrics cannot count did not
                   # happen
    "pulse",       # gradient-signal sample mark (kf-pulse,
                   # monitor/pulse.py: the GNS/variance pair computed
                   # every KF_PULSE_EVERY steps).  A hot-ish kind,
                   # recorded only when tracing is on — the always-on
                   # surfaces are the kf_gns / kf_grad_variance /
                   # kf_grad_norm gauges
    "step",        # training-step mark
    "mark",        # generic one-shot annotation
})

#: kinds whose registry counters tick even with tracing off — rare
#: events that /metrics must count unconditionally.  Values are the
#: counter names; chaos/shrink additionally label by the event name
#: (a closed set: clause kinds / phase names).
_COUNTED_KINDS = {
    "retry": "kf_engine_retries_total",
    "deadline": "kf_peer_faults_total",
    "chaos": "kf_chaos_injections_total",
    "down": "kf_detector_down_total",
    "shrink": "kf_shrink_events_total",
    "slice": "kf_slice_events_total",
    "swap": "kf_strategy_swaps_total",
    "request": "kf_serve_requests_total",
    "alert": "kf_alerts_total",
    "decision": "kf_decisions_total",
}
_LABELED_KINDS = ("chaos", "shrink", "slice", "swap", "request", "alert",
                  "decision")
#: label KEY per labeled kind; default "what".  Alerts label by "rule"
#: so the counter reads kf_alerts_total{rule="regress:step_time_s"} —
#: the name SLO dashboards group by; decisions label by ACTOR the same
#: way (kf_decisions_total{actor="bandit-host"}).
_LABEL_KEYS = {"alert": "rule", "decision": "actor"}

_lock = threading.Lock()
_ring: collections.deque = collections.deque()
_cap: Optional[int] = None  # resolved lazily from CAP_ENV
_dropped = 0
_rank: Optional[int] = None
_step = -1

# -- causal context (kf-xray) ----------------------------------------------
# Every recorded span carries a ``(trace, span, parent)`` triple in its
# attrs: ``span`` is a process-unique id allocated at entry, ``trace``
# groups spans of one logical operation ACROSS ranks/processes, and
# ``parent`` is the enclosing span (same trace) when one exists.  Two
# propagation paths, chosen so the hot path ships no extra wire bytes:
#
# * **derived** — collective spans compute the SAME trace id on every
#   rank from values all ranks already agree on
#   (:func:`collective_trace_id` over (cluster_version, step, op, tag)),
#   so the cross-rank link costs zero wire bytes;
# * **explicit** — request/response flows (serve frames, p2p blob pulls)
#   carry a compact ``tc`` string in their existing JSON meta body; the
#   receiving side re-enters it via :func:`trace_ctx` so its spans and
#   events join the requester's trace.
#
# Ambient context is a per-thread stack: entering a span (or a
# :func:`trace_ctx`) pushes ``(trace, span_id)``; events and child spans
# recorded inside inherit it unless their call site passes explicit
# ``trace=``/``parent=`` attrs.
_span_seq = itertools.count(1)
_tls = threading.local()


def new_span_id() -> str:
    """Process-unique span id (``s<rank>.<n>``); deterministic given the
    event order, so replayed tests produce stable ids."""
    r = _rank if _rank is not None else "x"
    return f"s{r}.{next(_span_seq)}"


def collective_trace_id(version, step, op: str, tag: str) -> str:
    """Deterministic cross-rank trace id for one logical collective:
    every participating rank derives the identical id from values it
    already holds — the cluster version (mesh epoch), the current step,
    and the collective's op/tag — so the same collective links across
    ranks in a merged trace with NO extra wire bytes."""
    return f"c{version}.{step}.{op}.{tag}"


def _ctx_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_trace() -> Tuple[Optional[str], Optional[str]]:
    """``(trace_id, span_id)`` of the innermost ambient context on this
    thread, or ``(None, None)``."""
    st = _ctx_stack()
    return st[-1] if st else (None, None)


class trace_ctx:
    """Re-enter a received trace context: spans/events recorded inside
    join ``trace`` as children of ``parent`` (e.g. the serving worker
    handling a router frame whose meta carried ``tc``)."""

    __slots__ = ("trace", "parent")

    def __init__(self, trace: Optional[str], parent: Optional[str] = None):
        self.trace = trace
        self.parent = parent

    def __enter__(self):
        _ctx_stack().append((self.trace, self.parent))
        return self

    def __exit__(self, *exc):
        _ctx_stack().pop()
        return False


def parse_trace_context(tc) -> Tuple[Optional[str], Optional[str]]:
    """``(trace, parent)`` from the compact wire form ``"trace"`` or
    ``"trace@parent"`` (the ``tc`` meta field of serve/p2p frames);
    ``(None, None)`` on anything malformed — a bad peer must not break
    the receiver's recording."""
    if not isinstance(tc, str) or not tc:
        return None, None
    trace, sep, parent = tc.partition("@")
    if not trace:
        # "@x" and friends: an empty trace id would group unrelated
        # requests under one bogus "" trace — unlinked beats mislinked
        return None, None
    return trace, (parent or None) if sep else None


def format_trace_context(trace: Optional[str],
                         parent: Optional[str] = None) -> Optional[str]:
    """The compact wire form consumed by :func:`parse_trace_context`."""
    if not trace:
        return None
    return f"{trace}@{parent}" if parent else trace


def context_attrs(trace: Optional[str],
                  parent: Optional[str] = None) -> Dict[str, str]:
    """Span/event attrs for an explicitly-propagated context: empty when
    there is no (or an empty) trace, and never a literal ``None`` parent
    — the dump schema stays uniform with the ambient-merge paths, which
    omit absent keys entirely."""
    if not trace:
        return {}
    attrs = {"trace": trace}
    if parent is not None:
        attrs["parent"] = parent
    return attrs


def enabled() -> bool:
    """Same gate as ``trace_scope`` (``KF_CONFIG_ENABLE_TRACE``)."""
    return trace_enabled()


def set_rank(rank: Optional[int]) -> None:
    """Default rank stamped on events whose call site passes none.
    (In-process multi-rank test clusters pass ``rank=`` explicitly at
    the rank-owning call sites; this default serves real one-rank-per-
    process workers and the dump filename.)"""
    global _rank
    _rank = rank


def set_step(step: int) -> None:
    """Current training step, stamped on subsequent events."""
    global _step
    _step = step


def current_step() -> int:
    """The step last stamped by :func:`set_step` (``-1`` before the
    first) — the live plane's reporter reads it for its snapshot."""
    return _step


def current_rank() -> Optional[int]:
    """The process-default rank installed by :func:`set_rank`."""
    return _rank


def _capacity() -> int:
    global _cap
    if _cap is None:
        try:
            _cap = max(1, int(os.environ.get(CAP_ENV, "") or DEFAULT_CAP))
        except ValueError:
            _cap = DEFAULT_CAP
    return _cap


def _append(ts: float, rank: Optional[int], kind: str, name: str,
            dur: float, attrs: Optional[Dict]) -> None:
    global _dropped
    ev = (ts, rank if rank is not None else _rank, _step, kind, name, dur,
          attrs or None)
    cap = _capacity()
    with _lock:
        if len(_ring) >= cap:
            # flight-recorder semantics: keep the newest, evict the
            # oldest, and count the loss so a truncated dump says so
            _ring.popleft()
            _dropped += 1
            REGISTRY.counter("kf_timeline_dropped_total").inc()
        _ring.append(ev)


def _count(kind: str, name: str) -> None:
    metric = _COUNTED_KINDS.get(kind)
    if metric is None:
        return
    if kind in _LABELED_KINDS:
        REGISTRY.counter(metric,
                         **{_LABEL_KEYS.get(kind, "what"): name}).inc()
    else:
        REGISTRY.counter(metric).inc()


def event(kind: str, name: str, rank: Optional[int] = None,
          force: bool = False, **attrs) -> None:
    """One-shot mark.  Counted kinds always tick their registry counter;
    the ring records only when tracing is enabled (or ``force``).  An
    ambient :func:`trace_ctx` (or enclosing span) stamps the mark's
    ``trace``/``parent`` unless the call site passed its own."""
    _count(kind, name)
    if not (force or trace_enabled()):
        return
    if "trace" not in attrs:
        tr, parent = current_trace()
        if tr is not None:
            attrs["trace"] = tr
            if parent is not None and "parent" not in attrs:
                attrs["parent"] = parent
    _append(time.time(), rank, kind, name, 0.0, attrs)


class _NoopSpan:
    """Shared disabled-path span: no allocation, no timing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("kind", "name", "rank", "attrs", "_t0", "_ts",
                 "span_id", "_trace", "_parent")

    def __init__(self, kind, name, rank, attrs):
        self.kind = kind
        self.name = name
        self.rank = rank
        self.attrs = attrs

    def __enter__(self):
        # causal triple: explicit trace= attr wins; else inherit the
        # thread's ambient context.  The span then BECOMES the ambient
        # parent for everything recorded inside it.
        attrs = self.attrs
        trace = (attrs or {}).get("trace")
        parent = (attrs or {}).get("parent")
        if trace is None:
            trace, ambient_parent = current_trace()
            if parent is None:
                parent = ambient_parent
        self.span_id = new_span_id()
        self._trace, self._parent = trace, parent
        _ctx_stack().append((trace, self.span_id))
        self._ts = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        dt = time.perf_counter() - self._t0
        _ctx_stack().pop()
        attrs = dict(self.attrs or {})
        if et is not None:
            attrs["error"] = et.__name__
        attrs["span"] = self.span_id
        if self._trace is not None:
            attrs["trace"] = self._trace
        if self._parent is not None:
            attrs["parent"] = self._parent
        _append(self._ts, self.rank, self.kind, self.name, dt, attrs)
        # aggregate parity: spans ARE trace scopes — trace_report() and
        # its histogram percentiles see every span duration, and the live
        # per-scope log line trace_scope users rely on keeps appearing
        record_duration(self.name, dt)
        _log.info("%s took %.3fms", self.name, dt * 1e3)
        if self.kind in ("collective", "device"):
            op = (attrs or {}).get("op") if attrs else None
            REGISTRY.histogram(
                "kf_collective_latency_seconds",
                plane=self.kind, op=op or self.name,
            ).observe(dt)
        return False


def span(kind: str, name: str, rank: Optional[int] = None,
         force: bool = False, **attrs):
    """Timed region: records one event with ``dur`` set, feeds the trace
    aggregates, and (for collective/device kinds) the per-op latency
    histogram.  Returns a shared no-op when tracing is off."""
    if not (force or trace_enabled()):
        return _NOOP_SPAN
    return _Span(kind, name, rank, attrs or None)


def dropped() -> int:
    with _lock:
        return _dropped


def snapshot() -> List[Dict]:
    """Current ring contents as dicts, oldest first."""
    with _lock:
        evs = list(_ring)
    return [
        {"ts": ts, "rank": r, "step": s, "kind": k, "name": n, "dur": d,
         "attrs": a or {}}
        for ts, r, s, k, n, d, a in evs
    ]


def events_tail(since: int, kinds: Optional[frozenset] = None
                ) -> Tuple[int, List[Dict]]:
    """``(cursor, events)``: every event appended after the ``since``
    cursor (0 = beginning of time), optionally kind-filtered, oldest
    first.  The cursor is the cumulative append count (evicted + live),
    so the cluster reporter's incremental read costs O(new events) per
    push and never re-sends or misses one — a timestamp filter would
    miss long spans, which are appended at exit carrying their *start*
    time.  Events evicted before the caller returned are simply gone
    (flight-recorder semantics; the drop counter says how many)."""
    with _lock:
        total = _dropped + len(_ring)
        start = max(0, since - _dropped)
        evs = list(_ring)[start:] if start < len(_ring) else []
    if kinds is not None:
        evs = [e for e in evs if e[3] in kinds]
    return total, [
        {"ts": ts, "rank": r, "step": s, "kind": k, "name": n, "dur": d,
         "attrs": a or {}}
        for ts, r, s, k, n, d, a in evs
    ]


def reset(cap: Optional[int] = None) -> None:
    """Clear the ring — tests and long-lived processes re-arming a
    capture.  ``cap`` pins a capacity; without it the next append
    re-resolves ``KF_CONFIG_TIMELINE_CAP``."""
    global _dropped, _cap, _step, _span_seq
    with _lock:
        _ring.clear()
        _dropped = 0
        _cap = max(1, cap) if cap is not None else None
        _step = -1
        _span_seq = itertools.count(1)  # stable span ids per capture


def dump_path_from_env() -> Optional[str]:
    """Resolve ``KF_CONFIG_TRACE_DUMP`` to this process's dump file, or
    None when dumping is not configured."""
    target = os.environ.get(DUMP_ENV, "").strip()
    if not target:
        return None
    if target.endswith(".jsonl"):
        return target
    r = _rank if _rank is not None else "x"
    return os.path.join(target, f"trace-r{r}-p{os.getpid()}.jsonl")


def dump(path: str) -> int:
    """Write the ring as JSONL (header line first); returns the event
    count written."""
    events = snapshot()
    header = {
        "kftrace": 1,
        "rank": _rank,
        "pid": os.getpid(),
        "dropped": dropped(),
        "wall": time.time(),
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps(header) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return len(events)


def maybe_dump() -> Optional[str]:
    """Dump to the env-configured path if set and the ring is non-empty;
    returns the path written (idempotent: later calls overwrite with a
    superset, so close + atexit double-firing is harmless)."""
    path = dump_path_from_env()
    if path is None:
        return None
    with _lock:
        if not _ring:
            return None
    try:
        n = dump(path)
    except OSError as e:
        _log.warning("cannot dump timeline to %s: %s", path, e)
        return None
    _log.info("%d event(s) dumped to %s", n, path)
    return path


atexit.register(maybe_dump)
