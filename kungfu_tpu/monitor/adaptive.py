"""Closed-loop strategy adaptation.

The reference monitors the active strategy's throughput against a
reference window and, when a cluster-wide majority sees a drop below 0.8x
(network interference), swaps every peer to an alternative strategy
(``session/adaptiveStrategies.go:57-121``) or installs the latency-MST
tree (``tensorflow/ops/cpu/adaptation.cpp`` + ``mst.hpp``).  Round 1
shipped the primitives (per-strategy windows, interference vote, MST,
``set_tree``) but no driver that actually performs the swap mid-training
— this module closes the loop.

Usage (training loop, every rank)::

    driver = AdaptiveStrategyDriver(peer, check_every=32)
    for step in range(steps):
        grads = engine.all_reduce(grads, op="mean")
        driver.step()          # may consensus-swap the strategy

The swap is fenced exactly like the reference's ``SetGlobalStrategy``
(``session/adaptation.go:8-28``): all ranks reach the SAME decision from
the majority vote (the vote result is itself an allreduce, so it is
identical everywhere), agree on the proposed strategy via a consensus
digest, barrier, then swap engines in lockstep.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from kungfu_tpu.monitor.adapt import (
    INTERFERENCE_THRESHOLD,
    check_interference,
    majority_vote_interference,
    minimum_spanning_tree_from_latencies,
    set_tree,
)
from kungfu_tpu.plan.strategy import Strategy
from kungfu_tpu.utils.log import get_logger

_log = get_logger("adaptive")

#: default swap rotation — mirrors the reference's single fixed
#: "alternativeStrategy"; a rotation keeps swapping meaningful when
#: interference persists across several strategies
DEFAULT_ALTERNATIVES = (
    Strategy.BINARY_TREE_STAR,
    Strategy.MULTI_BINARY_TREE_STAR,
    Strategy.RING,
    Strategy.STAR,
)


class AdaptiveStrategyDriver:
    """Per-rank driver; every rank must construct one with the SAME
    arguments and call :meth:`step` at the same points in the training
    loop (the decisions are collective)."""

    def __init__(
        self,
        peer,
        check_every: int = 32,
        alternatives: Sequence[Strategy] = DEFAULT_ALTERNATIVES,
        threshold: float = INTERFERENCE_THRESHOLD,
        use_mst: bool = False,
        min_steps_between_swaps: int = 2,
        consecutive_drops: int = 2,
    ):
        self.peer = peer
        self.check_every = max(1, check_every)
        self.alternatives = list(alternatives)
        self.threshold = threshold
        self.use_mst = use_mst
        self.min_checks_between_swaps = max(1, min_steps_between_swaps)
        #: windows below threshold required back-to-back before this rank
        #: votes "interference" — one noisy window (GC pause, CI box
        #: contention) must not trigger a cluster-wide topology swap
        self.consecutive_drops = max(1, consecutive_drops)
        self._drops = 0
        self._step = 0
        self._checks_since_swap = self.min_checks_between_swaps
        self._alt_idx = 0  # rotation cursor over `alternatives`
        self.swaps = 0  # observability: number of performed swaps

    # -- loop hook --------------------------------------------------------
    def step(self) -> bool:
        """Call once per training step; returns True when a strategy swap
        happened (collectively, on every rank)."""
        self._step += 1
        if self._step % self.check_every:
            return False
        engine = self.peer.engine()
        if engine is None:
            return False
        dropped = bool(
            check_interference(engine, threshold=self.threshold)
        )
        self._drops = self._drops + 1 if dropped else 0
        suspected = self._drops >= self.consecutive_drops
        # the vote is an allreduce: every rank computes the same verdict
        agreed = majority_vote_interference(self.peer, suspected)
        self._checks_since_swap += 1
        if not agreed:
            return False
        if self._checks_since_swap < self.min_checks_between_swaps:
            # hysteresis: a fresh strategy needs a window to establish its
            # own best before it can be judged (prevents swap thrash)
            return False
        if not self._swap(engine):
            # agreed interference but nothing to swap to (e.g. the only
            # alternative is already installed): report no swap, keep the
            # suspicion state — callers must not see phantom swaps
            return False
        self._checks_since_swap = 0
        self._drops = 0
        self.swaps += 1
        return True

    # -- the fenced swap --------------------------------------------------
    def _next_strategy(self, engine) -> Optional[Strategy]:
        """True rotation: advance a cursor through ``alternatives`` so
        persistent interference eventually tries every one (a first-match
        scan would ping-pong between the first two forever)."""
        cur = engine.strategy
        n = len(self.alternatives)
        for _ in range(n):
            s = self.alternatives[self._alt_idx % n]
            self._alt_idx += 1
            if s != cur:
                return s
        return None

    def _swap(self, engine) -> bool:
        """Returns whether a topology/strategy change was installed."""
        if self.use_mst:
            # min-of-3 pings per edge: one sample is corruptible by a
            # scheduler spike on a loaded box (observed: a 30 ms-throttled
            # edge beaten by a GIL stall on a fast edge, MST kept the slow
            # link); min() filters spikes but keeps any real injected floor
            forest = minimum_spanning_tree_from_latencies(self.peer, samples=3)
            # latency matrix is allgathered -> identical on all ranks ->
            # identical MST; peer.set_tree does consensus + barrier fencing
            self.peer.set_tree(forest)
            _log.info("interference: installed latency-MST tree %s", forest)
            return True
        target = self._next_strategy(engine)
        if target is None:
            _log.warning("interference agreed but no alternative strategy")
            return False
        # fencing (reference adaptation.go:8-28): consensus on the proposed
        # strategy, barrier, swap
        digest = f"strategy:{target.name}".encode()
        if not self.peer.consensus_bytes(digest, name="adapt-swap"):
            raise RuntimeError(
                f"peers disagree on the strategy swap target {target.name}"
            )
        self.peer.barrier()
        engine.set_strategy(target)
        _log.info("interference: swapped strategy to %s", target.name)
        return True


def monitored_all_reduce(engine, x: np.ndarray, driver: AdaptiveStrategyDriver,
                         op: str = "sum", name: str = "") -> np.ndarray:
    """Allreduce + adaptation step in one call (the reference's
    ``MonitoredAllReduce`` op shape, ``collective.go:16-157``)."""
    out = engine.all_reduce(x, op=op, name=name)
    driver.step()
    return out


class DeviceStrategyDriver:
    """Step-time-driven re-tuning for the DEVICE plane — the adaptation
    loop for compiled allreduce schedules (:mod:`kungfu_tpu.ops.schedules`).

    The host-plane :class:`AdaptiveStrategyDriver` watches per-strategy
    engine throughput; on the device plane the collective is fused into
    one compiled program, so the observable is the STEP TIME.  The caller
    feeds measured step seconds; when the window MEDIAN (robust to an
    aligned periodic outlier like a checkpoint save inside every window)
    regresses past ``regression``× the established EMA baseline for
    ``consecutive`` checks — as agreed by a cluster-wide MAJORITY VOTE,
    exactly like the host driver's interference vote: a locally-decided
    collective autotune would deadlock controllers whose local clocks
    disagree at the margin — the driver re-runs
    :meth:`Communicator.autotune_strategy` and reports True so the
    caller re-jits its step with ``schedule=comm.strategy``.  Hysteresis
    comes from the post-swap warm-up: the first window after a re-jit
    holds the compile and is discarded, and the next seeds a fresh
    baseline, so a new schedule always gets a clean evaluation window
    before it can be judged.

    Every controller must call :meth:`observe` every step (the vote is a
    collective); single-controller meshes vote trivially.

    Typical loop::

        driver = DeviceStrategyDriver(comm)
        step = make_step(comm.strategy)
        for batch in data:
            t0 = time.perf_counter(); ...step...; dt = time.perf_counter()-t0
            if driver.observe(dt):
                step = make_step(comm.strategy)   # re-jit on swap
    """

    def __init__(self, comm, check_every: int = 64, regression: float = 1.3,
                 consecutive: int = 2, ema: float = 0.1,
                 autotune_nbytes: int = 4 << 20):
        self.comm = comm
        self.check_every = max(1, check_every)
        self.regression = regression
        self.consecutive = max(1, consecutive)
        self.ema = ema
        self.autotune_nbytes = autotune_nbytes
        self._baseline = None  # EMA of healthy window medians
        self._warmed = False  # first window holds the compile; discard it
        self._window = []
        self._step = 0
        self._drops = 0
        self.swaps = 0

    def _vote(self, suspected: bool) -> bool:
        """Cluster-wide majority on this window's verdict — every
        controller must reach the same swap decision or their compiled
        programs diverge (the host driver's
        ``majority_vote_interference`` analog, on the device plane)."""
        import jax.numpy as jnp

        votes = jnp.full((self.comm.addressable_n, 1),
                         1.0 if suspected else 0.0, jnp.float32)
        total = float(np.asarray(self.comm.all_reduce(votes)).ravel()[0])
        return total * 2 > self.comm.size

    def observe(self, step_seconds: float) -> bool:
        """Feed one measured step time; returns True when the schedule
        was re-tuned (re-jit your step)."""
        self._window.append(step_seconds)
        self._step += 1
        if self._step % self.check_every:
            return False
        med = sorted(self._window)[len(self._window) // 2]
        self._window = []
        if not self._warmed:
            # the first window after (re-)jit contains the XLA compile —
            # seeding the baseline from it would mask every later
            # regression (a compile-sized baseline dwarfs real slowdowns)
            self._warmed = True
            self._vote(False)  # stay collective: every check votes
            return False
        if self._baseline is None:
            self._baseline = med
            self._vote(False)
            return False
        regressed = med > self.regression * self._baseline
        # the vote runs on EVERY check (it is a collective — skipping it
        # on healthy controllers would desynchronize the mesh)
        agreed = self._vote(regressed)
        if not agreed:
            if not regressed:
                # healthy window: fold into the baseline so slow drift
                # (bigger model via growth, colder machine) is tracked
                self._baseline = ((1 - self.ema) * self._baseline
                                  + self.ema * med)
            self._drops = 0
            return False
        self._drops += 1
        if self._drops < self.consecutive:
            return False
        before = self.comm.strategy
        ratio = med / self._baseline
        winner = self.comm.autotune_strategy(nbytes=self.autotune_nbytes)
        self._drops = 0
        # the new schedule establishes its own baseline, and its first
        # window is a fresh re-jit (compile) — discard it again
        self._baseline = None
        self._warmed = False
        self.swaps += 1
        _log.info("device step-time regression %.2fx: autotune %s -> %s",
                  ratio, before, winner)
        return True
