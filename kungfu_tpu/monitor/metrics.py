"""Network monitoring: egress/ingress counters, rates, /metrics endpoint.

Parity with reference ``srcs/go/monitor/{monitor,counters,server}.go``:
per-remote-peer byte counters sampled into rates every
``KF_CONFIG_MONITORING_PERIOD`` seconds (default 1s), exposed through an
HTTP ``/metrics`` endpoint at ``worker port + 10000``
(``peer/peer.go:92-100``) and through :meth:`NetMonitor.egress_rates`
(the ``GetEgressRates`` API / ``EgressRates`` op analog).
Enabled by ``KF_CONFIG_ENABLE_MONITORING``.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from kungfu_tpu.monitor.registry import REGISTRY
from kungfu_tpu.utils.envs import MONITORING_PERIOD, parse_bool_env
from kungfu_tpu.utils.log import get_logger

_log = get_logger("metrics")

DEFAULT_PERIOD_S = 1.0
METRICS_PORT_OFFSET = 10000  # reference peer.go:92


class _RateCounter:
    __slots__ = ("total", "last_total", "rate")

    def __init__(self):
        self.total = 0
        self.last_total = 0
        self.rate = 0.0

    def sample(self, dt: float):
        d = self.total - self.last_total
        self.rate = d / dt if dt > 0 else 0.0
        self.last_total = self.total


class NetMonitor:
    """Byte counters per remote address, sampled into rates periodically."""

    def __init__(self, period: float = DEFAULT_PERIOD_S):
        self.period = period
        self._egress: Dict[str, _RateCounter] = defaultdict(_RateCounter)
        self._ingress: Dict[str, _RateCounter] = defaultdict(_RateCounter)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def egress(self, addr: str, nbytes: int) -> None:
        with self._lock:
            self._egress[addr].total += nbytes

    def ingress(self, addr: str, nbytes: int) -> None:
        with self._lock:
            self._ingress[addr].total += nbytes

    def _sample_loop(self):
        t0 = time.time()
        while not self._stop.wait(self.period):
            now = time.time()
            dt, t0 = now - t0, now
            with self._lock:
                for c in self._egress.values():
                    c.sample(dt)
                for c in self._ingress.values():
                    c.sample(dt)
                eg = sum(c.total for c in self._egress.values())
                ing = sum(c.total for c in self._ingress.values())
            # mirror the aggregate totals into the unified registry so
            # they render alongside the timeline/engine metrics (the
            # per-peer breakdown stays in render_prometheus — mirroring
            # it per label would double every line)
            REGISTRY.gauge("kf_net_egress_bytes").set(eg)
            REGISTRY.gauge("kf_net_ingress_bytes").set(ing)

    def start(self) -> "NetMonitor":
        self._thread = threading.Thread(target=self._sample_loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def egress_rates(self, addrs: Optional[List[str]] = None) -> List[float]:
        """Bytes/sec toward each addr (reference GetEgressRates)."""
        with self._lock:
            if addrs is None:
                addrs = sorted(self._egress)
            return [self._egress[a].rate if a in self._egress else 0.0 for a in addrs]

    def totals(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                "egress": {a: c.total for a, c in self._egress.items()},
                "ingress": {a: c.total for a, c in self._ingress.items()},
            }

    def render_prometheus(self, extra: Optional[Dict[str, float]] = None) -> str:
        lines = []
        with self._lock:
            for a, c in sorted(self._egress.items()):
                lines.append(f'kf_egress_bytes_total{{peer="{a}"}} {c.total}')
                lines.append(f'kf_egress_bytes_per_sec{{peer="{a}"}} {c.rate:.1f}')
            for a, c in sorted(self._ingress.items()):
                lines.append(f'kf_ingress_bytes_total{{peer="{a}"}} {c.total}')
                lines.append(f'kf_ingress_bytes_per_sec{{peer="{a}"}} {c.rate:.1f}')
        for k, v in (extra or {}).items():
            lines.append(f"{k} {v}")
        return "\n".join(lines) + "\n"


class MetricsServer:
    """HTTP ``/metrics`` endpoint (reference ``monitor/server.go``).

    Renders the :class:`NetMonitor` per-peer counters AND the unified
    :data:`~kungfu_tpu.monitor.registry.REGISTRY` (collective latency
    histograms, retry/fault/shrink counters, timeline drop counter) in
    one scrape.

    Binding: ``port=0`` asks the OS for an ephemeral port; a *taken*
    fixed port degrades to an ephemeral bind with a warning instead of
    an unhandled ``OSError`` — a stale process squatting
    worker-port+10000 must not kill the peer.  :attr:`port` always holds
    the port actually bound."""

    def __init__(self, monitor: NetMonitor, port: int, host: str = "0.0.0.0",
                 extra_fn=None):
        mon = monitor

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                _log.debug(fmt, *args)

            def do_GET(self):
                if not self.path.startswith("/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                # section isolation: a raised exception inside a user
                # extra_fn (or a render bug in one section) must not 500
                # the whole scrape — Prometheus would mark the target
                # down and every OTHER healthy series would vanish with
                # it.  Render what renders; report the rest as comment
                # lines (legal exposition-format noise).
                errors: list = []
                extra = None
                if extra_fn is not None:
                    try:
                        extra = extra_fn()
                    except Exception as e:  # noqa: BLE001 - user callback
                        errors.append(f"extra_fn: {type(e).__name__}: {e}")
                try:
                    text = mon.render_prometheus(extra)
                except Exception as e:  # noqa: BLE001
                    text = ""
                    errors.append(f"netmonitor: {type(e).__name__}: {e}")
                try:
                    text += REGISTRY.render_prometheus()
                except Exception as e:  # noqa: BLE001
                    errors.append(f"registry: {type(e).__name__}: {e}")
                for err in errors:
                    _log.warning("metrics scrape section failed: %s", err)
                    text += "# error: " + err.replace("\n", " ") + "\n"
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        try:
            self._server = ThreadingHTTPServer((host, port), Handler)
        except OSError as e:
            if port == 0:
                raise
            _log.warning(
                "metrics port %d unavailable (%s); binding an ephemeral "
                "port instead", port, e,
            )
            self._server = ThreadingHTTPServer((host, 0), Handler)
        self._server.daemon_threads = True
        #: the port actually bound (differs from the request under
        #: port=0 or the taken-port fallback)
        self.port = self._server.server_address[1]

    def start(self) -> "MetricsServer":
        threading.Thread(target=self._server.serve_forever, daemon=True).start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def monitoring_period_from_env() -> float:
    import os

    try:
        return float(os.environ.get(MONITORING_PERIOD, DEFAULT_PERIOD_S))
    except ValueError:
        return DEFAULT_PERIOD_S


def publish_device_memory() -> bool:
    """Poll the local accelerators' allocator stats into the unified
    registry: ``kf_device_memory_bytes{kind="in_use"|"limit"}`` summed
    over local devices.  The cluster snapshot then carries both gauges
    to kftop's dev-mem column and the sentinel's history — HBM pressure
    becomes a recorded series, not a post-OOM guess.

    None-safe by contract: backends without ``memory_stats`` (CPU) or a
    jax that cannot import make this a no-op returning ``False`` — it
    is wired as the RankReporter's ``pre_snapshot_fn``, where a raise
    would cost the snapshot its event window."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 - monitoring must not raise
        return False
    in_use = limit = 0
    found = False
    for d in devices:
        stats_fn = getattr(d, "memory_stats", None)
        if stats_fn is None:
            continue
        try:
            stats = stats_fn() or {}
        except Exception:  # noqa: BLE001 - backend quirk, not fatal
            continue
        if "bytes_in_use" not in stats:
            continue
        found = True
        in_use += int(stats.get("bytes_in_use", 0))
        limit += int(stats.get("bytes_limit",
                               stats.get("bytes_reservable_limit", 0)))
    if not found:
        return False
    REGISTRY.gauge("kf_device_memory_bytes", kind="in_use").set(in_use)
    if limit:
        REGISTRY.gauge("kf_device_memory_bytes", kind="limit").set(limit)
    return True
