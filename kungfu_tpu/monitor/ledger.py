"""kf-ledger: durable decision records + measured per-decision effects.

The adaptive actors this codebase has grown — the host/device collective
bandits, the overlap-depth bandit, the serving batch-width controller
and autoscaler, the shrink protocol — all change knobs that move the
very series the sentinel judges, and until now each change vanished the
moment it executed.  This module is the accountability plane: every
actor writes ONE structured **decision record** ``(actor, knob, old,
new, consensus_seq, trace_id, evidence)`` through
:func:`record_decision`, and the ledger later joins it to its
**measured effect** — the median shift of a history series between the
``window`` samples before the decision and the ``window`` samples after
it, scored in MAD units with the exact :mod:`~kungfu_tpu.monitor.
detect` scale-floor math the changepoint detector uses.

Both halves land in one durable :class:`~kungfu_tpu.monitor.history.
HistoryRing` stream (``decisions``) under ``KF_SENTINEL_DIR``, next to
the ``cluster`` stream whose samples feed the join.  Determinism
doctrine: the effect verdict is a pure function of (decision record,
effect-series samples), so ``kfhist --decisions`` recomputing it
offline from the durable streams produces records byte-identical
(``json.dumps(..., sort_keys=True)``) to the ones the live ledger
appended — asserted in tests and the ``bench.py --pulse`` gate.

Field discipline: record field names are a declared closed schema
(:data:`LEDGER_FIELDS`), written through :func:`ledger_record` and read
through :func:`lfield` — both enforced at runtime here and statically
by the ``ledger-schema`` kflint rule (a typo'd field would silently
break every offline join).

Cost contract: with ``KF_SENTINEL_DIR`` unset :func:`active` is ``None``
and :func:`record_decision` is an env check + return.  Every decision
ticks the counted ``decision`` timeline kind
(``kf_decisions_total{actor=...}``) regardless, like alerts — a knob
change ``/metrics`` cannot count did not happen.

Stdlib-only, like every monitor/ module.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from kungfu_tpu.monitor import detect, history, timeline

#: the decisions stream name under ``KF_SENTINEL_DIR``
DECISIONS_STREAM = "decisions"

#: the closed record-field schema, enforced by :func:`ledger_record` /
#: :func:`lfield` at runtime and the ``ledger-schema`` kflint rule
#: statically.  Two record kinds share it: ``decision`` (the knob
#: change + provenance) and ``effect`` (the measured before/after
#: verdict joined back by ``decision_seq``).
LEDGER_FIELDS = frozenset({
    # both kinds
    "kfledger", "kind", "seq", "wall",
    # decision records
    "actor", "knob", "old", "new", "step", "consensus_seq", "trace_id",
    "evidence", "history_n", "series_n", "effect_series", "good_direction",
    # effect records
    "decision_seq", "series", "window", "threshold", "before_median",
    "before_mad", "after_median", "shift", "score", "verdict",
})

#: the series a decision is judged against when its actor names none —
#: every adaptive actor ultimately answers to step time
DEFAULT_EFFECT_SERIES = "step_time_s"
#: the shift direction that counts as an improvement for the default
#: series (step time going DOWN is good)
DEFAULT_GOOD_DIRECTION = "down"


def ledger_record(**fields) -> dict:
    """Schema-checked record builder (the ledger analog of
    ``aggregator.make_snapshot``): unknown field names raise — the
    runtime backstop behind the static ``ledger-schema`` rule."""
    unknown = set(fields) - LEDGER_FIELDS
    if unknown:
        raise ValueError(f"unknown ledger field(s) {sorted(unknown)}")
    return dict(fields)


def lfield(obj: Optional[dict], name: str, default=None):
    """Schema-checked record read (the ledger analog of
    ``aggregator.field``): reading a name outside :data:`LEDGER_FIELDS`
    raises instead of returning a silent ``None``."""
    if name not in LEDGER_FIELDS:
        raise KeyError(f"unknown ledger field {name!r}")
    if not isinstance(obj, dict):
        return default
    return obj.get(name, default)


def judge(decision: dict, before: List[float],
          after: List[float]) -> Optional[dict]:
    """The pure effect verdict: the median shift of the effect series
    across the decision boundary, scored in MAD units with the EXACT
    :func:`~kungfu_tpu.monitor.detect.changepoint` scale floor (and its
    9/6-decimal rounding), so online and offline computations are
    byte-identical.  ``None`` while the after window is short (the
    decision is still pending); verdict ``insufficient`` when the
    BEFORE window never had a full baseline."""
    window = int(lfield(decision, "window",
                        detect.DEFAULT_WINDOW) or detect.DEFAULT_WINDOW)
    threshold = float(lfield(decision, "threshold",
                             detect.DEFAULT_THRESHOLD)
                      or detect.DEFAULT_THRESHOLD)
    series = lfield(decision, "effect_series") or DEFAULT_EFFECT_SERIES
    good = lfield(decision, "good_direction") or DEFAULT_GOOD_DIRECTION
    if len(after) < window:
        return None
    after = [float(v) for v in after[:window]]
    base = ledger_record(
        kfledger=1,
        kind="effect",
        decision_seq=lfield(decision, "seq"),
        actor=lfield(decision, "actor"),
        knob=lfield(decision, "knob"),
        series=series,
        good_direction=good,
        window=window,
        threshold=threshold,
    )
    if len(before) < window:
        base.update(ledger_record(
            verdict="insufficient",
            before_median=None, before_mad=None, after_median=None,
            shift=None, score=None))
        return base
    before = [float(v) for v in before[-window:]]
    base_med = detect.median(before)
    base_mad = detect.mad(before, base_med)
    after_med = detect.median(after)
    shift = after_med - base_med
    scale = max(base_mad,
                detect.DEFAULT_REL_FLOOR * abs(base_med)
                / max(threshold, 1.0),
                detect.ABS_FLOOR)
    score = shift / scale                      # SIGNED, unlike changepoint
    if abs(score) < threshold:
        verdict = "neutral"
    elif (score < 0) == (good == "down"):
        verdict = "improved"
    else:
        verdict = "regressed"
    base.update(ledger_record(
        before_median=round(base_med, 9),
        before_mad=round(base_mad, 9),
        after_median=round(after_med, 9),
        shift=round(shift, 9),
        score=round(score, 6),
        verdict=verdict,
    ))
    return base


class DecisionLedger:
    """One run's decision stream: durable appends + the online join.

    The owner (the :class:`~kungfu_tpu.monitor.sentinel.Sentinel`, or a
    test) feeds every cluster history record through :meth:`on_sample`;
    :meth:`decide` snapshots the effect series' trailing ``window``
    samples as the BEFORE evidence and parks the decision until the
    AFTER window fills, at which point the verdict is appended to the
    same stream.  All state needed by the join is IN the records, so
    the offline replay (:func:`replay_effects`) is self-contained."""

    def __init__(self, root: str, window: int = detect.DEFAULT_WINDOW,
                 threshold: float = detect.DEFAULT_THRESHOLD,
                 keep_bytes: Optional[int] = None):
        self.root = root
        self.window = max(2, int(window))
        self.threshold = float(threshold)
        self._lock = threading.Lock()
        self._ring = history.HistoryRing(root, DECISIONS_STREAM,
                                         keep_bytes=keep_bytes)
        self._seq = 0                      # decision records appended
        self._samples_seen = 0             # cluster records observed
        self._series_n: Dict[str, int] = {}    # per-series sample counts
        self._tails: Dict[str, List[float]] = {}  # trailing `window` each
        self._pending: List[dict] = []     # [{decision, after: []}]
        self._effects: List[dict] = []     # judged effects (bounded)
        self._decisions: List[dict] = []   # decision records (bounded)
        self._max_kept = 256

    # -- write side -------------------------------------------------------
    def decide(self, actor: str, knob: str, old, new,
               consensus_seq=None, trace_id: Optional[str] = None,
               evidence: Optional[dict] = None,
               effect_series: str = DEFAULT_EFFECT_SERIES,
               good_direction: str = DEFAULT_GOOD_DIRECTION,
               step: Optional[int] = None,
               wall: Optional[float] = None) -> dict:
        """Append one decision record; returns it.  ``trace_id``
        defaults to the ambient timeline trace so a decision made while
        handling a traced operation joins its causal chain."""
        if trace_id is None:
            trace_id = timeline.current_trace()[0]
        if step is None:
            step = timeline.current_step()
        with self._lock:
            self._seq += 1
            rec = ledger_record(
                kfledger=1,
                kind="decision",
                seq=self._seq,
                wall=wall,
                actor=str(actor),
                knob=str(knob),
                old=old,
                new=new,
                step=step,
                consensus_seq=consensus_seq,
                trace_id=trace_id,
                evidence=evidence or {},
                history_n=self._samples_seen,
                series_n=self._series_n.get(effect_series, 0),
                effect_series=effect_series,
                good_direction=good_direction,
                window=self.window,
                threshold=self.threshold,
            )
            self._ring.append(rec)
            self._decisions.append(rec)
            del self._decisions[:-self._max_kept]
            self._pending.append({
                "decision": rec,
                "before": list(self._tails.get(effect_series, [])),
                "after": [],
            })
        # counted kind labeled by actor: kf_decisions_total{actor=...}
        # ticks even with tracing off; force=True lands the mark in the
        # flight recorder regardless, like alerts — rare events both
        timeline.event("decision", str(actor), force=True,
                       knob=str(knob), old=old, new=new,
                       seq=self._seq, consensus_seq=consensus_seq)
        return rec

    # -- sample feed ------------------------------------------------------
    def on_sample(self, record: dict) -> List[dict]:
        """One cluster history record (the sentinel's ``_observe_locked``
        appends it to the ``cluster`` stream, then feeds it here, so the
        ledger's sample counts mirror the durable stream exactly).
        Judges any pending decision whose after window just filled;
        returns the effect records appended by this sample."""
        series = record.get("series")
        if not isinstance(series, dict):
            series = {}
        out: List[dict] = []
        with self._lock:
            self._samples_seen += 1
            for name, value in series.items():
                if isinstance(value, bool) \
                        or not isinstance(value, (int, float)):
                    continue
                v = float(value)
                self._series_n[name] = self._series_n.get(name, 0) + 1
                tail = self._tails.setdefault(name, [])
                tail.append(v)
                del tail[:-self.window]
                for p in self._pending:
                    d = p["decision"]
                    if lfield(d, "effect_series") == name \
                            and len(p["after"]) < self.window:
                        p["after"].append(v)
            still = []
            for p in self._pending:
                effect = judge(p["decision"], p["before"], p["after"])
                if effect is None:
                    still.append(p)
                    continue
                self._ring.append(effect)
                self._effects.append(effect)
                del self._effects[:-self._max_kept]
                out.append(effect)
            self._pending = still
        return out

    # -- read side --------------------------------------------------------
    def summary(self) -> dict:
        """The ``decisions`` shape ``alerts_view()`` /
        ``policy.sentinel_signals()`` publish: counts by verdict plus
        the newest effect — enough for a policy to steer by without
        reading the stream."""
        with self._lock:
            by_verdict: Dict[str, int] = {}
            for e in self._effects:
                v = str(lfield(e, "verdict"))
                by_verdict[v] = by_verdict.get(v, 0) + 1
            return {
                "total": self._seq,
                "judged": len(self._effects),
                "pending": len(self._pending),
                "by_verdict": dict(sorted(by_verdict.items())),
                "last": dict(self._effects[-1]) if self._effects else None,
            }

    def view(self) -> dict:
        """The ``/decisions`` JSON: recent decision records with their
        effects joined by ``decision_seq``, plus the summary."""
        with self._lock:
            effects = {lfield(e, "decision_seq"): e for e in self._effects}
            rows = []
            for d in self._decisions:
                seq = lfield(d, "seq")
                rows.append({
                    "decision": dict(d),
                    "effect": (dict(effects[seq])
                               if seq in effects else None),
                })
        return {
            "kfledger": 1,
            "decisions": rows,
            "summary": self.summary(),
        }


# -- offline replay (kfhist --decisions) ------------------------------------
def replay_effects(root: str) -> dict:
    """Recompute every judged decision's effect record offline from the
    durable ``decisions`` + ``cluster`` streams — the exact
    :func:`judge` math over the exact sample slices the online ledger
    saw (``series_n`` positions the decision inside the effect series),
    so each replayed record must equal the stream's online effect
    record byte for byte.  Returns online/replayed pairs plus the
    stream's raw decisions for rendering."""
    decisions_raw, skipped = history.scan_stream(root, DECISIONS_STREAM)
    cluster, _ = history.scan_stream(root, "cluster")
    series = history.series_from_records(cluster)
    decisions = [r for r in decisions_raw if r.get("kind") == "decision"]
    online = {lfield(r, "decision_seq"): r for r in decisions_raw
              if r.get("kind") == "effect"}
    rows = []
    for d in decisions:
        name = lfield(d, "effect_series") or DEFAULT_EFFECT_SERIES
        pos = int(lfield(d, "series_n") or 0)
        window = int(lfield(d, "window",
                            detect.DEFAULT_WINDOW) or detect.DEFAULT_WINDOW)
        xs = series.get(name, [])
        before = xs[max(0, pos - window):pos]
        after = xs[pos:pos + window]
        replayed = judge(d, before, after)
        rows.append({
            "decision": d,
            "online": online.get(lfield(d, "seq")),
            "replayed": replayed,
        })
    return {
        "kfledger": 1,
        "records": len(decisions_raw),
        "skipped": skipped,
        "decisions": rows,
    }


# -- module-global registry (env-keyed, like the sentinel plane) ------------
_registry_lock = threading.Lock()
_ledgers: Dict[str, DecisionLedger] = {}


def ledger_for(root: str, window: Optional[int] = None,
               threshold: Optional[float] = None,
               keep_bytes: Optional[int] = None) -> DecisionLedger:
    """The per-root singleton: the sentinel constructs it with ITS
    window/threshold, and every actor's :func:`record_decision` (keyed
    off the same ``KF_SENTINEL_DIR``) lands in the same instance — one
    stream, one sample feed, one seq space."""
    with _registry_lock:
        led = _ledgers.get(root)
        if led is None:
            led = _ledgers[root] = DecisionLedger(
                root,
                window=(window if window is not None
                        else _env_i("KF_SENTINEL_WINDOW",
                                    detect.DEFAULT_WINDOW)),
                threshold=(threshold if threshold is not None
                           else _env_f("KF_SENTINEL_THRESHOLD",
                                       detect.DEFAULT_THRESHOLD)),
                keep_bytes=keep_bytes,
            )
        return led


def _env_i(env: str, default: int) -> int:
    try:
        return int(os.environ.get(env, "") or default)
    except ValueError:
        return default


def _env_f(env: str, default: float) -> float:
    try:
        return float(os.environ.get(env, "") or default)
    except ValueError:
        return default


def active() -> Optional[DecisionLedger]:
    """The env-keyed ledger, or ``None`` when ``KF_SENTINEL_DIR`` is
    unset (the whole accountability plane gated on the same one token
    as the sentinel — a decision stream with no sample feed would
    never judge anything)."""
    root = (os.environ.get(history.DIR_ENV, "") or "").strip()
    if not root:
        return None
    return ledger_for(root)


def record_decision(actor: str, knob: str, old, new,
                    **kwargs) -> Optional[dict]:
    """The one-line actor hook: appends a decision record when the
    plane is on, returns ``None`` (after one env check) when it is not.
    Never raises — an unwritable ledger must not take an adaptive
    actor down with it."""
    led = active()
    if led is None:
        return None
    try:
        return led.decide(actor, knob, old, new, **kwargs)
    except Exception:  # noqa: BLE001 - accountability must not break actors
        return None


def reset() -> None:
    """Drop every env-keyed ledger instance (tests — a process-global
    registry otherwise leaks state across tmp dirs)."""
    with _registry_lock:
        _ledgers.clear()
