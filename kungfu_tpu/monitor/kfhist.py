"""``kfhist``: offline reader for the kf-sentinel durable history.

Answers the post-mortem questions the live planes cannot: *when* did
step time start drifting, what did the serving latencies look like
before the alert, and — crucially — **would the detector have said the
same thing?**  ``kfhist --verdict`` replays the durable ``cluster``
stream through the SAME :mod:`~kungfu_tpu.monitor.detect` math the
online :class:`~kungfu_tpu.monitor.sentinel.Sentinel` runs, with the
same env-default knobs, so the offline verdict and the live alert are
one implementation and cannot disagree (asserted in tests and the
``bench.py --sentinel`` gate).

Modes::

    kfhist --dir RUNDIR --list               # streams + record counts
    kfhist --dir RUNDIR                      # cluster series summary
    kfhist --dir RUNDIR --series step_time_s # one series' samples
    kfhist --dir RUNDIR --verdict            # detector replay
    kfhist --dir RUNDIR --verdict --upto N   # ...over the first N records
    kfhist --dir RUNDIR --decisions          # kf-ledger effect replay
    kfhist --json ...                        # machine output (scripts)
    kfhist --self-check                      # ring+detector round trip

``--upto`` selects the exact record prefix an incident flight record
was judged over (its ``history_n`` field), so ``kfhist --verdict --upto
<history_n>`` must reproduce the bundle's embedded ``verdicts`` byte
for byte.  ``--decisions`` extends the doctrine to the kf-ledger: each
decision's effect verdict is recomputed offline from the durable
``decisions`` + ``cluster`` streams (:func:`kungfu_tpu.monitor.ledger.
replay_effects`) and must match the online effect record byte for byte.

Stdlib-only, launched through ``scripts/kfhist`` with the same package
stubs as ``kftop``/``kftrace``: no jax, no package ``__init__`` chain.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from typing import Dict, List, Optional, Sequence

from kungfu_tpu.monitor import detect, history
from kungfu_tpu.monitor import ledger as ledgerlib
from kungfu_tpu.monitor import sentinel as sentinellib


def _summary(series: Dict[str, List[float]]) -> Dict[str, dict]:
    out = {}
    for name in sorted(series):
        xs = series[name]
        out[name] = {
            "n": len(xs),
            "min": round(min(xs), 9),
            "median": round(detect.median(xs), 9),
            "max": round(max(xs), 9),
            "latest": round(xs[-1], 9),
        }
    return out


def verdict_from_dir(root: str, stream: str = sentinellib.CLUSTER_STREAM,
                     upto: Optional[int] = None,
                     window: Optional[int] = None,
                     threshold: Optional[float] = None) -> dict:
    """The offline detector replay: durable records -> series ->
    :func:`~kungfu_tpu.monitor.detect.window_verdicts`.  Defaults come
    from the SAME env knobs the online sentinel reads, so with no flags
    this is exactly what the live plane computed."""
    if window is None:
        window = sentinellib._i(sentinellib.WINDOW_ENV,
                                detect.DEFAULT_WINDOW)
    if threshold is None:
        threshold = sentinellib._f(sentinellib.THRESHOLD_ENV,
                                   detect.DEFAULT_THRESHOLD)
    records, skipped = history.scan_stream(root, stream)
    if upto is not None and upto >= 0:
        records = records[:upto]
    series = history.series_from_records(records)
    return {
        "kfhist": 1,
        "stream": stream,
        "records": len(records),
        "skipped": skipped,
        "window": window,
        "threshold": threshold,
        "verdicts": detect.window_verdicts(series, window=window,
                                           threshold=threshold),
    }


def _print_verdict(out: dict) -> None:
    print(f"kfhist: {out['records']} record(s), {out['skipped']} skipped, "
          f"window {out['window']}, threshold {out['threshold']}")
    verdicts = out["verdicts"]
    if not verdicts:
        print("  (not enough samples for any verdict — need two windows)")
        return
    for name, v in verdicts.items():
        mark = (f"SHIFTED {v['direction']}" if v["shifted"] else "flat")
        print(f"  {name}: {mark} — baseline {v['base_median']} "
              f"recent {v['recent_median']} score {v['score']} "
              f"(threshold {v['threshold']})")


def decisions_from_dir(root: str) -> dict:
    """The offline kf-ledger replay, with a ``match`` flag per decision:
    ``True`` iff the recomputed effect record equals the stream's online
    one byte for byte (``json.dumps(..., sort_keys=True)``)."""
    out = ledgerlib.replay_effects(root)
    for row in out["decisions"]:
        online, replayed = row["online"], row["replayed"]
        if online is None and replayed is None:
            row["match"] = None          # still pending on both sides
        else:
            row["match"] = (
                json.dumps(online, sort_keys=True)
                == json.dumps(replayed, sort_keys=True))
    return out


def _print_decisions(out: dict) -> None:
    rows = out["decisions"]
    print(f"kfhist: {out['records']} ledger record(s), "
          f"{out['skipped']} skipped, {len(rows)} decision(s)")
    if not rows:
        print("  (no decisions recorded — actors write via "
              "kungfu_tpu.monitor.ledger.record_decision)")
        return
    lf = ledgerlib.lfield
    for row in rows:
        d = row["decision"]
        head = (f"  #{lf(d, 'seq')} {lf(d, 'actor')}/{lf(d, 'knob')}: "
                f"{lf(d, 'old')!r} -> {lf(d, 'new')!r}"
                f" (step {lf(d, 'step')}, consensus "
                f"{lf(d, 'consensus_seq')})")
        print(head)
        e = row["replayed"]
        if e is None:
            if row["match"] is None:
                print("    effect: pending (after window not filled)")
            else:
                print("    effect: replay produced none but the stream "
                      "has an online record — replay MISMATCH")
            continue
        if lf(e, "verdict") == "insufficient":
            print(f"    effect: insufficient baseline "
                  f"({lf(e, 'series')})")
        else:
            print(f"    effect: {lf(e, 'verdict').upper()} — "
                  f"{lf(e, 'series')} {lf(e, 'before_median')} -> "
                  f"{lf(e, 'after_median')} "
                  f"(shift {lf(e, 'shift')}, score {lf(e, 'score')}, "
                  f"threshold {lf(e, 'threshold')})")
        mark = {True: "replay MATCH", False: "replay MISMATCH",
                None: "replay n/a"}[row["match"]]
        print(f"    {mark}")


# -- self-check --------------------------------------------------------------
def self_check() -> int:
    """Ring + reader + detector round trip in a temp dir: segmentation
    and GC behave, a torn line is skipped not fatal, a planted shift is
    detected and a clean series is not (wired into check.sh)."""
    import os

    ok = True
    with tempfile.TemporaryDirectory(prefix="kfhist-selfcheck-") as d:
        ring = history.HistoryRing(d, "cluster", keep_bytes=1 << 20,
                                   segment_records=8)
        # 24 clean + 8 shifted step-time samples: the last window is the
        # planted regression, the baseline is clean
        for i in range(32):
            st = 0.1 if i < 24 else 0.25
            ring.append({"kfhist": 1, "wall": 1000.0 + i,
                         "series": {"step_time_s": st, "mfu": 0.4}})
        segs = history._segments(d, "cluster")
        # 32 appends at 8/segment = 4 sealed segments (the next open
        # segment has no file until its first append)
        ok = ok and len(segs) == 4
        # a torn trailing line in a sealed segment is skipped, not fatal
        with open(segs[0][1], "ab") as f:
            f.write(b'{"torn": ')
        records, skipped = history.scan_stream(d, "cluster")
        ok = ok and len(records) == 32 and skipped == 1
        out = verdict_from_dir(d)
        v = out["verdicts"].get("step_time_s")
        ok = (ok and v is not None and v["shifted"]
              and v["direction"] == "up")
        # the untouched series must stay flat — no false positive
        m = out["verdicts"].get("mfu")
        ok = ok and m is not None and not m["shifted"]
        # --upto replays a prefix: before the shift landed, no verdict
        # may call step_time_s shifted
        pre = verdict_from_dir(d, upto=24)
        pv = pre["verdicts"].get("step_time_s")
        ok = ok and (pv is None or not pv["shifted"])
        # GC: a tiny budget drops sealed segments but never the open one
        # (14 appends at 4/segment: 3 sealed + an open segment of 2)
        ring2 = history.HistoryRing(d, "gc", keep_bytes=256,
                                    segment_records=4)
        for i in range(14):
            ring2.append({"kfhist": 1, "wall": float(i),
                          "series": {"x": float(i)}})
        remaining = [s for s, _ in history._segments(d, "gc")]
        ok = ok and remaining and remaining[-1] == ring2._seq
        sealed_size = sum(os.path.getsize(p)
                          for seq, p in history._segments(d, "gc")
                          if seq != ring2._seq)
        ok = ok and sealed_size <= 256
        # kf-ledger round trip (own subdir — the cluster stream above
        # would shift the sample positions): a decision judged online
        # over the live feed must replay byte-identically offline
        ld = os.path.join(d, "ledger")
        lg = ledgerlib.DecisionLedger(ld, window=4, threshold=4.0)
        cluster_ring = history.HistoryRing(ld, "cluster",
                                           keep_bytes=1 << 20)
        for i, st in enumerate([0.2] * 6 + [0.1] * 4):
            if i == 6:
                lg.decide("selfcheck", "knob", "a", "b", wall=0.0,
                          trace_id="t0", step=i)
            rec = {"kfhist": 1, "wall": 2000.0 + i,
                   "series": {"step_time_s": st}}
            cluster_ring.append(rec)
            lg.on_sample(rec)
        rep = decisions_from_dir(ld)
        ok = (ok and len(rep["decisions"]) == 1
              and rep["decisions"][0]["match"] is True
              and ledgerlib.lfield(rep["decisions"][0]["replayed"],
                                   "verdict") == "improved")
    if not ok:
        print("kfhist: self-check FAILED (ring/detector round-trip "
              "mismatch)", file=sys.stderr)
        return 1
    print("kfhist: self-check ok (ring + detector round-trip)")
    return 0


# -- CLI ---------------------------------------------------------------------
def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-check" in argv:
        return self_check()
    p = argparse.ArgumentParser(
        prog="kfhist",
        description="offline reader for the kf-sentinel durable metrics "
                    "history (KF_SENTINEL_DIR rings)",
    )
    p.add_argument("--dir", required=True,
                   help="history root (the run's KF_SENTINEL_DIR)")
    p.add_argument("--stream", default=sentinellib.CLUSTER_STREAM,
                   help="stream name (default: cluster; rank-N for ranks)")
    p.add_argument("--list", action="store_true",
                   help="list streams with record counts")
    p.add_argument("--series", default=None,
                   help="print one series' samples")
    p.add_argument("--last", type=int, default=None,
                   help="only the newest N records")
    p.add_argument("--upto", type=int, default=None,
                   help="only the first N records (an incident's "
                        "history_n — replays exactly what it was "
                        "judged over)")
    p.add_argument("--verdict", action="store_true",
                   help="replay the online detector over the stream")
    p.add_argument("--decisions", action="store_true",
                   help="replay the kf-ledger decision effects offline "
                        "and check them against the online records")
    p.add_argument("--window", type=int, default=None,
                   help="changepoint window (default: KF_SENTINEL_WINDOW)")
    p.add_argument("--threshold", type=float, default=None,
                   help="shift threshold (default: KF_SENTINEL_THRESHOLD)")
    p.add_argument("--json", action="store_true",
                   help="machine output")
    args = p.parse_args(argv)

    if args.list:
        out = {}
        for stream in history.streams(args.dir):
            records, skipped = history.scan_stream(args.dir, stream)
            out[stream] = {"records": len(records), "skipped": skipped}
        if args.json:
            json.dump(out, sys.stdout, indent=2, sort_keys=True)
            sys.stdout.write("\n")
        else:
            if not out:
                print(f"kfhist: no streams under {args.dir}")
            for stream, info in sorted(out.items()):
                print(f"  {stream}: {info['records']} record(s)"
                      + (f", {info['skipped']} skipped"
                         if info["skipped"] else ""))
        return 0

    if args.verdict:
        out = verdict_from_dir(args.dir, stream=args.stream,
                               upto=args.upto, window=args.window,
                               threshold=args.threshold)
        if args.json:
            json.dump(out, sys.stdout, indent=2, sort_keys=True)
            sys.stdout.write("\n")
        else:
            _print_verdict(out)
        return 0

    if args.decisions:
        out = decisions_from_dir(args.dir)
        if args.json:
            json.dump(out, sys.stdout, indent=2, sort_keys=True)
            sys.stdout.write("\n")
        else:
            _print_decisions(out)
        return 1 if any(row["match"] is False
                        for row in out["decisions"]) else 0

    records, skipped = history.scan_stream(args.dir, args.stream)
    if args.upto is not None and args.upto >= 0:
        records = records[:args.upto]
    if args.last is not None and args.last >= 0:
        records = records[-args.last:]
    series = history.series_from_records(records)
    if args.series:
        xs = series.get(args.series, [])
        if args.json:
            json.dump({"series": args.series, "samples": xs}, sys.stdout)
            sys.stdout.write("\n")
        else:
            print(f"kfhist: {args.series}: {len(xs)} sample(s)")
            for v in xs:
                print(f"  {v}")
        return 0
    out = {
        "kfhist": 1,
        "stream": args.stream,
        "records": len(records),
        "skipped": skipped,
        "series": _summary(series),
    }
    if args.json:
        json.dump(out, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(f"kfhist: stream {args.stream}: {len(records)} record(s)"
              + (f", {skipped} skipped" if skipped else ""))
        for name, s in out["series"].items():
            print(f"  {name}: n={s['n']} min={s['min']} "
                  f"median={s['median']} max={s['max']} "
                  f"latest={s['latest']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
