"""Weight-update sharding (ZeRO-1) — the optimizer step, data-parallel.

Plain S-SGD makes every replica apply the identical optimizer update to
the full parameter set: n copies of the update FLOPs, n copies of the
optimizer state in HBM.  Weight-update sharding (the "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training"
technique from the TPU MLPerf submissions; ZeRO stage 1 elsewhere)
splits the update instead:

    reduce-scatter(grads) → each replica owns 1/n of the flat gradient
    inner update on the owned shard (momentum/Adam state: 1/n per chip)
    all-gather(updated params) → everyone replicated again

For any ELEMENTWISE inner transform (sgd, momentum, adam, adamw,
rmsprop, …) the sharded update is exactly the full update restricted to
the shard, so the result matches
:func:`~kungfu_tpu.optimizers.synchronous_sgd` to float tolerance — the
win is n× less optimizer-state memory and n× fewer update FLOPs, paid
with an all-gather of params instead of an all-reduce of grads (the
same bytes on the wire: reduce-scatter + all-gather IS the
bandwidth-optimal all-reduce decomposition, cf.
:mod:`kungfu_tpu.ops.schedules`).

Non-elementwise transforms (``clip_by_global_norm``, anything that
mixes statistics across parameters) are NOT shard-equivalent — compose
them on the gradient side before this wrapper if needed.

Structure note: the scatter + shard update run inside ``shard_map``
(their outputs are genuinely sharded, declared ``P(axes)``); the param
re-gather is left to the enclosing jit — ``defuse`` of the sharded flat
buffer makes XLA's partitioner insert the all-gather, which also keeps
shard_map's varying-manual-axes checking fully on (an in-body
``all_gather`` result cannot be declared replicated without disabling
the check).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from kungfu_tpu.utils.jaxcompat import axis_size, shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from kungfu_tpu.ops.fuse import defuse, fuse


def zero1_train_step(loss_fn, inner: optax.GradientTransformation, comm,
                     average: bool = True, donate: bool = False):
    """Build a ZeRO-1 data-parallel training step over ``comm``'s mesh.

    ``loss_fn(params, batch) -> scalar`` runs per device on its batch
    shard (same contract as
    :func:`~kungfu_tpu.parallel.train.dp_train_step`); ``inner`` is any
    elementwise optax transform.

    Returns ``(step, init_opt)``:

    * ``init_opt(params) -> opt_shard`` — the optimizer state over the
      mesh-sharded flat parameter buffer (each device holds 1/n; build
      once per mesh epoch).
    * ``step(params, opt_shard, batch) -> (params, opt_shard, loss)`` —
      jitted over the mesh; params replicated in/out, ``batch`` leading
      axis divisible by ``comm.size``.
    """
    mesh, axes = comm.mesh, comm.axis
    axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
    n = comm.size

    def build(params):
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        buf, spec = fuse(zeros)
        total = int(buf.shape[-1])
        chunk = math.ceil(total / n)
        padded = chunk * n
        flat_dtype = spec.fused_dtype
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        # OUTER-axis-first scatter: the chunk device (i_h, i_l) ends up
        # owning then sits at flat offset (i_h*n_l + i_l)*chunk — the
        # same mesh-major order P(axes) uses to assemble the global
        # buffer, so the enclosing jit's defuse reads chunks back in
        # place (inner-first scattering produces local-major content and
        # a permuted parameter tree on hierarchical meshes)
        scatter_axes = [ax for ax in axes_t if sizes[ax] > 1]

        # optimizer-state pytree structure over one shard: vector leaves
        # are sharded over the mesh, scalar leaves (e.g. Adam's count)
        # are replicated
        state_shapes = jax.eval_shape(
            inner.init, jax.ShapeDtypeStruct((chunk,), flat_dtype)
        )
        state_specs = jax.tree_util.tree_map(
            lambda s: P(axes) if s.ndim else P(), state_shapes
        )

        def my_offset():
            off, seg = jnp.int32(0), padded
            for ax in scatter_axes:
                seg = seg // axis_size(ax)
                off = off + lax.axis_index(ax) * seg
            return off

        def flat_of(tree):
            b, _ = fuse(tree)
            pad = padded - total
            if pad:
                b = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)])
            return b.astype(flat_dtype)

        def init_body(params):
            shard = lax.dynamic_slice(
                flat_of(params), (my_offset(),), (chunk,)
            )
            return inner.init(shard)

        init_opt = jax.jit(shard_map(
            init_body, mesh=mesh, in_specs=(P(),), out_specs=state_specs,
        ))

        def step_body(params, opt_shard, batch):
            # differentiate w.r.t. a per-device VARYING view of the
            # params: against the replicated view, autodiff inserts a
            # full cotangent psum (an all-reduce — the exact collective
            # this technique replaces), and the scatter below would
            # re-sum the already-summed gradients on top (measured n^2)
            from kungfu_tpu.ops.pallas._sharding import match_vma

            p_var = jax.tree_util.tree_map(
                lambda a: match_vma(a, frozenset(axes_t)), params
            )
            loss, grads = jax.value_and_grad(loss_fn)(p_var, batch)
            g = flat_of(grads)
            for ax in scatter_axes:
                g = lax.psum_scatter(g, ax, scatter_dimension=0, tiled=True)
            if average:
                g = g / n
            p_shard = lax.dynamic_slice(
                flat_of(params), (my_offset(),), (chunk,)
            )
            updates, opt_shard = inner.update(g, opt_shard, p_shard)
            p_shard = optax.apply_updates(p_shard, updates)
            loss = lax.pmean(loss, axes)
            return p_shard, opt_shard, loss

        inner_step = shard_map(
            step_body, mesh=mesh,
            in_specs=(P(), state_specs, P(axes)),
            out_specs=(P(axes), state_specs, P()),
        )

        def outer(params, opt_shard, batch):
            p_flat, opt_shard, loss = inner_step(params, opt_shard, batch)
            # p_flat is the sharded [padded] buffer; defuse's slices make
            # the partitioner insert the all-gather back to replicated —
            # PINNED, not left to compiler choice: a sharded params
            # output would poison every replicated-convention consumer
            # (resync, host snapshots) on multi-controller meshes
            from jax.sharding import NamedSharding

            rep = NamedSharding(mesh, P())
            new_params = jax.tree_util.tree_map(
                lambda a: jax.lax.with_sharding_constraint(a, rep),
                defuse(p_flat[:total], spec),
            )
            return new_params, opt_shard, loss

        return (
            jax.jit(outer, donate_argnums=(0, 1) if donate else ()),
            init_opt,
        )

    # the flat geometry depends on the param structure AND leaf
    # shapes/dtypes (the fuse spec bakes both in); build lazily on first
    # use and cache per full abstract signature
    cache = {}

    def _get(params):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        key = (treedef,
               tuple((tuple(l.shape), str(l.dtype)) for l in leaves))
        if key not in cache:
            cache[key] = build(params)
        return cache[key]

    def init_opt(params):
        return _get(params)[1](params)

    def step(params, opt_shard, batch):
        return _get(params)[0](params, opt_shard, batch)

    return step, init_opt


def zero1_reshard(opt_shard, params, new_comm, peer=None, snapshot=None):
    """Re-place a ZeRO-1 optimizer shard onto a NEW mesh epoch.

    The sharded state's geometry (chunk = ceil(total/n), mesh-major
    scatter order) is baked into each vector leaf, so an elastic resize
    cannot just keep training — the state must be re-chunked for the
    new world size.  Each vector leaf is unpadded to the true parameter
    count (recovered from ``params``), re-padded to the NEW chunk
    geometry, and placed sharded over the new mesh; scalar leaves (e.g.
    Adam's step count) are re-placed replicated.  Values are exactly
    preserved, so training continues as if the optimizer had always run
    at the new size — the same guarantee the elementwise-equivalence of
    the step itself gives.

    Two modes:

    * **Single-controller** (simulated peers / one host), no
      ``snapshot``: every old chunk is addressable — direct runtime
      re-placement, no host channel involved.
    * **Multi-controller** (or an explicit ``snapshot``): the old
      chunks live in other processes — some of which a shrink just
      retired — so the state must have been captured with
      :func:`zero1_snapshot` over the OLD epoch's membership *before*
      the resize (rank 0 holds the blob; the chunk owners may no longer
      be reachable afterwards).  Rank 0 passes it as ``snapshot``;
      everyone else passes ``None`` and receives it over ``peer``'s
      host channel.  ``opt_shard`` supplies only the state STRUCTURE
      here (a joiner passes its fresh ``init_opt(params)``) — vector
      geometry is synthesized for the new mesh, values come from the
      snapshot.  This folds the former snapshot→restore detour under
      the one reshard entry point (reference elastic-state contract:
      ``peer/peer.go:236-276``).
    """
    from jax.sharding import NamedSharding

    total = int(np.sum([int(np.prod(l.shape)) for l in
                        jax.tree_util.tree_leaves(params)]))
    n = new_comm.size
    chunk = math.ceil(total / n)
    padded = chunk * n

    if new_comm._multiproc or snapshot is not None:
        # host-plane path: structure from opt_shard, geometry synthesized
        # for the new mesh, values from the (broadcast) snapshot
        fresh = jax.tree_util.tree_map(
            lambda a: (a if getattr(a, "ndim", 0) == 0
                       else jax.ShapeDtypeStruct((padded,), a.dtype)),
            opt_shard,
        )
        return zero1_restore(snapshot, fresh, params, peer, new_comm)

    sharded = NamedSharding(new_comm.mesh, P(new_comm.axis))
    replicated = new_comm.replicated_sharding()

    def leaf(a):
        if getattr(a, "ndim", 0) == 0:
            return jax.device_put(jnp.asarray(a), replicated)
        return jax.device_put(_repad(np.asarray(a), total, padded), sharded)

    return jax.tree_util.tree_map(leaf, opt_shard)


def _repad(full: np.ndarray, total: int, new_padded: int) -> np.ndarray:
    """Unpad a flat state vector to the true parameter count and re-pad
    for a new chunk geometry — shared by reshard and restore so their
    geometry (and its misuse diagnostic) cannot drift."""
    if full.shape[0] < total:
        # the state was built for MORE parameters than ``params`` holds
        # (e.g. a trainable-only subtree was passed): truncating would
        # silently corrupt the optimizer state
        raise ValueError(
            f"optimizer state vector has {full.shape[0]} elements but "
            f"params fuse to {total} — zero1 reshard/restore needs the "
            "SAME param tree the state was built from"
        )
    buf = np.zeros((new_padded,), full.dtype)
    buf[:total] = full[:total]
    return buf


def zero1_snapshot(opt_shard, peer=None):
    """End-of-epoch HOST snapshot of the sharded optimizer state.

    Each member contributes its addressable chunks over the host channel
    (state_bytes/n each — no HBM spike; only rank 0's HOST RAM holds the
    assembled state on the snapshot side.  :func:`zero1_restore` then
    broadcasts the blob, so each member transiently holds ~state_bytes
    in host RAM while re-chunking — host RAM, not HBM, so the 1/n HBM
    contract is untouched; a per-range scatter is the future
    optimization).  Rank 0 returns the blob, everyone else ``None``.
    The elastic contract is the coordinator's: **rank 0 must survive
    the resize** (it is the peer proposing it).

    Without a channel (single-process / simulated peers) every chunk is
    addressable locally and the blob is assembled in place.
    """
    import io

    chan = getattr(peer, "channel", None) if peer is not None else None
    leaves, _ = jax.tree_util.tree_flatten(opt_shard)
    parts = {}
    scalars = {}
    for i, leaf in enumerate(leaves):
        if getattr(leaf, "ndim", 0) == 0:
            scalars[f"s{i}"] = np.asarray(leaf)
            continue
        if chan is None and not leaf.is_fully_addressable:
            # mirror zero1_reshard's misuse guard: packing only the
            # local 1/n without a channel to gather the rest would
            # build a silently incomplete snapshot
            raise ValueError(
                "zero1_snapshot without a host channel needs fully "
                "addressable state (multi-controller meshes must pass "
                "the peer)"
            )
        for s in leaf.addressable_shards:
            start = s.index[0].start or 0
            parts[f"l{i}_o{start}"] = np.asarray(s.data)

    def pack(d):
        bio = io.BytesIO()
        np.savez(bio, **d)
        return bio.getvalue()

    if chan is None:
        merged = dict(parts)
        merged.update(scalars)
        return pack(merged)
    rank = peer.rank()
    name = f"kf.z1snap.v{peer.cluster_version}"
    gathered = chan.gather_bytes(pack(parts), peer.cluster.workers, name)
    if rank != 0:
        return None
    merged = {}
    for blob in gathered:
        with np.load(io.BytesIO(blob)) as z:
            for k in z.files:
                merged[k] = z[k]
    merged.update(scalars)  # replicated: rank 0's copy is everyone's
    return pack(merged)


def zero1_restore(snapshot, fresh_opt_shard, params, peer=None,
                  new_comm=None):
    """Rebuild the sharded optimizer state on a NEW mesh epoch from a
    :func:`zero1_snapshot` blob.

    ``fresh_opt_shard`` is ``init_opt(params)`` from the NEW epoch's
    :func:`zero1_train_step` — it supplies the state STRUCTURE and the
    new chunk geometry (joiners have no old state to supply either);
    its values are overwritten.  Rank 0 passes the blob; other members
    pass ``None`` and receive it over the host channel."""
    import io

    chan = getattr(peer, "channel", None) if peer is not None else None
    if chan is not None:
        if peer.rank() == 0 and snapshot is None:
            # fail HERE, before the broadcast: a bare assert inside
            # broadcast_bytes would kill rank 0 and leave every other
            # member stalling in recv until its timeout
            raise ValueError(
                "zero1_restore: rank 0 must supply the snapshot blob"
            )
        name = f"kf.z1rest.v{peer.cluster_version}"
        snapshot = chan.broadcast_bytes(snapshot, peer.cluster.workers, name)
    if snapshot is None:
        raise ValueError("zero1_restore: no snapshot (rank 0 must supply it)")
    total = int(np.sum([int(np.prod(l.shape)) for l in
                        jax.tree_util.tree_leaves(params)]))
    leaves, treedef = jax.tree_util.tree_flatten(fresh_opt_shard)
    with np.load(io.BytesIO(snapshot)) as z:
        by_leaf = {}
        for k in z.files:
            if k.startswith("s"):
                by_leaf[("s", int(k[1:]))] = z[k]
            else:
                li, off = k[1:].split("_o")
                by_leaf.setdefault(("l", int(li)), []).append(
                    (int(off), z[k]))

    sharded = None
    if new_comm is not None:
        from jax.sharding import NamedSharding

        sharded = NamedSharding(new_comm.mesh, P(new_comm.axis))
    out = []
    for i, leaf in enumerate(leaves):
        if getattr(leaf, "ndim", 0) == 0:
            val = by_leaf.get(("s", i))
            if val is None:
                out.append(leaf)
            elif new_comm is not None:
                out.append(jax.device_put(jnp.asarray(val),
                                          new_comm.replicated_sharding()))
            else:
                out.append(jnp.asarray(val))
            continue
        chunks = sorted(by_leaf.get(("l", i), []))
        if not chunks:
            raise ValueError(f"snapshot holds no chunks for state leaf {i}")
        # chunks must tile [0, covered) with no interior gap: a
        # count-based check misses a hole whenever the old padding is at
        # least one chunk wide, silently restoring zeros into momentum
        expected = 0
        for off, c in chunks:
            if off != expected:
                raise ValueError(
                    f"snapshot leaf {i}: chunk gap at offset {expected} "
                    f"(next chunk starts at {off}) — a contributing "
                    "member's chunks are missing"
                )
            expected = off + c.shape[0]
        full = np.concatenate([c for _, c in chunks])
        buf = _repad(full, total, int(leaf.shape[0]))  # NEW padded size
        out.append(jax.device_put(buf, sharded) if sharded is not None
                   else jnp.asarray(buf))
    return jax.tree_util.tree_unflatten(treedef, out)


def opt_state_bytes(opt_state) -> int:
    """Total bytes across an optimizer-state pytree (for the memory
    assertion in tests/benchmarks)."""
    return sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(opt_state)
        if hasattr(l, "shape") and hasattr(l, "dtype")
    )


def opt_state_bytes_per_device(opt_state) -> int:
    """Worst-case PER-DEVICE optimizer-state footprint: for each device,
    the bytes of every state shard it actually holds (a replicated leaf
    counts fully on every device; a 1/n-sharded leaf counts one chunk).
    This is the number the ZeRO memory claim is about — `opt_state_bytes`
    reports the global total, which is identical for replicated and
    sharded state and therefore cannot witness the sharding."""
    per: dict = {}
    for l in jax.tree_util.tree_leaves(opt_state):
        if isinstance(l, jax.Array):
            for s in l.addressable_shards:
                per[s.device] = per.get(s.device, 0) + int(s.data.nbytes)
        elif hasattr(l, "nbytes"):
            per[None] = per.get(None, 0) + int(l.nbytes)
    return max(per.values(), default=0)


def record_opt_state_gauge(opt_state) -> int:
    """Publish this rank's optimizer-state footprint as the
    ``kf_opt_state_bytes`` gauge (rendered by ``/metrics``, pushed to the
    aggregator, shown by kftop).  Returns the recorded bytes."""
    from kungfu_tpu.monitor.registry import REGISTRY

    nbytes = opt_state_bytes_per_device(opt_state)
    REGISTRY.gauge("kf_opt_state_bytes").set(nbytes)
    return nbytes


# ==========================================================================
# ZeRO-2 / ZeRO-3: bucketed reduce-scatter -> sharded update -> all-gather
# ==========================================================================
#
# Stage semantics (PAPERS.md 2004.13336 is the stage-1/2 blueprint; the
# DeepSpeed stage numbering is the vocabulary everyone searches for):
#
# ========  =======================  ==========================  ============
# stage     gradient collective      params between steps        opt state
# ========  =======================  ==========================  ============
# 1         all-reduce (2(n-1)/n*N)  replicated                  1/n sharded
# 2         reduce-scatter           replicated                  1/n sharded
#           ((n-1)/n*N)
# 3         reduce-scatter (via the  1/n SHARDED; all-gathered   1/n sharded
#           all-gather transpose)    bucket-wise JIT inside
#                                    the step
# ========  =======================  ==========================  ============
#
# plus the parameter all-gather every stage pays once per step ((n-1)/n*N;
# stage 3 pays it *inside* the step, stages 1/2 at the step boundary via
# the partitioner).  So stage 2 halves the gradient comm of the stage-1
# all-reduce path — the measured claim in ``bench.py --zero`` — and stage 3
# additionally drops the at-rest parameter replication to 1/n.
#
# The persistent sharded-state GEOMETRY is IDENTICAL across stages (and to
# :func:`zero1_train_step`): flat fused buffer, ceil(total/n) chunk per
# device, mesh-major contiguous.  That single invariant is what lets ONE
# elastic re-shard machinery (snapshot/restore, and the p2p re-carve
# below) serve every stage, including ZeRO-3's parameter shards.


class _ZeroGeometry:
    """Flat-buffer geometry + compiled helpers for one (params, mesh)."""

    def __init__(self, params, comm, inner, bucket_bytes: int):
        from kungfu_tpu.ops.schedules import bucket_widths

        mesh, axes = comm.mesh, comm.axis
        self.axes = axes
        self.axes_t = (axes,) if isinstance(axes, str) else tuple(axes)
        self.n = comm.size
        self.mesh = mesh
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        buf, spec = fuse(zeros)
        self.spec = spec
        self.total = int(buf.shape[-1])
        self.chunk = math.ceil(self.total / self.n)
        self.padded = self.chunk * self.n
        self.flat_dtype = spec.fused_dtype
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.scatter_axes = [ax for ax in self.axes_t if sizes[ax] > 1]
        self.widths = bucket_widths(
            self.chunk, self.n, jnp.dtype(self.flat_dtype).itemsize,
            bucket_bytes)
        state_shapes = jax.eval_shape(
            inner.init, jax.ShapeDtypeStruct((self.chunk,), self.flat_dtype)
        )
        self.state_specs = jax.tree_util.tree_map(
            lambda s: P(axes) if s.ndim else P(), state_shapes
        )

    def my_offset(self):
        off, seg = jnp.int32(0), self.padded
        for ax in self.scatter_axes:
            seg = seg // axis_size(ax)
            off = off + lax.axis_index(ax) * seg
        return off

    def flat_of(self, tree):
        b, _ = fuse(tree)
        pad = self.padded - self.total
        if pad:
            b = jnp.concatenate([b, jnp.zeros((pad,), b.dtype)])
        return b.astype(self.flat_dtype)


class ZeroStep:
    """A staged weight-update-sharded training step.

    Stages 1/2 keep the :func:`zero1_train_step` calling convention
    (``step(params, opt_shard, batch)``, params replicated in/out) —
    unpacking ``step, init_opt = zero_train_step(...)`` keeps working.
    Stage 3 stores parameters SHARDED between steps: call
    :meth:`init_params` once to carve the flat shard, then
    ``step(p_shard, opt_shard, batch)``; :meth:`gather_params`
    reassembles the full tree for eval/checkpoint/re-sync.

    kf-pulse: stages 1/2 carry a second jit program (``step_pulse``)
    that additionally returns the (local, reduced) gradient square-norm
    pair; :attr:`pulse` gates which program runs per step
    (``KF_PULSE_EVERY``) and publishes ``kf_gns`` /
    ``kf_grad_variance`` / ``kf_grad_norm{group="flat"}``.  Off steps
    and ``KF_PULSE_EVERY=0`` runs execute the bare program untouched.
    """

    def __init__(self, loss_fn, inner, comm, stage: int, average: bool,
                 donate: bool, bucket_bytes: int, schedule: str = "lax"):
        if stage not in (1, 2, 3):
            raise ValueError(f"ZeRO stage must be 1, 2 or 3, got {stage}")
        from kungfu_tpu.ops.schedules import FLAT_SCHEDULES

        if schedule not in FLAT_SCHEDULES:
            raise ValueError(
                f"unknown schedule {schedule!r}; one of {FLAT_SCHEDULES}")
        self.stage = stage
        self.comm = comm
        self._loss_fn = loss_fn
        self._inner = inner
        self._average = average
        self._donate = donate
        self._bucket_bytes = int(bucket_bytes)
        #: flat-collective schedule compiled into the bucket loops
        #: ("lax" | "pallas_ring"); the shard GEOMETRY is identical
        #: either way, so snapshots/re-shards are schedule-agnostic
        self._schedule = schedule
        self._cache = {}
        self._g3 = None  # stage-3 active geometry (set by init_params)
        from kungfu_tpu.monitor import pulse as pulselib
        #: kf-pulse gradient-signal monitor (None when KF_PULSE_EVERY=0).
        #: Stages 1/2 only: stage 3 never materializes a per-rank FULL
        #: flat gradient (the backward pass emits the bucketed
        #: reduce-scatter directly), so the small-batch side of the GNS
        #: pair does not exist there without a second gradient pass.
        self.pulse = (pulselib.PulseMonitor.from_env()
                      if stage in (1, 2) else None)

    # -- back-compat unpacking: step, init_opt = zero_train_step(...) -----
    def __iter__(self):
        return iter((self.step, self.init_opt))

    # -- dp_train_step contract: the returned object IS the step ----------
    def __call__(self, params, opt_shard, batch):
        return self.step(params, opt_shard, batch)

    # -- public API -------------------------------------------------------
    def step(self, params, opt_shard, batch):
        if self.stage == 3:
            built = self._require_g3()
            return built["step"](params, opt_shard, batch)
        built = self._get(params)
        mon = self.pulse
        if mon is not None and mon.should_sample():
            # kf-pulse step: the SECOND jit program returns the
            # already-reduced square-norm pair on top of the normal
            # outputs; off steps run the bare program untouched
            p, opt_shard, loss, gl, gg = built["step_pulse"](
                params, opt_shard, batch)
            self._publish_pulse(mon, float(gl), float(gg), batch)
            return p, opt_shard, loss
        return built["step"](params, opt_shard, batch)

    def _publish_pulse(self, mon, g_local_sq, g_global_sq, batch):
        n = int(self.comm.size)
        leaves = jax.tree_util.tree_leaves(batch)
        b_small = (int(leaves[0].shape[0]) // n) if (leaves and n) else 1
        mon.update(g_local_sq, g_global_sq, max(1, b_small), n,
                   group_norms={
                       "flat": math.sqrt(max(0.0, g_global_sq))})

    def init_opt(self, params):
        out = self._get(params)["init_opt"](params)
        record_opt_state_gauge(out)
        return out

    def init_params(self, params):
        """Stage 3: carve the replicated param tree into the flat
        mesh-sharded buffer the step trains on.  Stages 1/2: identity."""
        if self.stage != 3:
            return params
        built = self._get(params)
        self._g3 = built
        return built["init_params"](params)

    def gather_params(self, p):
        """Stage 3: all-gather the flat shard back into the full param
        tree (replicated — for eval/checkpoint/resync).  Stages 1/2:
        identity (params are already replicated)."""
        if self.stage != 3:
            return p
        built = self._require_g3()
        return built["gather_params"](p)

    def comm_bytes(self, params) -> dict:
        """Analytic per-rank wire bytes per step for THIS model on THIS
        mesh (ring convention; see :func:`zero_comm_bytes`)."""
        g = self._geometry_of(params)
        return zero_comm_bytes(g.total, g.n, self.stage,
                               jnp.dtype(g.flat_dtype).itemsize)

    # -- internals --------------------------------------------------------
    def _require_g3(self):
        if self._g3 is None:
            raise RuntimeError(
                "stage-3 step called before init_params (the parameter "
                "shard carve defines the step's geometry)")
        return self._g3

    def _geometry_of(self, params):
        return self._get(params)["geo"]

    def _get(self, params):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        key = (treedef,
               tuple((tuple(l.shape), str(l.dtype)) for l in leaves))
        if key not in self._cache:
            self._cache[key] = self._build(params)
        return self._cache[key]

    def _build(self, params):
        geo = _ZeroGeometry(params, self.comm, self._inner,
                            self._bucket_bytes)
        mesh, axes = geo.mesh, geo.axes
        inner, average, donate = self._inner, self._average, self._donate
        loss_fn = self._loss_fn
        n, chunk, total = geo.n, geo.chunk, geo.total
        state_specs = geo.state_specs
        from kungfu_tpu.ops.schedules import (all_gather_flat,
                                              reduce_scatter_flat)

        def init_body(p):
            shard = lax.dynamic_slice(
                geo.flat_of(p), (geo.my_offset(),), (chunk,))
            return inner.init(shard)

        init_opt = jax.jit(shard_map(
            init_body, mesh=mesh, in_specs=(P(),), out_specs=state_specs))

        rep = NamedSharding(mesh, P())

        def regather(p_flat):
            # the partitioner inserts the (bucketable) all-gather for the
            # replicated constraint — PINNED, same reasoning as zero1
            return jax.tree_util.tree_map(
                lambda a: jax.lax.with_sharding_constraint(a, rep),
                defuse(p_flat[:total], geo.spec),
            )

        if self.stage in (1, 2):
            from kungfu_tpu.ops.pallas._sharding import match_vma

            def make_body(with_pulse):
                def step_body(p, opt_shard, batch):
                    p_var = jax.tree_util.tree_map(
                        lambda a: match_vma(a, frozenset(geo.axes_t)), p)
                    loss, grads = jax.value_and_grad(loss_fn)(p_var, batch)
                    g = geo.flat_of(grads)
                    gl_sq = gg_sq = None
                    if with_pulse:
                        # kf-pulse small-batch side: this rank's flat
                        # gradient square norm.  The cross-peer MEAN
                        # lands below — stage 1 pmeans it directly;
                        # stage 2 folds it into ONE stacked psum with
                        # the shard term, so a pulse sample costs a
                        # single extra scalar collective either way
                        gl_sq = jnp.sum(
                            jnp.square(g.astype(jnp.float32)))
                    if self.stage == 1:
                        # the classic ZeRO-1 all-reduce path: every device
                        # sees the full reduced gradient, then updates only
                        # its own chunk — 2x the wire bytes of the stage-2
                        # reduce-scatter (the measured delta in bench --zero)
                        for ax in geo.scatter_axes:
                            g = lax.psum(g, ax)
                        if with_pulse:
                            for ax in geo.scatter_axes:
                                gl_sq = lax.pmean(gl_sq, ax)
                            # g is the full SUMMED gradient (replicated):
                            # |mean|^2 = |sum|^2 / n^2 — no collective
                            gg_sq = jnp.sum(
                                jnp.square(g.astype(jnp.float32))
                            ) / float(n * n)
                        g_shard = lax.dynamic_slice(
                            g, (geo.my_offset(),), (chunk,))
                    else:
                        g_shard = reduce_scatter_flat(
                            g, geo.scatter_axes, chunk, geo.widths,
                            schedule=self._schedule)
                        if with_pulse:
                            # the shards tile the summed flat buffer
                            # disjointly, so psum of the shard square
                            # norms IS |sum|^2; stacked with the local
                            # term both scalars ride one psum (psum/n
                            # is bitwise what pmean lowers to)
                            pair = jnp.stack([gl_sq, jnp.sum(
                                jnp.square(g_shard.astype(jnp.float32)))])
                            for ax in geo.scatter_axes:
                                pair = lax.psum(pair, ax)
                            gl_sq = pair[0] / float(n)
                            gg_sq = pair[1] / float(n * n)
                    if average:
                        g_shard = g_shard / n
                    p_shard = lax.dynamic_slice(
                        geo.flat_of(p), (geo.my_offset(),), (chunk,))
                    updates, opt_shard = inner.update(
                        g_shard, opt_shard, p_shard)
                    p_shard = optax.apply_updates(p_shard, updates)
                    loss = lax.pmean(loss, axes)
                    if with_pulse:
                        return p_shard, opt_shard, loss, gl_sq, gg_sq
                    return p_shard, opt_shard, loss
                return step_body

            inner_step = shard_map(
                make_body(False), mesh=mesh,
                in_specs=(P(), state_specs, P(axes)),
                out_specs=(P(axes), state_specs, P()),
            )
            inner_pulse = shard_map(
                make_body(True), mesh=mesh,
                in_specs=(P(), state_specs, P(axes)),
                out_specs=(P(axes), state_specs, P(), P(), P()),
            )

            def outer(p, opt_shard, batch):
                p_flat, opt_shard, loss = inner_step(p, opt_shard, batch)
                return regather(p_flat), opt_shard, loss

            def outer_pulse(p, opt_shard, batch):
                p_flat, opt_shard, loss, gl, gg = inner_pulse(
                    p, opt_shard, batch)
                return regather(p_flat), opt_shard, loss, gl, gg

            step = jax.jit(outer, donate_argnums=(0, 1) if donate else ())
            # compiled lazily on the first pulse step (never, for runs
            # shorter than KF_PULSE_EVERY)
            step_pulse = jax.jit(
                outer_pulse, donate_argnums=(0, 1) if donate else ())
            return {"geo": geo, "step": step, "step_pulse": step_pulse,
                    "init_opt": init_opt}

        # -- stage 3: params live sharded; gather is JIT inside the step --
        def init_params_body(p):
            return lax.dynamic_slice(
                geo.flat_of(p), (geo.my_offset(),), (chunk,))

        init_params = jax.jit(shard_map(
            init_params_body, mesh=mesh, in_specs=(P(),),
            out_specs=P(axes)))

        def step3_body(p_loc, opt_shard, batch):
            def loss_of(ps):
                # bucket-wise all-gather INSIDE the step: parameters are
                # only ever full in-flight; the transpose of each tiled
                # all-gather is the matching tiled psum-scatter, so the
                # backward pass emits the bucketed gradient
                # reduce-scatter with no extra collective written here.
                # prefetch=True double-buffers the bucket gathers (and,
                # via the fence's custom vjp, the transposed backward
                # reduce-scatters): the next bucket's wire time hides
                # under the current one's retirement without letting
                # XLA hold every gathered slab live at once — values
                # bitwise identical (tests/test_schedules.py pins it)
                full = all_gather_flat(ps, geo.scatter_axes, geo.widths,
                                       prefetch=True,
                                       schedule=self._schedule)
                return loss_fn(defuse(full[:total], geo.spec), batch)

            loss, g_shard = jax.value_and_grad(loss_of)(p_loc)
            if average:
                g_shard = g_shard / n
            updates, opt_shard = inner.update(g_shard, opt_shard, p_loc)
            p_loc = optax.apply_updates(p_loc, updates)
            loss = lax.pmean(loss, axes)
            return p_loc, opt_shard, loss

        step3 = jax.jit(
            shard_map(
                step3_body, mesh=mesh,
                in_specs=(P(axes), state_specs, P(axes)),
                out_specs=(P(axes), state_specs, P()),
            ),
            donate_argnums=(0, 1) if donate else (),
        )

        gather_params = jax.jit(regather)
        return {"geo": geo, "step": step3, "init_opt": init_opt,
                "init_params": init_params, "gather_params": gather_params}


def zero_train_step(loss_fn, inner: optax.GradientTransformation, comm,
                    stage: Optional[int] = None, average: bool = True,
                    donate: bool = False,
                    bucket_bytes: int = 4 << 20,
                    schedule: Optional[str] = None,
                    plan=None) -> ZeroStep:
    """Build a staged ZeRO data-parallel training step over ``comm``.

    ``stage``: 1 = all-reduce grads + sharded update (the classic ZeRO-1
    path, kept as the measured comm baseline), 2 = bucketed
    reduce-scatter grads (half the gradient wire bytes), 3 = stage 2
    plus parameters sharded 1/n between steps with bucket-wise
    just-in-time all-gather inside the step.  ``bucket_bytes`` sizes the
    reduce-scatter/all-gather buckets (the gradient-bucket fusion of
    ``ops/schedules.py`` folded to collective-sized pieces).

    Returns a :class:`ZeroStep`; for stages 1/2 ``step, init_opt =
    zero_train_step(...)`` unpacks like :func:`zero1_train_step`.  The
    sharded state geometry is identical across stages and to ZeRO-1, so
    :func:`zero_snapshot` / :func:`zero_restore` / :func:`zero_reshard` /
    :func:`zero_reshard_p2p` apply unchanged (stage 3's parameter shard
    is re-carved by the same machinery — it is just one more flat
    state vector).

    ``schedule`` selects the bucket collectives' implementation:
    ``"lax"`` (default — ``psum_scatter``/``all_gather`` primitives) or
    ``"pallas_ring"`` (the in-kernel-overlap ICI ring kernels of
    :mod:`kungfu_tpu.ops.pallas.collectives`; the stage-3 gather's
    custom vjp keeps the transposed gradient reduce-scatter).  The
    sharded state geometry is identical either way.

    ``plan`` (a :class:`~kungfu_tpu.parallel.train.ParallelPlan`)
    supplies ``stage`` from ``plan.zero_stage`` and maps
    ``plan.collective_schedule`` onto the bucket vocabulary — the
    unified-plan route every entrypoint shares.  Both ``stage`` and
    ``schedule`` default to None so an EXPLICIT argument is
    distinguishable from the default: one that disagrees with the plan
    raises instead of being silently replaced."""
    if plan is not None:
        if plan.tp != 1 or plan.pp != 1 or plan.sp != 1:
            raise ValueError(
                f"zero_train_step shards over ONE dp axis but the plan "
                f"carries tp={plan.tp} pp={plan.pp} sp={plan.sp}")
        if not plan.zero_stage:
            raise ValueError("plan.zero_stage is 0 — use dp_train_step")
        if stage is not None and stage != plan.zero_stage:
            raise ValueError(
                f"stage={stage} disagrees with plan.zero_stage="
                f"{plan.zero_stage} — set it in the plan")
        plan_sched = ("pallas_ring"
                      if plan.collective_schedule == "pallas_ring"
                      else "lax")
        if schedule is not None and schedule != plan_sched:
            raise ValueError(
                f"schedule={schedule!r} disagrees with "
                f"plan.collective_schedule="
                f"{plan.collective_schedule!r} — set it in the plan")
        stage = plan.zero_stage
        schedule = plan_sched
    return ZeroStep(loss_fn, inner, comm,
                    2 if stage is None else stage, average, donate,
                    bucket_bytes, "lax" if schedule is None else schedule)


def zero_comm_bytes(total_params: int, n: int, stage: int,
                    itemsize: int = 4) -> dict:
    """Analytic per-rank wire bytes per training step (ring convention,
    the busbw accounting ``bench.py`` uses): the honest denominator for
    the measured :func:`~kungfu_tpu.ops.schedules.traced_collective_bytes`
    rows.  Keys: ``grad_bytes`` (all-reduce at stage 1, reduce-scatter at
    stages 2/3), ``param_bytes`` (the per-step parameter all-gather —
    partitioner-inserted at stages 1/2, explicit in-step at stage 3) and
    their ``total_bytes``."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    padded = math.ceil(total_params / n) * n if n else total_params
    rs = (n - 1) / n * padded * itemsize
    grad = 2.0 * rs if stage == 1 else rs
    return {
        "grad_bytes": grad,
        "param_bytes": rs,
        "total_bytes": grad + rs,
        "padded_params": padded,
    }


# -- host-plane bucket pipelining (kf-overlap) -----------------------------
#
# The multi-process data path (CPU test clusters, between-mesh-epoch
# phases) runs the ZeRO bucket loops over the host engine, where
# communication is real wall time the Python loop used to serialize:
# issue bucket i, WAIT, do bucket i's optimizer math, issue bucket i+1 —
# wire and compute adding instead of overlapping.  The helpers below are
# the depth-k software pipeline over the engine's async handles: issue
# bucket i+k while bucket i's math runs.  Bucket order, tags, and
# per-bucket arithmetic are IDENTICAL to the serial loop (one geometry,
# PR 7's invariant), so serial and pipelined runs produce bitwise-equal
# results — only the wall clock moves (measured: bench.py --overlap).


def host_bucket_spans(chunk: int, widths) -> list:
    """``[(offset, width)]`` bucket tiling of one rank's chunk — shared
    by the serial and pipelined loops so their geometry cannot drift."""
    spans = []
    off = 0
    for w in widths:
        spans.append((off, int(w)))
        off += int(w)
    if off != chunk:
        raise ValueError(f"widths {list(widths)} do not tile chunk {chunk}")
    return spans


def host_bucket_pipeline(engine, flat, widths, compute, *, op: str = "sum",
                         pipelined: bool = True,
                         depth: Optional[int] = None,
                         name: str = "zp") -> list:
    """Bucketed host-plane reduce-scatter with a depth-k software
    pipeline: ``flat`` is this rank's full mesh-major ``[n*chunk]``
    buffer (the fused gradient), bucket b's collective operand is the
    ``[n, width_b]`` column slab — the exact device-plane
    :func:`~kungfu_tpu.ops.schedules.reduce_scatter_flat` geometry, so
    concatenating the per-bucket results reproduces this rank's
    contiguous chunk.  ``compute(i, reduced)`` runs each bucket's local
    math (optimizer update on the owned slice) and its results are
    returned in bucket order.

    ``pipelined=True`` issues bucket ``i+depth``'s reduce-scatter
    *before* running bucket ``i``'s compute, so wire time hides under
    math (and under other buckets' wire time — the engine's bounded
    window runs up to ``depth`` collectives concurrently).  The serial
    form is the reference loop: issue, wait, compute, repeat.  Tags are
    explicit and identical in both forms, so the two are wire-compatible
    and bitwise-equal in results."""
    n = len(engine.peers)
    if len(flat) % n:
        raise ValueError(f"flat buffer ({len(flat)}) must tile {n} ranks")
    chunk = len(flat) // n
    g2 = np.asarray(flat).reshape(n, chunk)
    spans = host_bucket_spans(chunk, widths)

    def slab(i):
        off, w = spans[i]
        return np.ascontiguousarray(g2[:, off:off + w]).reshape(-1)

    if not pipelined:
        return [compute(i, engine.reduce_scatter(
                    slab(i), op=op, name=f"{name}.b{i}"))
                for i in range(len(spans))]

    if depth is None:
        depth = engine.overlap_depth
    if depth < 1:
        # same guard as engine.set_overlap_depth: an empty prefill would
        # otherwise surface as a bare IndexError on the first popleft
        raise ValueError(f"pipeline depth must be >= 1, got {depth}")
    from collections import deque

    handles = deque(
        engine.reduce_scatter_async(slab(i), op=op, name=f"{name}.b{i}")
        for i in range(min(depth, len(spans))))
    outs = []
    for i in range(len(spans)):
        reduced = handles.popleft().wait()
        nxt = i + depth
        if nxt < len(spans):
            # issue BEFORE compute: bucket nxt's wire time runs under
            # bucket i's optimizer math — the pipeline's whole point
            handles.append(engine.reduce_scatter_async(
                slab(nxt), op=op, name=f"{name}.b{nxt}"))
        outs.append(compute(i, reduced))
    return outs


def host_bucket_all_gather(engine, shard, widths, *, pipelined: bool = True,
                           depth: Optional[int] = None,
                           name: str = "zg"):
    """Bucketed host-plane all-gather of this rank's ``[chunk]`` shard
    back to the mesh-major ``[n*chunk]`` full buffer — the ZeRO-3
    parameter path's host-plane analog of
    :func:`~kungfu_tpu.ops.schedules.all_gather_flat`.  Pipelined form
    keeps up to ``depth`` bucket gathers in flight; results are
    assembled in bucket order either way (bitwise-equal)."""
    n = len(engine.peers)
    chunk = len(shard)
    spans = host_bucket_spans(chunk, widths)
    shard = np.asarray(shard)

    def assemble(pieces):
        full = np.empty((n, chunk), shard.dtype)
        for (off, w), piece in zip(spans, pieces):
            full[:, off:off + w] = piece.reshape(n, w)
        return full.reshape(-1)

    if not pipelined:
        return assemble([
            engine.all_gather(shard[off:off + w], name=f"{name}.b{i}")
            for i, (off, w) in enumerate(spans)])

    if depth is None:
        depth = engine.overlap_depth
    if depth < 1:
        raise ValueError(f"pipeline depth must be >= 1, got {depth}")
    from collections import deque

    handles = deque(
        engine.all_gather_async(shard[spans[i][0]:spans[i][0] + spans[i][1]],
                                name=f"{name}.b{i}")
        for i in range(min(depth, len(spans))))
    pieces = []
    for i in range(len(spans)):
        got = handles.popleft().wait()
        nxt = i + depth
        if nxt < len(spans):
            off, w = spans[nxt]
            handles.append(engine.all_gather_async(
                shard[off:off + w], name=f"{name}.b{nxt}"))
        pieces.append(got)
    return assemble(pieces)


# -- generalized (stage-agnostic) elastic state movement -------------------
# The snapshot/restore/reshard trio below IS zero1's: every stage shares
# the flat chunk geometry, so the zero1_* machinery already moves any
# stage's state (including ZeRO-3 parameter shards).  The aliases make
# call sites say what they mean.
zero_snapshot = zero1_snapshot
zero_restore = zero1_restore
zero_reshard = zero1_reshard


def reshard_plan(total: int, old_n: int, new_n: int):
    """Pure segment-exchange plan for an old_n -> new_n re-carve of a
    flat ``total``-element state vector: ``[(old_rank, new_rank, start,
    length)]`` in global flat offsets, covering exactly ``[0, total)``
    (padding is zeros by construction on both sides and never moves).
    Every rank computes the identical plan — the whole point: the
    exchange needs no leader and no gather, each rank moves only the
    O(total/n) bytes it owns or will own."""
    if old_n < 1 or new_n < 1:
        raise ValueError(f"world sizes must be >= 1 ({old_n} -> {new_n})")
    oc = math.ceil(total / old_n)
    nc = math.ceil(total / new_n)
    segs = []
    for r in range(new_n):
        lo, hi = r * nc, min((r + 1) * nc, total)
        if lo >= hi:
            continue  # new rank holds pure padding
        for o in range(lo // oc, (hi - 1) // oc + 1):
            s = max(lo, o * oc)
            e = min(hi, (o + 1) * oc, total)
            if s < e:
                segs.append((o, r, s, e - s))
    return segs


def _vector_leaves(tree):
    """(index, leaf) of the sharded flat state vectors (ndim >= 1)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def _local_chunk(leaf, chunk: int):
    """(offset, np.ndarray) of THIS process's chunk of a sharded flat
    state vector.  Single-controller arrays are fully addressable — the
    caller slices per-rank chunks out of the returned full buffer
    instead (offset None signals that)."""
    if leaf.is_fully_addressable:
        return None, np.asarray(leaf)
    shards = leaf.addressable_shards
    if len(shards) != 1:
        raise NotImplementedError(
            "zero_reshard_p2p assumes one device per process (one chunk "
            f"per rank); this process holds {len(shards)} shards")
    s = shards[0]
    return int(s.index[0].start or 0), np.asarray(s.data)


def _place_sharded(new_comm, full_np=None, my_chunk=None):
    """Place a flat state vector on ``new_comm``'s mesh, sharded P(axes):
    from the full host buffer (single-controller) or from this process's
    chunk (multi-controller, one device per process)."""
    from jax.sharding import NamedSharding

    sharded = NamedSharding(new_comm.mesh, P(new_comm.axis))
    if not new_comm._multiproc:
        return jax.device_put(full_np, sharded)
    devs = [d for d in new_comm.mesh.devices.ravel()
            if d.process_index == jax.process_index()]
    if len(devs) != 1:
        raise NotImplementedError(
            "zero_reshard_p2p placement assumes one device per process")
    n = new_comm.size
    shape = (my_chunk.shape[0] * n,)
    return jax.make_array_from_single_device_arrays(
        shape, sharded, [jax.device_put(my_chunk, devs[0])])


def zero_reshard_p2p(opt_shard, params, new_comm, peer=None,
                     new_workers=None, old_n: Optional[int] = None,
                     tag: str = "0"):
    """Peer-to-peer elastic re-carve of sharded ZeRO state: every member
    of the OLD membership sends exactly the segments of its own chunk
    that the NEW geometry assigns elsewhere, every member of the NEW
    membership assembles its chunk from those segments — **no gather to
    a leader, no full-state blob anywhere** (contrast
    :func:`zero_snapshot` + :func:`zero_restore`, which funnel
    state_bytes through rank 0's host RAM).  Per-rank traffic is
    O(total/old_n + total/new_n).

    Call it at the step boundary BEFORE the resize is applied, on every
    old member (leavers serve their segments and return ``None``) and on
    every new member that was an old member.  Joiners that held no old
    chunk receive everything, including the replicated scalar leaves
    (served by old rank 0): pass their fresh ``init_opt(params)`` as
    ``opt_shard`` for structure.

    Single-controller worlds (every chunk addressable) re-carve by pure
    slicing — bit-identical to the channel path, which the tests pin.

    ``tag`` must be identical on every participant (use the agreed NEW
    cluster version); it keys the rendezvous names."""
    total = int(np.sum([int(np.prod(l.shape)) for l in
                        jax.tree_util.tree_leaves(params)]))
    new_n = new_comm.size
    new_chunk = math.ceil(total / new_n)
    new_padded = new_chunk * new_n

    leaves, treedef = _vector_leaves(opt_shard)
    vec_idx = [i for i, l in enumerate(leaves)
               if getattr(l, "ndim", 0) >= 1]

    chan = getattr(peer, "channel", None) if peer is not None else None
    if chan is None:
        # single-controller: every old chunk is addressable; replay the
        # exact segment plan in numpy (same data movement as the wire
        # path, minus the wire)
        if old_n is None:
            for i in vec_idx:
                old_n = len(leaves[i].sharding.device_set)
                break
            else:
                old_n = new_n
        plan = reshard_plan(total, old_n, new_n)
        out = []
        for i, leaf in enumerate(leaves):
            if i not in vec_idx:
                out.append(jax.device_put(jnp.asarray(leaf),
                                          new_comm.replicated_sharding()))
                continue
            full = np.asarray(leaf)
            if full.shape[0] < total:
                raise ValueError(
                    f"state vector has {full.shape[0]} elements but params "
                    f"fuse to {total} — same param tree required")
            buf = np.zeros((new_padded,), full.dtype)
            for (_, _, s, ln) in plan:
                buf[s:s + ln] = full[s:s + ln]
            out.append(_place_sharded(new_comm, full_np=buf))
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- host-channel exchange --------------------------------------------
    old_workers = peer.cluster.workers
    if new_workers is None:
        raise ValueError("zero_reshard_p2p over a channel needs the agreed "
                         "new worker list")
    if old_n is None:
        old_n = len(old_workers)
    my_old = old_workers.rank(peer.config.self_id)
    my_new = new_workers.rank(peer.config.self_id)
    plan = reshard_plan(total, old_n, new_n)
    old_chunk = math.ceil(total / old_n)

    def seg_name(i, s):
        return f"kf.zrs.{tag}.l{i}.o{s}"

    import io

    # planned-resize exchange still runs next to live peers: convert a
    # raw channel timeout (a death mid-exchange) into the typed
    # PeerFailureError the recovery contract promises, same as the
    # committed-boundary path in elastic/reshard.py
    from kungfu_tpu.elastic.reshard import _recv_or_fail

    # 1) serve: every segment my old chunk owns, destined elsewhere
    if my_old is not None:
        for i in vec_idx:
            off, mine = _local_chunk(leaves[i], old_chunk)
            if off is None:  # fully addressable leaf in a multiproc world
                off = my_old * old_chunk
                mine = mine[off:off + old_chunk]
            for (o, r, s, ln) in plan:
                if o != my_old:
                    continue
                dst = new_workers[r]
                if dst == peer.config.self_id:
                    continue
                chan.send(dst, seg_name(i, s),
                          np.ascontiguousarray(mine[s - off:s - off + ln]))
        if my_old == 0:
            # scalars for pure joiners (replicated leaves have no owner)
            scal = {f"s{i}": np.asarray(l) for i, l in enumerate(leaves)
                    if i not in vec_idx}
            blob = io.BytesIO()
            np.savez(blob, **scal)
            for w in new_workers:
                if old_workers.rank(w) is None:
                    chan.send(w, f"kf.zrs.{tag}.scalars", blob.getvalue())

    if my_new is None:
        return None  # leaver: served its segments, holds nothing now

    # 2) assemble my new chunk
    scalars = None
    if my_old is None:
        with np.load(io.BytesIO(_recv_or_fail(
                chan, old_workers[0], 0, "zero-reshard",
                f"kf.zrs.{tag}.scalars"))) as z:
            scalars = {k: z[k] for k in z.files}
    out = []
    for i, leaf in enumerate(leaves):
        if i not in vec_idx:
            val = (scalars[f"s{i}"] if scalars is not None
                   else np.asarray(leaf))
            out.append(jax.device_put(jnp.asarray(val),
                                      new_comm.replicated_sharding()))
            continue
        off = mine = None
        if my_old is not None:
            off, mine = _local_chunk(leaf, old_chunk)
            if off is None:
                off = my_old * old_chunk
                mine = mine[off:off + old_chunk]
        buf = np.zeros((new_chunk,), leaf.dtype)
        lo = my_new * new_chunk
        for (o, r, s, ln) in plan:
            if r != my_new:
                continue
            if o == my_old:
                buf[s - lo:s - lo + ln] = mine[s - off:s - off + ln]
            else:
                got = np.frombuffer(
                    _recv_or_fail(chan, old_workers[o], o, "zero-reshard",
                                  seg_name(i, s)),
                    dtype=buf.dtype)
                if got.shape[0] != ln:
                    raise ValueError(
                        f"reshard segment {seg_name(i, s)}: expected {ln} "
                        f"elements, got {got.shape[0]}")
                buf[s - lo:s - lo + ln] = got
        out.append(_place_sharded(new_comm, my_chunk=buf))
    return jax.tree_util.tree_unflatten(treedef, out)
